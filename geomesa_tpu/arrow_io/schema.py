"""SFT <-> Arrow schema mapping with typed geometry vectors.

Layout parity with the reference's geomesa-arrow-jts vectors
(vector/GeometryVector: PointVector = 2 fixed-width float8 children;
LineStringVector = list over point struct; PolygonVector adds a ring
nesting level [UNVERIFIED - empty reference mount]). The SFT spec string is
carried in schema metadata so readers reconstruct the feature type from
the stream alone (ref ArrowEncodedSft).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.geom.base import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

SFT_NAME_KEY = b"geomesa.sft.name"
SFT_SPEC_KEY = b"geomesa.sft.spec"
GEOM_TYPE_KEY = b"geomesa.geom.type"
#: stream-level "batches form ascending runs of this key" stamp — the
#: result plane's Z-sorted resident exports set it without re-sorting.
#: The value is either a stream COLUMN name — consumers can then k-way
#: merge streams by it (merge_delta_streams with that key) — or an
#: order TAG naming an ordering the stream does not materialize as a
#: column (``"z"``: the resident index's Z-curve order; same-tag
#: streams are sorted runs of the same global order but cannot be
#: value-merged without the key column)
SORT_KEY_META = b"geomesa.sort.key"

_SCALAR_TYPES = {
    "String": "string",
    "Integer": "int32",
    "Long": "int64",
    "Float": "float32",
    "Double": "float64",
    "Boolean": "bool_",
    "Date": None,  # timestamp("ms")
}


def _point_struct():
    import pyarrow as pa

    return pa.struct([("x", pa.float64()), ("y", pa.float64())])


def _geom_arrow_type(type_name: str):
    import pyarrow as pa

    pt = _point_struct()
    return {
        "Point": pt,
        "MultiPoint": pa.list_(pt),
        "LineString": pa.list_(pt),
        "MultiLineString": pa.list_(pa.list_(pt)),
        "Polygon": pa.list_(pa.list_(pt)),
        "MultiPolygon": pa.list_(pa.list_(pa.list_(pt))),
        "Geometry": pa.string(),  # mixed columns fall back to WKT
    }[type_name]


def arrow_schema_for(
    sft: SimpleFeatureType,
    dict_encode: "tuple[str, ...] | None" = None,
    with_visibility: bool = False,
):
    """Arrow schema with fid column, typed geometry vectors, SFT metadata.

    dict_encode: string attributes to dictionary-encode (default: all of
    them -- the reference dictionary-encodes strings for the wire).
    """
    import pyarrow as pa

    fields = [pa.field("__fid__", pa.string())]
    if with_visibility:
        from geomesa_tpu.security import VIS_COLUMN

        fields.append(pa.field(VIS_COLUMN, pa.string()))
    for attr in sft.attributes:
        if attr.is_geometry:
            f = pa.field(
                attr.name,
                _geom_arrow_type(attr.type_name),
                metadata={GEOM_TYPE_KEY: attr.type_name.encode()},
            )
        elif attr.type_name == "Date":
            f = pa.field(attr.name, pa.timestamp("ms"))
        else:
            t = getattr(pa, _SCALAR_TYPES.get(attr.type_name) or "string")()
            if attr.type_name == "String" and (
                dict_encode is None or attr.name in dict_encode
            ):
                t = pa.dictionary(pa.int32(), pa.string())
            f = pa.field(attr.name, t)
        fields.append(f)
    meta = {SFT_NAME_KEY: sft.type_name.encode(), SFT_SPEC_KEY: sft.spec.encode()}
    return pa.schema(fields, metadata=meta)


def sft_from_schema(schema) -> SimpleFeatureType:
    """Reconstruct the SFT from stream metadata (ArrowEncodedSft role)."""
    meta = schema.metadata or {}
    if SFT_SPEC_KEY not in meta:
        raise ValueError("schema carries no geomesa SFT metadata")
    return SimpleFeatureType.create(
        meta[SFT_NAME_KEY].decode(), meta[SFT_SPEC_KEY].decode()
    )


# -- geometry column encode/decode ------------------------------------------


def _pt(xy) -> dict:
    return {"x": float(xy[0]), "y": float(xy[1])}


def _line_pts(coords) -> list:
    return [_pt(c) for c in np.asarray(coords)]


def _poly_rings(p: Polygon) -> list:
    return [_line_pts(r) for r in p.rings()]


def _encode_geom_column(col: np.ndarray, type_name: str, arrow_type):
    import pyarrow as pa

    if type_name == "Point":
        if col.dtype != object:  # (n, 2) packed points
            x = pa.array(col[:, 0], pa.float64())
            y = pa.array(col[:, 1], pa.float64())
            return pa.StructArray.from_arrays([x, y], ["x", "y"])
        return pa.array([None if g is None else _pt((g.x, g.y)) for g in col],
                        type=arrow_type)
    enc = {
        "MultiPoint": lambda g: [_pt((p.x, p.y)) for p in g.points],
        "LineString": lambda g: _line_pts(g.coords),
        "MultiLineString": lambda g: [_line_pts(l.coords) for l in g.lines],
        "Polygon": _poly_rings,
        "MultiPolygon": lambda g: [_poly_rings(p) for p in g.polygons],
    }
    if type_name in enc:
        fn = enc[type_name]
        return pa.array(
            [None if g is None else fn(g) for g in col], type=arrow_type
        )
    from geomesa_tpu.geom.wkt import to_wkt

    return pa.array([None if g is None else to_wkt(g) for g in col])


def _decode_geom_column(arr, type_name: str) -> np.ndarray:
    if type_name == "Point":
        x = np.asarray(arr.field("x"))
        y = np.asarray(arr.field("y"))
        return np.stack([x, y], axis=1)

    def pts(v) -> np.ndarray:
        return np.array([(p["x"], p["y"]) for p in v], dtype=np.float64)

    dec = {
        "MultiPoint": lambda v: MultiPoint(
            tuple(Point(p["x"], p["y"]) for p in v)
        ),
        "LineString": lambda v: LineString(pts(v)),
        "MultiLineString": lambda v: MultiLineString(
            tuple(LineString(pts(l)) for l in v)
        ),
        "Polygon": lambda v: Polygon(pts(v[0]), tuple(pts(h) for h in v[1:])),
        "MultiPolygon": lambda v: MultiPolygon(
            tuple(
                Polygon(pts(rs[0]), tuple(pts(h) for h in rs[1:])) for rs in v
            )
        ),
    }
    if type_name in dec:
        fn = dec[type_name]
        vals = arr.to_pylist()
        return np.array(
            [None if v is None else fn(v) for v in vals], dtype=object
        )
    from geomesa_tpu.geom.wkt import parse_wkt

    return np.array(
        [None if w is None else parse_wkt(w) for w in arr.to_pylist()],
        dtype=object,
    )


# -- batch <-> RecordBatch ---------------------------------------------------


def _encode_fids(fids: np.ndarray):
    """Feature ids as an Arrow string array with NO per-feature Python
    on the common dtypes: integer fids cast in C++ (Arrow compute),
    numpy unicode wraps directly; only true object arrays pay the
    str() loop (matches the GeoJSON path's ``str(fid)`` rendering)."""
    import pyarrow as pa

    if fids.dtype.kind in "iu":
        import pyarrow.compute as pc

        return pc.cast(pa.array(fids), pa.string())
    if fids.dtype.kind == "U":
        return pa.array(fids, pa.string())
    return pa.array([str(f) for f in fids], pa.string())


def batch_to_arrow(batch: FeatureBatch, schema=None, string_encoder=None):
    """FeatureBatch -> pyarrow RecordBatch under the typed-vector schema.

    string_encoder: optional hook ``(attr_name, col, field) -> Array | None``
    for dictionary fields (the DeltaWriter supplies one that encodes against
    its monotonically growing dictionaries); None falls back to per-batch
    encoding."""
    import pyarrow as pa

    from geomesa_tpu.security import VIS_COLUMN

    sft = batch.sft
    if schema is None:
        schema = arrow_schema_for(
            sft, with_visibility=VIS_COLUMN in batch.columns
        )
    arrays = [_encode_fids(batch.fids)]
    if schema.get_field_index(VIS_COLUMN) >= 0:
        vis = batch.columns.get(VIS_COLUMN)
        arrays.append(
            pa.array(
                [""] * len(batch) if vis is None else [str(v) for v in vis],
                pa.string(),
            )
        )
    for attr in sft.attributes:
        col = batch.columns[attr.name]
        field = schema.field(attr.name)
        if attr.is_geometry:
            a = _encode_geom_column(col, attr.type_name, field.type)
        elif attr.type_name == "Date":
            a = pa.array(col, type=pa.timestamp("ms"))
        elif attr.type_name == "String":
            a = None
            if string_encoder is not None and pa.types.is_dictionary(field.type):
                a = string_encoder(attr.name, col, field)
            if a is None:
                a = pa.array(
                    [None if v is None else str(v) for v in col], pa.string()
                )
                if pa.types.is_dictionary(field.type):
                    a = a.dictionary_encode()
        else:
            a = pa.array(col, type=field.type)
        arrays.append(a)
    return pa.RecordBatch.from_arrays(arrays, schema=schema)


def arrow_to_batch(rb, sft: "SimpleFeatureType | None" = None) -> FeatureBatch:
    """RecordBatch/Table -> FeatureBatch; SFT from metadata if omitted."""
    sft = sft or sft_from_schema(rb.schema)
    cols: dict = {}
    for attr in sft.attributes:
        arr = rb.column(rb.schema.get_field_index(attr.name))
        if hasattr(arr, "combine_chunks"):
            arr = arr.combine_chunks()
        if attr.is_geometry:
            cols[attr.name] = _decode_geom_column(arr, attr.type_name)
        elif attr.type_name == "Date":
            cols[attr.name] = (
                arr.cast("timestamp[ms]")
                .to_numpy(zero_copy_only=False)
                .astype("datetime64[ms]")
                .astype(np.int64)
            )
        elif attr.type_name == "String":
            if hasattr(arr, "dictionary_decode"):
                arr = arr.dictionary_decode()
            cols[attr.name] = np.array(arr.to_pylist(), dtype=object)
        elif attr.column_dtype is not None:
            cols[attr.name] = arr.to_numpy(zero_copy_only=False).astype(
                attr.column_dtype
            )
        else:
            cols[attr.name] = np.array(arr.to_pylist(), dtype=object)
    idx = rb.schema.get_field_index("__fid__")
    fids = np.array(rb.column(idx).to_pylist()) if idx >= 0 else None
    batch = FeatureBatch.from_columns(sft, cols, fids)
    from geomesa_tpu.security import VIS_COLUMN

    vidx = rb.schema.get_field_index(VIS_COLUMN)
    if vidx >= 0:
        batch = batch.with_visibility(rb.column(vidx).to_pylist())
    return batch
