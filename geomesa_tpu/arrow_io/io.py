"""Arrow IPC streaming: writer, reader, k-way sorted merge.

Ref roles (geomesa-arrow .../io/ [UNVERIFIED - empty reference mount]):
- ``ArrowStreamWriter``/``write_feature_stream`` = DeltaWriter minus the
  server/client delta protocol -- batches stream out under one
  self-describing schema (SFT in metadata, dictionary-encoded strings).
- ``read_feature_stream`` = ArrowStreamReader: streams FeatureBatches.
- ``merge_sorted_streams`` = the reader's sorted-batch merge: given
  per-partition streams each sorted by a key attribute, yields globally
  sorted batches (heap merge on host; partitions were sorted on device by
  the index build's lax.sort).
"""

from __future__ import annotations

import heapq

import numpy as np

from geomesa_tpu.arrow_io.schema import (
    arrow_schema_for,
    arrow_to_batch,
    batch_to_arrow,
    sft_from_schema,
)
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType


class ArrowStreamWriter:
    """Streams FeatureBatches to a binary file/buffer as Arrow IPC."""

    def __init__(
        self,
        sink,
        sft: SimpleFeatureType,
        dict_encode: "tuple[str, ...] | None" = None,
        with_visibility: bool = False,
    ):
        import pyarrow as pa

        self.schema = arrow_schema_for(
            sft, dict_encode, with_visibility=with_visibility
        )
        self.sft = sft
        self._writer = pa.ipc.new_stream(sink, self.schema)
        self.batches = 0

    def write(self, batch: FeatureBatch) -> None:
        self._writer.write_batch(batch_to_arrow(batch, self.schema))
        self.batches += 1

    def close(self) -> None:
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def ensure_labels_representable(auto_detect: bool, want_vis: bool,
                                batch) -> None:
    """Never silently strip security labels: when visibility was
    AUTO-detected from an unlabeled first batch, the stream schema is
    label-free and a later labeled batch cannot be represented — fail
    loudly. (An EXPLICIT with_visibility=False is the caller opting out
    of labels; that strips without complaint.) The ONE implementation
    of the rule, shared by the buffered writers here and the result
    plane's streamed encoder (results/stream.py)."""
    from geomesa_tpu.security import VIS_COLUMN

    if auto_detect and not want_vis and VIS_COLUMN in batch.columns:
        raise ValueError(
            "batch carries visibility labels but the stream schema "
            "was auto-detected from an unlabeled first batch; pass "
            "with_visibility=True (or False to strip deliberately)"
        )


def _write_stream(writer_cls, sink, batches, sft=None, **kw) -> int:
    """Shared stream-writing protocol for the plain and delta writers:
    peek the first batch for the SFT / visibility auto-detect, stream the
    rest, return the batch count (0-batch streams need an explicit sft)."""
    from geomesa_tpu.security import VIS_COLUMN

    batches = iter(batches)
    first = next(batches, None)
    if first is None:
        if sft is None:
            raise ValueError("empty stream needs an explicit sft")
        with writer_cls(sink, sft, **kw):
            pass
        return 0
    auto_detect = "with_visibility" not in kw
    want_vis = kw.setdefault("with_visibility", VIS_COLUMN in first.columns)
    with writer_cls(sink, sft or first.sft, **kw) as w:
        w.write(first)
        for b in batches:
            ensure_labels_representable(auto_detect, want_vis, b)
            w.write(b)
        return w.batches


def write_feature_stream(sink, batches, sft=None, **kw) -> int:
    """Write an iterable of FeatureBatches as one IPC stream; returns the
    batch count."""
    return _write_stream(ArrowStreamWriter, sink, batches, sft, **kw)


def _reader_batches(reader, sft=None):
    """Decode an OPEN IPC reader into FeatureBatches, closing it on
    exhaustion and on abandonment (generator close runs the finally)."""
    try:
        stream_sft = sft or sft_from_schema(reader.schema)
        for rb in reader:
            yield arrow_to_batch(rb, stream_sft)
    finally:
        reader.close()


def read_feature_stream(source, sft: "SimpleFeatureType | None" = None):
    """Yield FeatureBatches from an IPC stream; the SFT comes from stream
    metadata unless overridden."""
    import pyarrow as pa

    yield from _reader_batches(pa.ipc.open_stream(source), sft)


def merge_sorted_streams(streams, key: str, batch_size: int = 8192):
    """K-way merge of per-partition FeatureBatch iterators, each already
    sorted ascending by scalar attribute ``key``; yields globally sorted
    batches of ~batch_size. Heap holds one (head value, stream) entry per
    live stream."""
    iters = [iter(s) for s in streams]
    cursors: list = [None] * len(iters)  # per stream: [batch, vals, pos]
    heap: list = []
    sft = None

    def load(sid: int) -> None:
        nonlocal sft
        b = next(iters[sid], None)
        while b is not None and len(b) == 0:
            b = next(iters[sid], None)
        if b is None:
            cursors[sid] = None
            return
        sft = sft or b.sft
        cursors[sid] = [b, b.column(key), 0]
        heapq.heappush(heap, (cursors[sid][1][0], sid))

    for sid in range(len(iters)):
        load(sid)

    rows: list = []  # (batch, row-index) picks in output order
    while heap:
        _, sid = heapq.heappop(heap)
        b, vals, pos = cursors[sid]
        rows.append((b, pos))
        pos += 1
        if pos < len(b):
            cursors[sid][2] = pos
            heapq.heappush(heap, (vals[pos], sid))
        else:
            load(sid)
        if len(rows) >= batch_size:
            yield _take_rows(sft, rows)
            rows = []
    if rows:
        yield _take_rows(sft, rows)


def _take_rows(sft, rows) -> FeatureBatch:
    """Gather (batch, row) picks into one FeatureBatch, grouped per source
    batch so the column gathers stay vectorized. Per-feature visibility
    labels (the reserved security column) travel with their rows."""
    from geomesa_tpu.security import VIS_COLUMN

    groups: dict = {}
    for j, (batch, i) in enumerate(rows):
        groups.setdefault(id(batch), (batch, []))[1].append((i, j))
    n = len(rows)
    pieces = []
    for batch, picks in groups.values():
        idx = np.array([i for i, _ in picks])
        dst = np.array([j for _, j in picks])
        pieces.append((batch.take(idx), dst))
    out_cols: dict = {}
    for a in sft.attributes:
        first = pieces[0][0].columns[a.name]
        buf = np.empty((n,) + first.shape[1:], dtype=first.dtype)
        for taken, dst in pieces:
            buf[dst] = taken.columns[a.name]
        out_cols[a.name] = buf
    fids = np.empty(n, dtype=object)
    for taken, dst in pieces:
        fids[dst] = taken.fids
    out = FeatureBatch.from_columns(sft, out_cols, fids)
    if any(VIS_COLUMN in taken.columns for taken, _ in pieces):
        vis = np.full(n, "", dtype=object)
        for taken, dst in pieces:
            v = taken.columns.get(VIS_COLUMN)
            if v is not None:
                vis[dst] = v
        out = out.with_visibility(list(vis))
    return out


class DeltaWriter:
    """Dictionary-delta streaming writer (ref geomesa-arrow io/DeltaWriter
    [UNVERIFIED - empty reference mount]).

    String dictionaries grow monotonically across batches and each IPC
    message carries only the NEW dictionary entries (Arrow delta
    dictionary messages, ``emit_dictionary_deltas``), so long exports and
    server-side streaming aggregation never retransmit or rebuild a
    dictionary. ``sort_key`` sorts EACH written batch independently; a
    stream is globally sorted (mergeable with ``merge_delta_streams``)
    only when the written batches form ascending runs -- use
    ``write_delta_stream``, which sorts each input batch BEFORE chunking,
    for that. Any Arrow IPC reader (including ``read_feature_stream``)
    consumes the output; deltas are applied transparently.
    """

    def __init__(
        self,
        sink,
        sft: SimpleFeatureType,
        dict_encode: "tuple[str, ...] | None" = None,
        sort_key: "str | None" = None,
        with_visibility: bool = False,
        presorted: "str | None" = None,
    ):
        import pyarrow as pa

        self.sft = sft
        self.sort_key = sort_key
        self.schema = arrow_schema_for(
            sft, dict_encode, with_visibility=with_visibility
        )
        if presorted is not None:
            # stamp "batches form ascending runs of this order" WITHOUT
            # re-sorting — the result plane's Z-sorted resident exports
            # ride the index order as-is (no host re-sort). Column-named
            # stamps are value-mergeable; order tags ("z") only declare
            # the run discipline (see SORT_KEY_META in schema.py)
            from geomesa_tpu.arrow_io.schema import SORT_KEY_META

            self.schema = self.schema.with_metadata(
                {**(self.schema.metadata or {}),
                 SORT_KEY_META: presorted.encode()}
            )
        self._dict_ids: dict = {}  # field -> {value: index}
        self._dict_values: dict = {}  # field -> [values in id order]
        for f in self.schema:
            if pa.types.is_dictionary(f.type):
                self._dict_ids[f.name] = {}
                self._dict_values[f.name] = []
        self._writer = pa.ipc.new_stream(
            sink,
            self.schema,
            options=pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True),
        )
        self.batches = 0

    def _encode_dict(self, name: str, col, field):
        """Vectorized: Arrow's native dictionary_encode builds the
        per-batch dictionary in C++; only that (small) dictionary is
        remapped to global ids in Python, then a numpy gather rewrites
        the indices -- no per-row Python loop on the export hot path."""
        import pyarrow as pa

        ids = self._dict_ids[name]
        values = self._dict_values[name]
        try:
            arr = pa.array(col, pa.string())
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            # mixed/non-str objects: slow path, same as plain encoding
            arr = pa.array(
                [None if v is None else str(v) for v in col], pa.string()
            )
        enc = arr.dictionary_encode()
        local = enc.dictionary.to_pylist()
        lut = np.empty(max(len(local), 1), np.int32)
        for j, v in enumerate(local):
            i = ids.get(v)
            if i is None:
                i = ids[v] = len(values)
                values.append(v)
            lut[j] = i
        valid = np.asarray(enc.indices.is_valid())
        li = np.asarray(enc.indices.fill_null(0)).astype(np.int32)
        gi = lut[li]
        return pa.DictionaryArray.from_arrays(
            pa.array(gi, pa.int32(), mask=~valid),
            pa.array(values, pa.string()),
        )

    def write(self, batch: FeatureBatch) -> None:
        if self.sort_key is not None:
            order = np.argsort(batch.column(self.sort_key), kind="stable")
            batch = batch.take(order)
        self._writer.write_batch(
            batch_to_arrow(batch, self.schema, string_encoder=self._encode_dict)
        )
        self.batches += 1

    def dictionary(self, name: str) -> list:
        """Current accumulated dictionary for a field (test/debug hook)."""
        return list(self._dict_values[name])

    def close(self) -> None:
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_delta_stream(
    sink,
    batches,
    sft: "SimpleFeatureType | None" = None,
    chunk_size: "int | None" = None,
    **kw,
) -> int:
    """Write FeatureBatches as one dictionary-delta IPC stream; returns
    the batch count. ``chunk_size`` re-chunks large batches so dictionary
    deltas actually stream instead of arriving in one message.

    ``sort_key`` (kwarg) sorts each INPUT batch before chunking, so the
    chunks of one batch form a sorted run; global stream order across
    multiple input batches is the caller's responsibility (each reference
    server sorts only its own delta stream -- the reader's k-way merge
    unifies them)."""
    sort_key = kw.pop("sort_key", None)

    def chunked():
        for b in batches:
            if sort_key is not None:
                b = b.take(np.argsort(b.column(sort_key), kind="stable"))
            if chunk_size is None or len(b) <= chunk_size:
                yield b
            else:
                for i in range(0, len(b), chunk_size):
                    yield b.take(np.arange(i, min(i + chunk_size, len(b))))

    return _write_stream(DeltaWriter, sink, chunked(), sft, **kw)


def _open_stream_readers(sources, sft=None):
    """Open each IPC source eagerly (schemas become available up front)
    and return ([batch iterators], any_source_has_visibility)."""
    import pyarrow as pa

    from geomesa_tpu.security import VIS_COLUMN

    readers = []
    try:
        for s in sources:
            readers.append(pa.ipc.open_stream(s))
    except BaseException:
        for r in readers:  # don't leak the ones already opened
            r.close()
        raise
    has_vis = any(VIS_COLUMN in r.schema.names for r in readers)
    return [_reader_batches(r, sft) for r in readers], has_vis


def merge_delta_streams(sources, key: str, batch_size: int = 8192):
    """K-way merge of sorted Arrow IPC streams (delta-dictionary or plain)
    into globally sorted FeatureBatches (ref ArrowStreamReader's sorted
    merge). Each source is a binary file-like/buffer of one IPC stream."""
    streams, _ = _open_stream_readers(sources)
    yield from merge_sorted_streams(streams, key, batch_size)


def write_merged_delta_stream(
    sink, sources, key: str, sft: "SimpleFeatureType | None" = None, **kw
) -> int:
    """Merge N sorted delta streams into ONE delta stream with unified
    dictionaries (the client-side reduce of the reference's server-side
    Arrow aggregation).

    Visibility is decided from the SOURCE STREAM SCHEMAS, not the first
    merged chunk: when any input stream carries labels, the output schema
    must too, even if the first chunk of merged rows happens to be
    entirely unlabeled."""
    streams, has_vis = _open_stream_readers(sources, sft)
    kw.setdefault("with_visibility", has_vis)
    return write_delta_stream(
        sink, merge_sorted_streams(streams, key), sft=sft, **kw
    )
