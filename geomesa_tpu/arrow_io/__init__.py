"""Arrow columnar layer (ref: geomesa-arrow -- ArrowSimpleFeatureVector,
vector/GeometryVector impls, io/DeltaWriter, io/ArrowStreamReader,
ArrowEncodedSft [UNVERIFIED - empty reference mount]).

Geometries are typed Arrow vectors, not WKT blobs: points are fixed-width
``struct<x: float64, y: float64>`` (the reference's PointVector twin child
vectors), lines are ``list<point>``, polygons ``list<list<point>>`` and so
on. String attributes dictionary-encode. The SFT rides in schema metadata
so a bare IPC stream is self-describing -- the reference's ArrowEncodedSft
role. Sorted per-partition streams merge with a k-way heap, the client-side
half of the reference's DeltaWriter/reader protocol.
"""

from geomesa_tpu.arrow_io.schema import (
    arrow_schema_for,
    batch_to_arrow,
    arrow_to_batch,
    sft_from_schema,
)
from geomesa_tpu.arrow_io.io import (
    ArrowStreamWriter,
    read_feature_stream,
    merge_sorted_streams,
    write_feature_stream,
)

__all__ = [
    "arrow_schema_for",
    "batch_to_arrow",
    "arrow_to_batch",
    "sft_from_schema",
    "ArrowStreamWriter",
    "read_feature_stream",
    "write_feature_stream",
    "merge_sorted_streams",
]
