"""Arrow columnar layer (ref: geomesa-arrow -- ArrowSimpleFeatureVector,
vector/GeometryVector impls, io/DeltaWriter, io/ArrowStreamReader,
ArrowEncodedSft [UNVERIFIED - empty reference mount]).

Geometries are typed Arrow vectors, not WKT blobs: points are fixed-width
``struct<x: float64, y: float64>`` (the reference's PointVector twin child
vectors), lines are ``list<point>``, polygons ``list<list<point>>`` and so
on. String attributes dictionary-encode; the DeltaWriter grows its
dictionaries monotonically and ships only the new entries per batch (Arrow
delta dictionary messages -- the reference's DeltaWriter protocol). The
SFT rides in schema metadata so a bare IPC stream is self-describing --
the reference's ArrowEncodedSft role. Sorted per-partition streams merge
with a k-way heap into one unified-dictionary stream, the client-side half
of the reference's DeltaWriter/reader protocol.
"""

from geomesa_tpu.arrow_io.schema import (
    SORT_KEY_META,
    arrow_schema_for,
    batch_to_arrow,
    arrow_to_batch,
    sft_from_schema,
)
from geomesa_tpu.arrow_io.io import (
    ArrowStreamWriter,
    DeltaWriter,
    merge_delta_streams,
    merge_sorted_streams,
    read_feature_stream,
    write_delta_stream,
    write_feature_stream,
    write_merged_delta_stream,
)

__all__ = [
    "SORT_KEY_META",
    "arrow_schema_for",
    "batch_to_arrow",
    "arrow_to_batch",
    "sft_from_schema",
    "ArrowStreamWriter",
    "DeltaWriter",
    "read_feature_stream",
    "write_feature_stream",
    "write_delta_stream",
    "merge_sorted_streams",
    "merge_delta_streams",
    "write_merged_delta_stream",
]
