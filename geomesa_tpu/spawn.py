"""Blessed worker-spawn helpers: the one place serving code creates
threads and pools.

Contextvars are per-thread, so every raw ``threading.Thread`` /
``ThreadPoolExecutor`` in the serving tier silently drops the request
contexts the observability and accounting layers live on — the
submitting request's tracing span (:mod:`geomesa_tpu.tracing`), its
ledger :class:`~geomesa_tpu.ledger.RequestCost` collector, its
degradation collector (:mod:`geomesa_tpu.resilience`) and the active
``compile_scope``. PR 17's warmup-misattribution bug was exactly this
class: a background compile finishing on an unblessed thread charged
whichever request happened to be in flight. The fix discipline, applied
by hand in ``store/prefetch.py`` and the scheduler since PR 6, is
capture-on-the-submitting-thread + attach-around-the-worker-body; this
module packages that discipline so it cannot be forgotten:

- :meth:`RequestContext.capture` snapshots the FULL context set on the
  calling thread; ``with ctx.attach():`` installs it around the worker
  body (each piece attaches with its own token, so nested attaches and
  worker-local overrides — e.g. warmup's ``_system`` collector —
  compose normally).
- :func:`spawn_thread` is the ``threading.Thread`` drop-in. By default
  it captures the spawner's context; ``context=False`` declares a
  SERVICE thread (scheduler workers, compactors, health pollers — loops
  that outlive any request and attach per-work-item contexts
  themselves, or need none).
- :class:`ContextPool` is the ``ThreadPoolExecutor`` drop-in whose
  ``submit``/``map`` capture at SUBMIT time — the pool outlives any one
  request, so capture-at-construction would pin the first request's
  context forever.

Lint rule GT010 enforces that every spawn site in the package goes
through here, and the runtime context checker
(:mod:`geomesa_tpu.analysis.ctxcheck`, armed by
``GEOMESA_TPU_CTXCHECK=1``) instruments exactly these wrappers: it
records which contexts were live at submit and reports worker tasks
whose device/compile/degradation accounting ran against an orphaned or
mismatched context. With the env unset the wrappers add one ``None``
check per task — no instrumentation, no overhead.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["RequestContext", "ContextPool", "spawn_thread"]


class RequestContext:
    """One captured set of per-request ambient contexts: tracing span,
    ledger cost collector, degradation collector, compile scope."""

    __slots__ = ("trace", "cost", "degraded", "scope")

    def __init__(self, trace=None, cost=None, degraded=None, scope=None):
        self.trace = trace
        self.cost = cost
        self.degraded = degraded
        self.scope = scope

    @staticmethod
    def capture() -> "RequestContext":
        """Snapshot the calling thread's full context set (each piece
        may be None — attaching a None is a no-op for that piece)."""
        from geomesa_tpu import ledger, resilience, tracing

        return RequestContext(
            trace=tracing.capture(),
            cost=ledger.capture_cost(),
            degraded=resilience.capture_degraded(),
            scope=ledger.capture_scope(),
        )

    def any(self) -> bool:
        return (
            self.trace is not None
            or self.cost is not None
            or self.degraded is not None
            or self.scope is not None
        )

    @contextmanager
    def attach(self):
        """Install the captured set around a worker's work item."""
        from geomesa_tpu import ledger, resilience, tracing

        with tracing.attach(self.trace), \
                ledger.attach_cost(self.cost), \
                resilience.attach_degraded(self.degraded), \
                ledger.attach_scope(self.scope):
            yield


def _blessed(target, ctx: "RequestContext | None", kind: str, label: str):
    """Wrap ``target`` so the captured context attaches around the call
    and the runtime context checker (when armed) brackets the task."""
    from geomesa_tpu.analysis import ctxcheck

    if not ctxcheck.enabled():
        if ctx is None:
            return target

        def run_plain(*args, **kwargs):
            with ctx.attach():
                return target(*args, **kwargs)

        return run_plain

    def run_checked(*args, **kwargs):
        # the checker snapshots the worker's ambient state OUTSIDE the
        # attach, so a task that installs context and fails to reset it
        # (poisoning the next task on this pool thread) is a finding
        with ctxcheck.CHECKER.task(kind, label, ctx):
            if ctx is None:
                return target(*args, **kwargs)
            with ctx.attach():
                return target(*args, **kwargs)

    return run_checked


def spawn_thread(
    target,
    *,
    name: str,
    args=(),
    kwargs=None,
    daemon: bool = True,
    context: bool = True,
) -> threading.Thread:
    """The blessed ``threading.Thread`` factory (returned UNSTARTED —
    a drop-in for construct-then-start sites). ``context=True`` captures
    the spawner's full request-context set now and attaches it around
    ``target``; ``context=False`` declares a service thread (a loop
    that outlives requests and attaches per-item contexts itself).
    Every thread gets a name: the ctxcheck/lockcheck reports and the
    stuck-thread dumps are unreadable without one."""
    ctx = RequestContext.capture() if context else None
    return threading.Thread(  # lint: disable=GT010(this IS the blessed spawn factory)
        target=_blessed(
            target, ctx, "thread" if context else "service", name
        ),
        args=tuple(args),
        kwargs=dict(kwargs) if kwargs else {},
        name=name,
        daemon=daemon,
    )


class ContextPool:
    """The blessed ``ThreadPoolExecutor`` drop-in: ``submit``/``map``
    capture the submitting thread's context set per call and attach it
    around the worker-side run. ``context=False`` builds a plain pool
    for work that must NOT inherit the caller's contexts (warmup legs
    install their own ``_system`` collector — inheriting a live
    request's collector is precisely the PR 17 misattribution bug).
    Supports the executor context-manager protocol; ``shutdown`` passes
    through."""

    __slots__ = ("_ex", "_context", "_label")

    def __init__(
        self,
        max_workers: int,
        thread_name_prefix: str = "",
        context: bool = True,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self._ex = ThreadPoolExecutor(  # lint: disable=GT010(this IS the blessed pool factory)
            max_workers=max_workers,
            thread_name_prefix=thread_name_prefix or "geomesa-pool",
        )
        self._context = context
        self._label = thread_name_prefix or "geomesa-pool"

    def submit(self, fn, /, *args, **kwargs):
        ctx = RequestContext.capture() if self._context else None
        return self._ex.submit(
            _blessed(fn, ctx, "pool", self._label), *args, **kwargs
        )

    def map(self, fn, *iterables):
        """Context-carrying ``Executor.map`` (capture once — map's
        items all belong to the calling thread's current request)."""
        ctx = RequestContext.capture() if self._context else None
        return self._ex.map(_blessed(fn, ctx, "pool", self._label), *iterables)

    def shutdown(self, wait: bool = True, cancel_futures: bool = False):
        self._ex.shutdown(wait=wait, cancel_futures=cancel_futures)

    def __enter__(self) -> "ContextPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True)
