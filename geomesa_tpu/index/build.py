"""Index build: key compute -> global sort -> partition manifest.

The rebuild's analog of bulk ingest + table splits (ref: geomesa-accumulo
bulk ingest MapReduce sort + AccumuloIndexAdapter table splits, SURVEY.md
section 2.6 "Z-order bulk sort"). Host path uses numpy lexsort; the device
path (:func:`build_index_device`) encodes z keys on the mesh and globally
sorts rows with the all_to_all splitter exchange, row ids riding the
exchange as payload -- the MapReduce-bulk-sort-on-ICI analog, producing
the same BuiltIndex the host path does.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.index.api import BuiltIndex, PartitionMeta

DEFAULT_PARTITION_SIZE = 1 << 20  # ~1M rows per partition

# key spaces build_index_device can marshal encode inputs for — the ONE
# dispatch list (keyspaces with a device encode still need an entry in the
# per-kind input marshaling below); callers gate mesh routing on this
DEVICE_BUILD_KINDS = ("z3", "z2", "xz3", "xz2")

# time bins (weeks/months/... since epoch) can be negative; bias them into
# non-negative uint32 lane values so the lexicographic uint32 device sort
# matches the host's signed-int sort. Full int32 bias: a smaller bias would
# wrap far-past bins around to huge lane values and silently mis-sort.
_BIN_BIAS = 1 << 31

# per-curve device-encode jit wrappers (sfc dataclasses are frozen and
# hashable); see the cache note at the use site
_ENCODE_JITS: dict = {}


def build_index(
    keyspace,
    batch: FeatureBatch,
    partition_size: int = DEFAULT_PARTITION_SIZE,
    mesh=None,
) -> BuiltIndex:
    if mesh is not None:
        return build_index_device(keyspace, batch, mesh, partition_size)
    keys = keyspace.index_keys(batch)
    cols = [keys[c] for c in keyspace.key_columns]
    order = _sort_order(cols)
    sorted_batch = batch.take(order)
    sorted_keys = {k: v[order] for k, v in keys.items()}
    partitions = make_partitions(keyspace, sorted_batch, sorted_keys, partition_size)
    return BuiltIndex(keyspace, sorted_batch, sorted_keys, partitions)


def build_index_device(
    keyspace,
    batch: FeatureBatch,
    mesh,
    partition_size: int = DEFAULT_PARTITION_SIZE,
    axis: str = "shard",
) -> BuiltIndex:
    """Mesh-path index build for the spatial key spaces (z3/z2/xz3/xz2).

    The keys are encoded on device (hi/lo uint32 lanes; point schemas get
    Morton z keys, non-point schemas the XZ extent codes of their geometry
    envelopes), and rows are globally sorted across the mesh by
    ([bin,] key_hi, key_lo, row_id) via the all_to_all splitter exchange
    -- the trailing row-id lane makes the device sort stable over
    duplicate keys, so ties order exactly like the host's stable lexsort
    and the resulting permutation materializes the same sorted batch +
    partition manifest bit for bit. Overflow in the exchange raises (a
    build must never silently lose rows).
    """
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.jaxconf import enable_compilation_cache, require_x64
    from geomesa_tpu.parallel.dist import distributed_sort

    enable_compilation_cache()  # the exchange/encode compiles are heavy

    # host-parity encode needs float64 quantization; without it the jnp
    # coords silently downcast to float32 and the device keys disagree
    # with the host planner's ranges
    require_x64()

    kind = keyspace.name
    sfc = getattr(keyspace, "sfc", None)
    if sfc is None or not hasattr(sfc, "index_jax_hi_lo"):
        raise ValueError(
            f"device build requires a key space with a hi/lo device encode; "
            f"{kind!r} has none (use the host build)"
        )
    if kind not in DEVICE_BUILD_KINDS:
        # the encode dispatch below is positional per kind; a custom key
        # space with a device encode still needs a dispatch entry here
        raise ValueError(
            f"device build has no input dispatch for key space {kind!r} "
            "(supported: z3/z2/xz3/xz2)"
        )
    n = len(batch)
    if n == 0:
        return build_index(keyspace, batch, partition_size)

    n_shards = mesh.shape[axis]
    binned = kind in ("z3", "xz3")
    # one shared kind-dispatch for encode-input marshaling (same table the
    # resident cache stages with, so build and staging cannot drift)
    from geomesa_tpu.index.keyplanes import encode_inputs

    coords, b = encode_inputs(
        batch, kind, sfc, keyspace.geom_field,
        getattr(keyspace, "dtg_field", None),
    )
    if binned and (
        int(b.min()) < -_BIN_BIAS or int(b.max()) >= _BIN_BIAS - 1
    ):
        raise ValueError(
            f"time bins [{b.min()}, {b.max()}] exceed the "
            "device-sortable int32 range"
        )

    # pad to a POWER-OF-TWO row bucket (then to a shard multiple): the
    # encode + exchange jits retrace per input shape, and a ~30-60s
    # remote compile per distinct flush size would dominate every flush.
    # Bucketing bounds the shape set; the valid mask hides the padding.
    cap = 1 << max(n - 1, 0).bit_length()
    cap += (-cap) % n_shards
    pad = cap - n
    if pad:
        coords = [np.concatenate([c, np.zeros(pad)]) for c in coords]
        if binned:
            b = np.concatenate([b, np.zeros(pad, dtype=b.dtype)])
    valid = np.arange(n + pad) < n
    rid = np.arange(n + pad, dtype=np.uint32)

    enc = _ENCODE_JITS.get(sfc)
    if enc is None:
        # cached wrapper: a fresh jax.jit per build would re-compile the
        # encode every flush (the jit cache lives on the wrapper)
        enc = jax.jit(sfc.index_jax_hi_lo)
        _ENCODE_JITS[sfc] = enc
    hi, lo = enc(*map(jnp.asarray, coords))

    lanes = (hi, lo, jnp.asarray(rid))
    if binned:
        lanes = (jnp.asarray((b + _BIN_BIAS).astype(np.uint32)),) + lanes
    sorted_lanes, _, v = distributed_sort(
        mesh, lanes, axis=axis, valid=jnp.asarray(valid), on_overflow="raise"
    )
    v = np.asarray(v)
    kr = sorted_lanes[-1]
    kh, kl = np.asarray(sorted_lanes[-3]), np.asarray(sorted_lanes[-2])
    order = np.asarray(kr)[v].astype(np.int64)
    if order.shape[0] != n:  # pragma: no cover - overflow already raises
        raise RuntimeError(
            f"device build lost rows: {order.shape[0]} of {n} survived"
        )
    sorted_batch = batch.take(order)
    key64 = (kh.astype(np.uint64) << np.uint64(32)) | kl.astype(np.uint64)
    key_name = "z" if kind in ("z3", "z2") else "xz"
    sorted_keys = {
        key_name: key64[v]
        if kind in ("z3", "z2")
        else key64[v].astype(np.int64)  # xz codes are int64 on the host
    }
    if binned:
        kb = np.asarray(sorted_lanes[0])
        sorted_keys["bin"] = (kb[v].astype(np.int64) - _BIN_BIAS).astype(
            np.int32
        )
    partitions = make_partitions(
        keyspace, sorted_batch, sorted_keys, partition_size
    )
    return BuiltIndex(keyspace, sorted_batch, sorted_keys, partitions)


def _sort_order(cols: list) -> np.ndarray:
    from geomesa_tpu import native

    if native.enabled():
        # byte-wise LSD radix argsort (native/sort.cpp): linear instead
        # of comparison sort, ~5x lexsort on the z3 (bin, hi, lo) lanes
        order = native.radix_argsort(cols)
        if order is not None:
            return order
    if len(cols) == 1:
        return np.argsort(cols[0], kind="stable")
    # np.lexsort: last key is primary -> reverse
    return np.lexsort(tuple(reversed(cols)))


def make_partitions(
    keyspace,
    sorted_batch: FeatureBatch,
    sorted_keys: dict,
    partition_size: int,
) -> "list[PartitionMeta]":
    n = len(sorted_batch)
    sft = sorted_batch.sft
    geom = sft.geom_field
    dtg = sft.dtg_field
    key_cols = [sorted_keys[c] for c in keyspace.key_columns]
    starts = np.arange(0, max(n, 1), partition_size)
    starts = starts[starts < max(n, 1)]
    # per-partition reductions via reduceat: one pass per statistic over
    # the whole column instead of materializing an (n, 4) bbox array (a
    # full extra copy of the coordinate data) and slicing it per partition
    bb_mins = bb_maxs = None
    if geom is not None and n:
        col = sorted_batch.columns[geom]
        if col.dtype != object:
            x = np.ascontiguousarray(col[:, 0])
            y = np.ascontiguousarray(col[:, 1])
            bb_mins = (
                np.minimum.reduceat(x, starts), np.minimum.reduceat(y, starts)
            )
            bb_maxs = (
                np.maximum.reduceat(x, starts), np.maximum.reduceat(y, starts)
            )
        else:
            bb = sorted_batch.bboxes(geom)
            bb_mins = (
                np.minimum.reduceat(bb[:, 0], starts),
                np.minimum.reduceat(bb[:, 1], starts),
            )
            bb_maxs = (
                np.maximum.reduceat(bb[:, 2], starts),
                np.maximum.reduceat(bb[:, 3], starts),
            )
    t_mins = t_maxs = None
    if dtg is not None and n:
        d_all = sorted_batch.column(dtg)
        t_mins = np.minimum.reduceat(d_all, starts)
        t_maxs = np.maximum.reduceat(d_all, starts)
    partitions = []
    for pid, start in enumerate(starts.tolist() if n else [0]):
        stop = min(start + partition_size, n)
        if stop <= start:
            break
        key_lo = tuple(_item(c[start]) for c in key_cols)
        key_hi = tuple(_item(c[stop - 1]) for c in key_cols)
        bbox = None
        if bb_mins is not None:
            bbox = (
                float(bb_mins[0][pid]), float(bb_mins[1][pid]),
                float(bb_maxs[0][pid]), float(bb_maxs[1][pid]),
            )
        time_range = None
        if t_mins is not None:
            time_range = (int(t_mins[pid]), int(t_maxs[pid]))
        partitions.append(
            PartitionMeta(pid, start, stop, key_lo, key_hi, stop - start, bbox, time_range)
        )
    return partitions


def _item(v):
    """numpy scalar -> python scalar for tuple comparisons; uint64 z values
    stay exact via int()."""
    if isinstance(v, np.generic):
        return v.item()
    return v
