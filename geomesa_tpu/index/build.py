"""Index build: key compute -> global sort -> partition manifest.

The rebuild's analog of bulk ingest + table splits (ref: geomesa-accumulo
bulk ingest MapReduce sort + AccumuloIndexAdapter table splits, SURVEY.md
section 2.6 "Z-order bulk sort"). Host path uses numpy lexsort; the device
path (jax.lax.sort over z keys, ICI radix exchange across a mesh) lives in
geomesa_tpu.parallel and is exercised by the bench/dryrun.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.index.api import BuiltIndex, PartitionMeta

DEFAULT_PARTITION_SIZE = 1 << 20  # ~1M rows per partition


def build_index(
    keyspace,
    batch: FeatureBatch,
    partition_size: int = DEFAULT_PARTITION_SIZE,
) -> BuiltIndex:
    keys = keyspace.index_keys(batch)
    cols = [keys[c] for c in keyspace.key_columns]
    order = _sort_order(cols)
    sorted_batch = batch.take(order)
    sorted_keys = {k: v[order] for k, v in keys.items()}
    partitions = make_partitions(keyspace, sorted_batch, sorted_keys, partition_size)
    return BuiltIndex(keyspace, sorted_batch, sorted_keys, partitions)


def _sort_order(cols: list) -> np.ndarray:
    if len(cols) == 1:
        return np.argsort(cols[0], kind="stable")
    # np.lexsort: last key is primary -> reverse
    return np.lexsort(tuple(reversed(cols)))


def make_partitions(
    keyspace,
    sorted_batch: FeatureBatch,
    sorted_keys: dict,
    partition_size: int,
) -> "list[PartitionMeta]":
    n = len(sorted_batch)
    sft = sorted_batch.sft
    geom = sft.geom_field
    dtg = sft.dtg_field
    key_cols = [sorted_keys[c] for c in keyspace.key_columns]
    partitions = []
    for pid, start in enumerate(range(0, max(n, 1), partition_size)):
        stop = min(start + partition_size, n)
        if stop <= start:
            break
        key_lo = tuple(_item(c[start]) for c in key_cols)
        key_hi = tuple(_item(c[stop - 1]) for c in key_cols)
        bbox = None
        if geom is not None:
            bb = sorted_batch.bboxes(geom)[start:stop]
            bbox = (
                float(bb[:, 0].min()),
                float(bb[:, 1].min()),
                float(bb[:, 2].max()),
                float(bb[:, 3].max()),
            )
        time_range = None
        if dtg is not None:
            d = sorted_batch.column(dtg)[start:stop]
            time_range = (int(d.min()), int(d.max()))
        partitions.append(
            PartitionMeta(pid, start, stop, key_lo, key_hi, stop - start, bbox, time_range)
        )
    return partitions


def _item(v):
    """numpy scalar -> python scalar for tuple comparisons; uint64 z values
    stay exact via int()."""
    if isinstance(v, np.generic):
        return v.item()
    return v
