"""Concrete index key spaces: Z3, Z2, XZ3, XZ2, attribute, id.

(ref: geomesa-index-api .../index/index/z3/Z3IndexKeySpace.scala and
siblings [UNVERIFIED - empty reference mount]). Key layouts follow the
reference's row-key structure minus the shard byte (sharding is a partition/
mesh concern in the rebuild -- SURVEY.md section 2.6):

- z3:  (bin: int32, z: uint64)    bin = BinnedTime period index
- z2:  (z: uint64,)
- xz3: (bin: int32, xz: int64)
- xz2: (xz: int64,)
- attr: (value,) host-comparable
- id:  (fid,)
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.curves import (
    TimePeriod,
    XZ2SFC,
    XZ3SFC,
    Z2SFC,
    Z3SFC,
)
from geomesa_tpu.curves.binnedtime import (
    bins_for_interval,
    max_offset,
    offset_to_millis,
    to_binned_time,
)
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.curves.zranges import DEFAULT_MAX_RANGES
from geomesa_tpu.filter.extract import FilterBounds, NEG_INF, POS_INF
from geomesa_tpu.index.api import KeyRange


def _envelopes(geoms: FilterBounds):
    return [v[0] for v in geoms.values]


@dataclass(frozen=True)
class Z3KeySpace:
    """Point geometries + time: (epoch bin, z3)."""

    geom_field: str
    dtg_field: str
    period: TimePeriod = TimePeriod.WEEK
    name: str = "z3"

    @property
    def key_columns(self) -> tuple:
        return ("bin", "z")

    @property
    def sfc(self) -> Z3SFC:
        return Z3SFC(self.period)

    def index_keys(self, batch: FeatureBatch) -> dict:
        x, y = batch.point_coords(self.geom_field)
        ms = batch.column(self.dtg_field)
        b, off = to_binned_time(ms, self.period)
        z = self.sfc.index(x, y, off)
        return {"bin": b.astype(np.int32), "z": z}

    def supports(self, geoms: FilterBounds, intervals: FilterBounds) -> bool:
        return not intervals.unbounded

    def cost(self, geoms: FilterBounds, intervals: FilterBounds) -> float:
        if intervals.unbounded:
            return float("inf")
        return 1.0 if not geoms.unbounded else 10.0

    def scan_ranges(
        self,
        geoms: FilterBounds,
        intervals: FilterBounds,
        max_ranges: int = DEFAULT_MAX_RANGES,
        data_interval=None,
    ):
        if intervals.unbounded:
            if data_interval is None:
                return None
            t_lo, t_hi = data_interval
        else:
            if intervals.empty or geoms.empty:
                return []
            t_lo = min(v[0] for v in intervals.values)
            t_hi = max(v[1] for v in intervals.values)
            if data_interval is not None:
                t_lo = max(t_lo, data_interval[0])
                t_hi = min(t_hi, data_interval[1])
            if t_lo > t_hi:
                return []
        envs = _envelopes(geoms) if not geoms.unbounded else [None]
        sfc = self.sfc
        mx = max_offset(self.period)
        spans = bins_for_interval(int(t_lo), int(t_hi), self.period)
        if len(spans) > max_ranges:
            # bin count alone exceeds the range budget: one coarse
            # lexicographic range over the whole (bin, z) span
            return [
                KeyRange((spans[0][0], 0), (spans[-1][0], (1 << 63) - 1), False)
            ]
        ranges: list[KeyRange] = []
        # middle whole-period bins share one decomposition (ref
        # Z3IndexKeySpace "whole period" optimization); per-bin budget keeps
        # the total under max_ranges (the geomesa.scan.ranges.target analog)
        whole_cache = None
        per_bin_budget = max(1, max_ranges // len(spans))
        for b, off_lo, off_hi in spans:
            whole = off_lo == 0 and off_hi == mx
            if whole and whole_cache is not None:
                zrs = whole_cache
            else:
                zrs = []
                for env in envs:
                    if env is None:
                        xmin, ymin, xmax, ymax = -180.0, -90.0, 180.0, 90.0
                    else:
                        xmin, ymin, xmax, ymax = env.xmin, env.ymin, env.xmax, env.ymax
                    zrs.extend(
                        sfc.ranges(
                            xmin, ymin, xmax, ymax,
                            float(off_lo), float(off_hi),
                            max_ranges=per_bin_budget,
                        )
                    )
                zrs.sort(key=lambda r: r.lower)
                if whole:
                    whole_cache = zrs
            for r in zrs:
                ranges.append(KeyRange((b, r.lower), (b, r.upper), r.contained))
        return ranges


@dataclass(frozen=True)
class Z2KeySpace:
    """Point geometries, no time: (z2,)."""

    geom_field: str
    name: str = "z2"

    @property
    def key_columns(self) -> tuple:
        return ("z",)

    @property
    def sfc(self) -> Z2SFC:
        return Z2SFC()

    def index_keys(self, batch: FeatureBatch) -> dict:
        x, y = batch.point_coords(self.geom_field)
        return {"z": self.sfc.index(x, y)}

    def supports(self, geoms: FilterBounds, intervals: FilterBounds) -> bool:
        return not geoms.unbounded

    def cost(self, geoms: FilterBounds, intervals: FilterBounds) -> float:
        return 2.0 if not geoms.unbounded else float("inf")

    def scan_ranges(
        self, geoms, intervals, max_ranges: int = DEFAULT_MAX_RANGES, data_interval=None
    ):
        if geoms.unbounded:
            return None
        if geoms.empty:
            return []
        ranges: list[KeyRange] = []
        budget = max(16, max_ranges // max(1, len(geoms.values)))
        for env, _ in geoms.values:
            for r in self.sfc.ranges(
                env.xmin, env.ymin, env.xmax, env.ymax, max_ranges=budget
            ):
                ranges.append(KeyRange((r.lower,), (r.upper,), r.contained))
        ranges.sort(key=lambda r: r.lo)
        return ranges


@dataclass(frozen=True)
class XZ2KeySpace:
    """Non-point geometries: (xz2,)."""

    geom_field: str
    g: int = 12
    name: str = "xz2"

    @property
    def key_columns(self) -> tuple:
        return ("xz",)

    @property
    def sfc(self) -> XZ2SFC:
        return XZ2SFC(self.g)

    def index_keys(self, batch: FeatureBatch) -> dict:
        bb = batch.bboxes(self.geom_field)
        return {
            "xz": self.sfc.index(bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3])
        }

    def supports(self, geoms, intervals) -> bool:
        return not geoms.unbounded

    def cost(self, geoms, intervals) -> float:
        return 3.0 if not geoms.unbounded else float("inf")

    def scan_ranges(self, geoms, intervals, max_ranges: int = DEFAULT_MAX_RANGES, data_interval=None):
        if geoms.unbounded:
            return None
        if geoms.empty:
            return []
        envs = _envelopes(geoms)
        rs = self.sfc.ranges(
            np.array([e.xmin for e in envs]),
            np.array([e.ymin for e in envs]),
            np.array([e.xmax for e in envs]),
            np.array([e.ymax for e in envs]),
            max_ranges=max_ranges,
        )
        return [KeyRange((r.lower,), (r.upper,), False) for r in rs]


@dataclass(frozen=True)
class XZ3KeySpace:
    """Non-point geometries + time: (bin, xz3)."""

    geom_field: str
    dtg_field: str
    period: TimePeriod = TimePeriod.WEEK
    g: int = 12
    name: str = "xz3"

    @property
    def key_columns(self) -> tuple:
        return ("bin", "xz")

    @property
    def sfc(self) -> XZ3SFC:
        return XZ3SFC(self.period, self.g)

    def index_keys(self, batch: FeatureBatch) -> dict:
        bb = batch.bboxes(self.geom_field)
        ms = batch.column(self.dtg_field)
        b, off = to_binned_time(ms, self.period)
        # instantaneous features: tmin == tmax == offset
        xz = self.sfc.index(bb[:, 0], bb[:, 1], off, bb[:, 2], bb[:, 3], off)
        return {"bin": b.astype(np.int32), "xz": xz}

    def supports(self, geoms, intervals) -> bool:
        return not intervals.unbounded

    def cost(self, geoms, intervals) -> float:
        if intervals.unbounded:
            return float("inf")
        return 1.5 if not geoms.unbounded else 10.0

    def scan_ranges(self, geoms, intervals, max_ranges: int = DEFAULT_MAX_RANGES, data_interval=None):
        if intervals.unbounded:
            if data_interval is None:
                return None
            t_lo, t_hi = data_interval
        else:
            if intervals.empty or geoms.empty:
                return []
            t_lo = min(v[0] for v in intervals.values)
            t_hi = max(v[1] for v in intervals.values)
        envs = _envelopes(geoms) if not geoms.unbounded else None
        spans = bins_for_interval(int(t_lo), int(t_hi), self.period)
        mx = max_offset(self.period)
        ranges: list[KeyRange] = []
        per_bin = max(16, max_ranges // max(1, len(spans)))
        for b, off_lo, off_hi in spans:
            if envs is None:
                xs = [(-180.0, -90.0, 180.0, 90.0)]
            else:
                xs = [(e.xmin, e.ymin, e.xmax, e.ymax) for e in envs]
            rs = self.sfc.ranges(
                np.array([e[0] for e in xs]),
                np.array([e[1] for e in xs]),
                np.full(len(xs), float(off_lo)),
                np.array([e[2] for e in xs]),
                np.array([e[3] for e in xs]),
                np.full(len(xs), float(off_hi)),
                max_ranges=per_bin,
            )
            for r in rs:
                ranges.append(KeyRange((b, r.lower), (b, r.upper), False))
        return ranges


@dataclass(frozen=True)
class AttributeKeySpace:
    """Secondary index on one attribute, sorted by value.
    (ref: geomesa-index-api .../index/attribute/AttributeIndexKeySpace)"""

    attr: str
    name: str = "attr"

    @property
    def key_columns(self) -> tuple:
        return ("value",)

    def index_keys(self, batch: FeatureBatch) -> dict:
        return {"value": batch.column(self.attr)}

    def supports(self, geoms, intervals) -> bool:
        # planner routes attribute predicates explicitly (see planner)
        return False

    def cost(self, geoms, intervals) -> float:
        return float("inf")

    def scan_ranges(self, geoms, intervals, max_ranges: int = DEFAULT_MAX_RANGES, data_interval=None):
        return None

    def ranges_for_values(self, bounds: FilterBounds):
        """Value bounds (from extract_intervals-style extraction or equality
        sets) -> ranges."""
        if bounds.unbounded:
            return None
        return [KeyRange((lo,), (hi,), False) for lo, hi in bounds.values]


@dataclass(frozen=True)
class IdKeySpace:
    """Primary key index on feature id."""

    name: str = "id"

    @property
    def key_columns(self) -> tuple:
        return ("fid",)

    def index_keys(self, batch: FeatureBatch) -> dict:
        return {"fid": batch.fids}

    def supports(self, geoms, intervals) -> bool:
        return False

    def cost(self, geoms, intervals) -> float:
        return float("inf")

    def scan_ranges(self, geoms, intervals, max_ranges: int = DEFAULT_MAX_RANGES, data_interval=None):
        return None


def keyspace_for(sft: SimpleFeatureType, name: str):
    """Index name -> key space, wired from SFT fields + user data.
    (ref: GeoMesaFeatureIndexFactory default index selection)"""
    geom = sft.geom_field
    dtg = sft.dtg_field
    period = TimePeriod.parse(sft.z3_interval)
    point = geom is not None and sft.descriptor(geom).is_point
    if name == "z3":
        if not (point and dtg):
            raise ValueError("z3 requires a Point default geometry and a Date field")
        return Z3KeySpace(geom, dtg, period)
    if name == "z2":
        if not point:
            raise ValueError("z2 requires a Point default geometry")
        return Z2KeySpace(geom)
    if name == "xz3":
        if not (geom and dtg):
            raise ValueError("xz3 requires a geometry and a Date field")
        return XZ3KeySpace(geom, dtg, period, sft.xz_precision)
    if name == "xz2":
        if geom is None:
            raise ValueError("xz2 requires a geometry")
        return XZ2KeySpace(geom, sft.xz_precision)
    if name == "id":
        return IdKeySpace()
    if name.startswith("attr:"):
        attr = name.split(":", 1)[1]
        if attr not in sft.attribute_names:
            raise ValueError(
                f"attribute index {name!r}: schema has no attribute {attr!r}"
            )
        return AttributeKeySpace(attr)
    raise ValueError(f"unknown index {name!r}")


def default_indices(sft: SimpleFeatureType) -> list[str]:
    """Default enabled indices for a schema (ref: GeoMesaFeatureIndexFactory
    defaults: z3+z2+id for points with time, xz3+xz2+id for non-points,
    plus attr:<name> for attributes flagged index=true)."""
    explicit = sft.user_data.get("geomesa.indices")
    if explicit:
        return [s.strip() for s in explicit.split(",") if s.strip()]
    out = []
    geom = sft.geom_field
    dtg = sft.dtg_field
    if geom is not None:
        point = sft.descriptor(geom).is_point
        if point:
            if dtg:
                out.append("z3")
            out.append("z2")
        else:
            if dtg:
                out.append("xz3")
            out.append("xz2")
    out.append("id")
    for a in sft.attributes:
        if a.indexed:
            out.append(f"attr:{a.name}")
    return out
