"""Index API: key spaces, ranges, partitions.

(ref: geomesa-index-api .../index/api/GeoMesaFeatureIndex.scala +
IndexKeySpace.scala [UNVERIFIED - empty reference mount])
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Protocol, Sequence

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.filter.extract import FilterBounds


@dataclass(frozen=True)
class KeyRange:
    """Inclusive lexicographic range over sort-key tuples."""

    lo: tuple
    hi: tuple
    contained: bool = False  # True: every key in range satisfies the primary


class IndexKeySpace(Protocol):
    """Maps features -> sort keys and query bounds -> key ranges."""

    name: str
    key_columns: tuple  # ordered names of the sort-key columns

    def index_keys(self, batch: FeatureBatch) -> dict:
        """Compute {key_column: np.ndarray} for a batch."""
        ...

    def scan_ranges(
        self,
        geoms: FilterBounds,
        intervals: FilterBounds,
        max_ranges: int,
        data_interval: "tuple[int, int] | None" = None,
    ) -> "list[KeyRange] | None":
        """Bounds -> ranges; None = cannot prune (full scan)."""
        ...

    def supports(self, geoms: FilterBounds, intervals: FilterBounds) -> bool:
        """Can this index usefully serve these bounds?"""
        ...

    def cost(self, geoms: FilterBounds, intervals: FilterBounds) -> float:
        """Heuristic cost for StrategyDecider (lower = better).
        (ref: geomesa-index-api .../planning/StrategyDecider heuristics)"""
        ...


@dataclass
class PartitionMeta:
    """Manifest entry for one sorted partition (the tablet-split analog,
    rolled together with geomesa-fs partition metadata + stats)."""

    pid: int
    start: int  # row offset in the sorted index
    stop: int
    key_lo: tuple
    key_hi: tuple
    count: int
    bbox: "tuple[float, float, float, float] | None" = None
    time_range: "tuple[int, int] | None" = None
    leaf: "str | None" = None  # fs partition-scheme directory leaf
    #: content integrity record for the partition FILE (fs stores only):
    #: {"algo": "crc32"|"crc32c", "value": int, "length": bytes} --
    #: written at flush, verified on read per the store.verify knob
    checksum: "dict | None" = None
    #: partition format v2 chunk statistics (store/chunkstats.ChunkSet;
    #: fs stores only): per-chunk row counts, key min/max, bbox, time
    #: range, coarse density cells and sketch partials -- the
    #: aggregation-pushdown and sub-partition scan-pruning index.
    #: None = legacy v1 partition (no chunk stats recorded)
    chunks: "object | None" = None
    #: the file generation that OWNS this partition (fs stores only;
    #: stamped at manifest load and flush-publish). Reads resolve the
    #: partition file through this, not the type's CURRENT generation,
    #: so a scan iterating a pre-flush snapshot keeps reading its own
    #: generation's files (and fails loudly once they are GC'd) instead
    #: of silently mixing generations. None = legacy un-scoped files.
    gen: "str | None" = None

    def overlaps(self, r: KeyRange) -> bool:
        return not (r.hi < self.key_lo or r.lo > self.key_hi)


@dataclass
class ShardMeta:
    """Manifest entry for one mesh shard of a resident index (the
    multi-chip twin of :class:`PartitionMeta`): which contiguous
    globally-sorted key range the shard serves, and how much of the
    dataset lives on it. Built by ``ShardedDeviceIndex`` at every
    (re)stage and surfaced through ``/stats/mesh``."""

    shard: int
    rows: int  # real rows resident on the shard (padding excluded)
    #: inclusive sort-key range the shard serves; None when the schema
    #: has no spatial key (positional sharding) or the shard is empty
    key_lo: "tuple | None" = None
    key_hi: "tuple | None" = None

    def to_json(self) -> dict:
        return {
            "shard": self.shard,
            "rows": self.rows,
            "key_lo": list(self.key_lo) if self.key_lo else None,
            "key_hi": list(self.key_hi) if self.key_hi else None,
        }


@dataclass
class BuiltIndex:
    """A fully built (sorted + partitioned) index over a feature set."""

    keyspace: "IndexKeySpace"
    batch: FeatureBatch  # sorted by key columns
    keys: dict  # {key_column: sorted np.ndarray}
    partitions: "list[PartitionMeta]"

    @property
    def n(self) -> int:
        return len(self.batch)

    def prune(self, ranges: "list[KeyRange] | None") -> "list[PartitionMeta]":
        """Partitions whose key span overlaps any range (all if None)."""
        if ranges is None:
            return list(self.partitions)
        out = []
        for p in self.partitions:
            # ranges sorted by lo; binary-search the first candidate
            for r in ranges:
                if p.overlaps(r):
                    out.append(p)
                    break
        return out
