"""Schema -> index-key encode plumbing shared by the resident cache and
the device index build.

One kind-dispatch table for the four spatial key spaces (z3/z2 Morton for
point geometries, xz3/xz2 extent curves for non-point) so the staging path
(device_cache) and the mesh build path (index/build) cannot drift on
encode-input marshaling. (ref: the Z3/Z2/XZ3/XZ2 IndexKeySpace family,
SURVEY section 2.1 [UNVERIFIED - empty reference mount]).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.sft import SimpleFeatureType


def schema_kind(sft: SimpleFeatureType):
    """(kind, sfc) the schema's key planes use: z3/z2 for point geometries
    (with/without a date field), xz3/xz2 extent curves for non-point ones,
    (None, None) when the SFT has no geometry at all.

    The curves honor the SAME user-data hints the durable key spaces do
    (``geomesa.z3.interval``, ``geomesa.xz.precision`` — ref
    SimpleFeatureTypes index hints): resident key planes packed with a
    different period than the on-disk index would silently diverge from
    the planner's per-bin decomposition."""
    from geomesa_tpu.curves.binnedtime import TimePeriod
    from geomesa_tpu.curves.xz2 import XZ2SFC
    from geomesa_tpu.curves.xz3 import XZ3SFC
    from geomesa_tpu.curves.z2 import Z2SFC
    from geomesa_tpu.curves.z3 import Z3SFC

    geom = sft.geom_field
    if geom is None:
        return None, None
    dtg = sft.dtg_field
    if not sft.descriptor(geom).is_point:
        # extent curve over the per-row geometry envelopes (ref XZ2/XZ3
        # index key spaces are the non-point peers of Z2/Z3)
        if dtg is not None:
            return "xz3", XZ3SFC(
                TimePeriod.parse(sft.z3_interval), sft.xz_precision
            )
        return "xz2", XZ2SFC(sft.xz_precision)
    if dtg is not None:
        return "z3", Z3SFC(TimePeriod.parse(sft.z3_interval))
    return "z2", Z2SFC()


def encode_inputs(batch, kind: str, sfc, geom_field: str, dtg_field=None):
    """(coords, bins) host-side encode inputs for a batch: float64 coord
    arrays in the sfc's positional encode order (``sfc.index(*coords)`` ==
    ``sfc.index_jax_hi_lo(*coords)`` input contract), plus the int32
    period-bin plane (or None for unbinned kinds). Time offsets ride
    inside coords; geometry envelope extraction and time binning stay on
    host (cheap vectorized passes; geometry parsing is host-side anyway).
    """
    from geomesa_tpu.curves.binnedtime import to_binned_time

    bins = None
    if kind in ("z3", "z2"):
        x, y = batch.point_coords(geom_field)
        coords = [np.asarray(x, np.float64), np.asarray(y, np.float64)]
        if kind == "z3":
            bins, off = to_binned_time(batch.column(dtg_field), sfc.period)
            coords.append(np.asarray(off, np.float64))
    else:
        bb = batch.bboxes(geom_field)
        if kind == "xz3":
            bins, off = to_binned_time(batch.column(dtg_field), sfc.period)
            offf = np.asarray(off, np.float64)
            coords = [bb[:, 0], bb[:, 1], offf, bb[:, 2], bb[:, 3], offf]
        else:
            coords = [bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3]]
    return coords, bins
