"""Index core (maps reference L4: geomesa-index-api).

Key spaces map feature batches to sort-key columns and query bounds to scan
ranges (ref: geomesa-index-api .../index/index/{z3,z2,xz3,xz2,attribute,id}/
*IndexKeySpace.scala [UNVERIFIED - empty reference mount]). The TPU-native
index structure is: batch -> key columns -> global sort -> fixed-size
partitions with a manifest (key bounds + stats per partition) -- the
columnar analog of the reference's sorted KV tables with tablet splits.
"""

from geomesa_tpu.index.api import (
    BuiltIndex,
    IndexKeySpace,
    KeyRange,
    PartitionMeta,
)
from geomesa_tpu.index.keyspaces import (
    AttributeKeySpace,
    IdKeySpace,
    XZ2KeySpace,
    XZ3KeySpace,
    Z2KeySpace,
    Z3KeySpace,
    keyspace_for,
)
from geomesa_tpu.index.build import build_index, build_index_device

__all__ = [
    "IndexKeySpace",
    "KeyRange",
    "PartitionMeta",
    "BuiltIndex",
    "Z3KeySpace",
    "Z2KeySpace",
    "XZ2KeySpace",
    "XZ3KeySpace",
    "AttributeKeySpace",
    "IdKeySpace",
    "keyspace_for",
    "build_index",
    "build_index_device",
]
