"""Project-specific static analysis + runtime concurrency checking.

PRs 1-4 built a concurrent serving stack whose correctness rests on
disciplines that no compiler enforces in Python: locks never held across
blocking I/O or device launches, fsync-before-publish in the flush path,
monotonic clocks for durations, tracer context carried explicitly across
worker pools, bounded metric label cardinality. Upstream GeoMesa leans
on scalac/Error Prone-style compile-time checking for exactly this class
of invariant; a Python rebuild loses that layer entirely, so this
package encodes the rules the repo itself established and runs them on
every tier-1 pass:

- :mod:`geomesa_tpu.analysis.lint` -- an AST-based project linter with
  repo-specific rules GT001-GT008 (see ``geomesa-tpu lint`` and the
  README rule table). Each rule has a ``# lint: disable=GTnnn(reason)``
  escape hatch; a reason is mandatory.
- :mod:`geomesa_tpu.analysis.lockcheck` -- a runtime lock-order checker
  (the thread-sanitizer analog): every lock built through
  ``locking.checked_lock()`` records its acquisition graph, ABBA
  lock-order cycles and lock-held-across-blocking-call events are
  reported, and the whole test suite runs under it via the conftest
  fixture (env ``GEOMESA_TPU_LOCKCHECK``). Off by default in
  production with near-zero overhead.
"""
