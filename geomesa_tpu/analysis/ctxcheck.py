"""Runtime context-propagation checker: lockcheck's twin for the
request-context set that must cross every pool boundary.

Contextvars are per-thread, so work handed to a worker only keeps its
request's tracing span, ledger :class:`~geomesa_tpu.ledger.RequestCost`
collector, degradation collector and ``compile_scope`` if the submit
site explicitly captured-and-attached them — the discipline
:mod:`geomesa_tpu.spawn` packages and lint rule GT010 enforces
statically. This module checks the part statics cannot see: that the
contexts actually attached at RUN time match what was live at SUBMIT
time, and that the accounting events a worker task emits (device
seconds, compile seconds, degradation stamps) land in a collector the
task was legitimately handed. The PR 17 warmup bug — a background
compile charging whichever request happened to be in flight — becomes a
session-end report line instead of a p99 mystery.

Armed by ``GEOMESA_TPU_CTXCHECK=1`` (read dynamically, like lockcheck);
unset, the blessed spawn wrappers take their plain path and the ledger /
resilience observer seams stay ``None`` — zero production overhead.
Armed, :func:`install` hooks the seams and every blessed task is
bracketed by :meth:`CtxCheck.task`:

- **ctx-leak** — a task returned with a DIFFERENT ambient context set
  than the worker thread had before it ran: the task attached a
  context and failed to reset it, poisoning every later task on that
  pool thread.
- **mismatched-cost** — a context-routed ledger charge hit a
  :class:`RequestCost` that was never attached on the charging thread
  (someone smuggled a collector across a pool without the blessed
  capture/attach, i.e. exactly how misattribution starts).
- **orphan-degraded** — a degradation stamp landed in a collector the
  stamping thread was never handed.
- **orphan-compile** — a backend compile finished on a non-main thread
  with no ``compile_scope`` and no request collector: nobody will ever
  be charged for those compile seconds (the PR 17 class).

The conftest arms the env for the whole tier-1 suite, installs the
seams, prints :meth:`CtxCheck.report` at session end and fails the run
on any finding. Seeding tests use a private :class:`CtxCheck` (or
monkeypatch :data:`CHECKER`) so deliberate violations never pollute the
global report.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

__all__ = [
    "ENV_VAR",
    "CHECKER",
    "CtxCheck",
    "enabled",
    "install",
]

ENV_VAR = "GEOMESA_TPU_CTXCHECK"


def enabled() -> bool:
    """True when the environment arms the checker (read per spawn, so a
    test can arm a private checker without re-importing the package —
    but the observer seams only feed events after :func:`install`)."""
    return os.environ.get(ENV_VAR, "").strip().lower() in (
        "1", "true", "t", "yes", "on",
    )


def _ambient() -> tuple:
    """Identity snapshot of the calling thread's full context set (the
    ctx-leak comparison wants IDENTITY, not equality — two empty reason
    lists are different collectors)."""
    from geomesa_tpu import ledger, resilience, tracing

    return (
        id(tracing.capture()),
        id(ledger.capture_cost()),
        id(resilience.capture_degraded()),
        ledger.capture_scope(),
    )


class CtxCheck:
    """One findings store plus per-thread attach bookkeeping. The
    module-level :data:`CHECKER` is the process-wide one the observer
    seams feed; tests build private instances for seeded scenarios."""

    def __init__(self, name: str = "global"):
        self.name = name
        # the checker's own mutex must be invisible to itself
        self._mu = threading.Lock()  # lint: disable=GT001(the checker's internal mutex cannot be a checked lock)
        self._tls = threading.local()
        self._findings: list = []
        self._keys: set = set()
        self.tasks = 0
        self.attaches = 0
        self.charges = 0
        self.compiles = 0

    # -- per-thread state ---------------------------------------------------

    def _allowed(self) -> dict:
        """id -> [attach_depth, obj] for every collector currently
        attached on THIS thread (the obj ref pins the id against
        reuse). Fed by the ledger/resilience attach seams."""
        a = getattr(self._tls, "allowed", None)
        if a is None:
            a = self._tls.allowed = {}
        return a

    def _task_rec(self) -> "dict | None":
        return getattr(self._tls, "task", None)

    # -- recording (fed by spawn._blessed and the observer seams) -----------

    @contextmanager
    def task(self, kind: str, label: str, ctx):
        """Bracket one blessed worker task (:mod:`geomesa_tpu.spawn`
        wraps the worker body in this OUTSIDE the context attach, so the
        pre/post snapshots see the worker's ambient state)."""
        prev = self._task_rec()
        rec = {
            "kind": kind,
            "label": label,
            "thread": threading.current_thread().name,
            "declared": bool(ctx is not None and ctx.any()),
        }
        self._tls.task = rec
        pre = _ambient()
        with self._mu:
            self.tasks += 1
        try:
            yield
        finally:
            post = _ambient()
            if post != pre:
                self._record(
                    "ctx-leak",
                    (kind, label),
                    task=f"{kind}:{label}",
                    thread=rec["thread"],
                    detail="worker ambient context set changed across the "
                    "task (an attach was not reset; later tasks on this "
                    "pool thread inherit a dead request's context)",
                )
            self._tls.task = prev

    def on_attach(self, obj, entering: bool) -> None:
        """A cost or degradation collector was attached on (entering)
        or detached from (exiting) the calling thread."""
        if obj is None:
            return
        allowed = self._allowed()
        key = id(obj)
        if entering:
            with self._mu:
                self.attaches += 1
            ent = allowed.get(key)
            if ent is None:
                allowed[key] = [1, obj]
            else:
                ent[0] += 1
        else:
            ent = allowed.get(key)
            if ent is not None:
                ent[0] -= 1
                if ent[0] <= 0:
                    del allowed[key]

    def on_charge(self, cost, field: str) -> None:
        """A context-routed ledger charge is about to fold into
        ``cost`` (None = dropped on the floor, which is legal)."""
        with self._mu:
            self.charges += 1
        if cost is None:
            return
        if id(cost) not in self._allowed():
            rec = self._task_rec()
            self._record(
                "mismatched-cost",
                (getattr(cost, "tenant", ""), field,
                 threading.current_thread().name),
                task=(f"{rec['kind']}:{rec['label']}" if rec else None),
                thread=threading.current_thread().name,
                field=field,
                tenant=getattr(cost, "tenant", ""),
                detail="charge hit a RequestCost never attached on this "
                "thread -- a collector crossed a pool boundary outside "
                "the blessed capture/attach",
            )

    def on_degraded(self, reasons, reason: str) -> None:
        """A degradation stamp is about to append to ``reasons``."""
        if reasons is None:
            return
        if id(reasons) not in self._allowed():
            rec = self._task_rec()
            self._record(
                "orphan-degraded",
                (reason, threading.current_thread().name),
                task=(f"{rec['kind']}:{rec['label']}" if rec else None),
                thread=threading.current_thread().name,
                reason=reason,
                detail="degradation stamp landed in a collector this "
                "thread was never handed",
            )

    def on_compile(self, scope, cost, dur_s: float) -> None:
        """A backend compile finished on the calling thread (raw scope:
        None when no ``compile_scope`` was active)."""
        with self._mu:
            self.compiles += 1
        if scope is not None or cost is not None:
            return
        if threading.current_thread() is threading.main_thread():
            return  # interactive / test-harness compiles are normal
        rec = self._task_rec()
        self._record(
            "orphan-compile",
            (threading.current_thread().name,),
            task=(f"{rec['kind']}:{rec['label']}" if rec else None),
            thread=threading.current_thread().name,
            seconds=round(float(dur_s), 4),
            detail="backend compile on a worker thread with no "
            "compile_scope and no request collector: these compile "
            "seconds are unattributable (the PR 17 warmup bug class)",
        )

    def _record(self, kind: str, key: tuple, **detail) -> None:
        with self._mu:
            k = (kind,) + key
            if k in self._keys:
                return
            self._keys.add(k)
            self._findings.append({"kind": kind, **detail})

    # -- read side ----------------------------------------------------------

    def report(self) -> dict:
        """The findings document plus activity counters; pushes the
        ``geomesa_ctxcheck_*`` gauges for the global checker."""
        with self._mu:
            doc = {
                "checker": self.name,
                "tasks": int(self.tasks),
                "attaches": int(self.attaches),
                "charges": int(self.charges),
                "compiles": int(self.compiles),
                "findings": [dict(f) for f in self._findings],
            }
        self._publish(doc)
        return doc

    def _publish(self, doc: dict) -> None:
        if self is not CHECKER:
            return  # private (seeded-test) checkers stay off the metrics
        try:
            from geomesa_tpu import metrics

            metrics.ctxcheck_tasks.set(doc["tasks"])
            metrics.ctxcheck_findings.set(len(doc["findings"]))
        except Exception:  # pragma: no cover - observability must not break
            pass

    def clear(self) -> None:
        with self._mu:
            self._findings.clear()
            self._keys.clear()
            self.tasks = 0
            self.attaches = 0
            self.charges = 0
            self.compiles = 0


CHECKER = CtxCheck()


# The seams call these forwarders, which dispatch to the CURRENT module
# attribute -- so a test can swap CHECKER for a private instance without
# re-arming the seams.


def _on_attach(obj, entering):
    CHECKER.on_attach(obj, entering)


def _on_charge(cost, field):
    CHECKER.on_charge(cost, field)


def _on_degraded(reasons, reason):
    CHECKER.on_degraded(reasons, reason)


def _on_compile(scope, cost, dur_s):
    CHECKER.on_compile(scope, cost, dur_s)


_installed = False


def install() -> None:
    """Arm the ledger/resilience observer seams and the jax.monitoring
    compile listener (idempotent). The conftest calls this once at
    session start when the env is set."""
    global _installed
    if _installed:
        return
    _installed = True
    from geomesa_tpu import ledger, resilience

    ledger.set_charge_observer(_on_charge)
    ledger.set_attach_observer(_on_attach)
    ledger.add_compile_observer(_on_compile)
    resilience.set_attach_observer(_on_attach)
    resilience.set_degraded_observer(_on_degraded)
    ledger.install()  # compile events flow from the first jit, not the first server
