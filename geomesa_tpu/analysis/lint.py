"""AST-based project linter: the GT001-GT008 invariant rules.

Driver only -- the rules themselves live in
:mod:`geomesa_tpu.analysis.rules`, one module per rule. Each rule walks
a parsed module and yields :class:`Finding`s; findings are suppressed by
a ``# lint: disable=GTnnn(reason)`` comment on the flagged line or the
line directly above it. The reason is mandatory: a bare
``disable=GTnnn`` does NOT suppress (an un-justified exemption is
exactly the silent regression the linter exists to prevent).

Entry points: :func:`lint_paths` (files/directories), :func:`lint_package`
(the installed ``geomesa_tpu`` tree -- what the self-lint test and the
``geomesa-tpu lint`` default run), and :func:`main` (CLI body; exit 0
clean / 1 findings / 2 unreadable input). ``main`` also grows the CI
surface: ``fmt="json"``/``"sarif"`` emit machine-readable findings
(SARIF 2.1.0 for code-scanning upload) and ``changed=True`` scopes the
run to files touched per ``git diff`` -- exit codes are identical in
every mode so pipelines never special-case the format.

The linter is purely static: it parses source text and never imports
the code under analysis, so it runs without jax and can lint fixture
trees that would not import at all.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

__all__ = [
    "Finding",
    "LintContext",
    "lint_file",
    "lint_paths",
    "lint_package",
    "format_findings",
    "findings_to_json",
    "findings_to_sarif",
    "changed_paths",
    "main",
]

_DISABLE_RE = re.compile(
    r"#\s*lint:\s*disable=((?:GT\d{3})(?:\s*,\s*GT\d{3})*)\s*\(([^)#]*)\)"
)
# the lookahead rejects BOTH '(' and ',': rejecting only '(' lets the
# regex engine backtrack the greedy code-list one element short and
# "find" a bare directive inside a reasoned multi-code disable
_BARE_DISABLE_RE = re.compile(
    r"#\s*lint:\s*disable=((?:GT\d{3})(?:\s*,\s*GT\d{3})*)(?!\s*[,(])"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation: ``rule`` is the GTnnn code, ``line`` 1-based."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class LintContext:
    """Per-file state handed to every rule: the parsed tree, source
    lines, the path relative to the lint root (forward slashes -- rules
    scope themselves by it, e.g. GT007 to ``store/``), and the project
    registries (declared conf keys, registered failpoint names) parsed
    STATICALLY from source so linting never imports the linted code."""

    def __init__(
        self, path, rel, src, tree, conf_keys, failpoints, slo_registries=None
    ):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.conf_keys = conf_keys
        self.failpoints = failpoints
        # the GT009 registries: declared SLO names + flight-recorder
        # reasons (slo.py) and ledger cost fields (ledger.py)
        sr = slo_registries or {}
        self.slo_names = sr.get("slo_names", frozenset())
        self.flight_reasons = sr.get("flight_reasons", frozenset())
        self.ledger_fields = sr.get("ledger_fields", frozenset())

    def finding(self, rule: str, node, message: str) -> Finding:
        return Finding(
            rule,
            self.path,
            getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0) + 1,
            message,
        )


def _disabled_rules(lines) -> "dict[int, set]":
    """line (1-based) -> set of GT codes a reasoned disable comment on
    that line suppresses."""
    out: dict = {}
    for i, line in enumerate(lines, start=1):
        m = _DISABLE_RE.search(line)
        if m and m.group(2).strip():
            out[i] = {c.strip() for c in m.group(1).split(",")}
    return out


def _bare_disables(lines) -> "list[tuple[int, str]]":
    """Reason-less ``disable=GTnnn`` directives: reported as findings of
    the rule they tried to silence (the exemption needs a justification)."""
    out: list = []
    for i, line in enumerate(lines, start=1):
        m = _BARE_DISABLE_RE.search(line)
        if m:
            for code in m.group(1).split(","):
                out.append((i, code.strip()))
    return out


# -- project registries (parsed, never imported) -----------------------------


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _find_source(root: str, name: str) -> "str | None":
    """Locate ``name`` (e.g. ``conf.py``) in the linted tree, falling
    back to this package's own copy -- fixture trees usually carry no
    registry of their own and lint against the real one."""
    for cand in (
        os.path.join(root, name),
        os.path.join(root, "geomesa_tpu", name),
    ):
        if os.path.isfile(cand):
            return cand
    own = os.path.join(_package_root(), name)
    return own if os.path.isfile(own) else None


def _assigned_node(tree, target: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == target:
                    return node.value
    return None


def _parse_conf_keys(root: str) -> "frozenset[str]":
    """The GT008 key registry: string keys of the ``_DEFS`` dict in
    conf.py (every declared system property)."""
    path = _find_source(root, "conf.py")
    if path is None:
        return frozenset()
    try:
        with open(path) as fh:
            value = _assigned_node(ast.parse(fh.read()), "_DEFS")
    except (OSError, SyntaxError):
        return frozenset()
    if not isinstance(value, ast.Dict):
        return frozenset()
    return frozenset(
        k.value
        for k in value.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    )


def _parse_failpoints(root: str) -> "frozenset[str]":
    """The GT005 registry: the ``POINTS`` tuple in failpoints.py."""
    return _parse_str_tuple(root, "failpoints.py", "POINTS")


def _parse_str_tuple(root: str, fname: str, target: str) -> "frozenset[str]":
    """String elements of a module-level tuple/list assignment, parsed
    statically (the shared mechanism behind the GT005/GT009 registries)."""
    path = _find_source(root, fname)
    if path is None:
        return frozenset()
    try:
        with open(path) as fh:
            value = _assigned_node(ast.parse(fh.read()), target)
    except (OSError, SyntaxError):
        return frozenset()
    if not isinstance(value, (ast.Tuple, ast.List)):
        return frozenset()
    return frozenset(
        e.value
        for e in value.elts
        if isinstance(e, ast.Constant) and isinstance(e.value, str)
    )


def _parse_slo_registries(root: str) -> dict:
    """The GT009 registries: SLO names and flight-recorder reasons from
    slo.py, ledger cost fields from ledger.py."""
    return {
        "slo_names": _parse_str_tuple(root, "slo.py", "SLO_NAMES"),
        "flight_reasons": _parse_str_tuple(
            root, "slo.py", "FLIGHT_REASONS"
        ),
        "ledger_fields": _parse_str_tuple(root, "ledger.py", "FIELDS"),
    }


# -- driver ------------------------------------------------------------------


def lint_file(
    path: str,
    rel: "str | None" = None,
    root: "str | None" = None,
    rules=None,
    _registries=None,
) -> "list[Finding]":
    from geomesa_tpu.analysis.rules import ALL_RULES

    root = root or os.path.dirname(os.path.abspath(path))
    rel = rel if rel is not None else os.path.basename(path)
    with open(path) as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("GT000", path, e.lineno or 1, 1, f"syntax error: {e.msg}")]
    conf_keys, failpoints, slo_registries = _registries or (
        _parse_conf_keys(root),
        _parse_failpoints(root),
        _parse_slo_registries(root),
    )
    ctx = LintContext(
        path, rel, src, tree, conf_keys, failpoints, slo_registries
    )
    disabled = _disabled_rules(ctx.lines)
    findings: list = []
    seen = set()  # nested withs/loops walk shared sub-trees: dedupe
    for rule in rules if rules is not None else ALL_RULES:
        for f in rule.check(ctx):
            if f in seen:
                continue
            seen.add(f)
            if f.rule in disabled.get(f.line, ()) or f.rule in disabled.get(
                f.line - 1, ()
            ):
                continue
            findings.append(f)
    for line, code in _bare_disables(ctx.lines):
        findings.append(
            Finding(
                code,
                path,
                line,
                1,
                "disable comment without a reason -- use "
                f"`# lint: disable={code}(why this site is exempt)`",
            )
        )
    return findings


def _iter_py_files(top: str):
    for dirpath, dirnames, names in os.walk(top):
        dirnames[:] = [
            d for d in sorted(dirnames) if d != "__pycache__" and not d.startswith(".")
        ]
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def lint_paths(paths, rules=None) -> "list[Finding]":
    """Lint files and/or directory trees; findings sorted by location.
    Relative paths (rule scoping, e.g. GT007's ``store/``) resolve
    against each given directory (or the file's own directory)."""
    findings: list = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            registries = (
                _parse_conf_keys(p),
                _parse_failpoints(p),
                _parse_slo_registries(p),
            )
            for f in _iter_py_files(p):
                findings += lint_file(
                    f,
                    rel=os.path.relpath(f, p),
                    root=p,
                    rules=rules,
                    _registries=registries,
                )
        elif os.path.isfile(p):
            findings += lint_file(p, rules=rules)
        else:
            raise FileNotFoundError(p)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_package(rules=None) -> "list[Finding]":
    """Lint the installed ``geomesa_tpu`` tree itself (the self-lint
    test and the ``geomesa-tpu lint`` default)."""
    return lint_paths([_package_root()], rules=rules)


def format_findings(findings) -> str:
    return "\n".join(f.format() for f in findings)


# -- machine-readable emitters (CI surface) ----------------------------------


def _rule_titles() -> "dict[str, str]":
    from geomesa_tpu.analysis.rules import ALL_RULES

    return {r.CODE: r.TITLE for r in ALL_RULES}


def findings_to_json(findings) -> str:
    """Findings as a JSON array (stable keys: rule/path/line/col/
    message/title) -- the greppable CI artifact."""
    import json

    titles = _rule_titles()
    return json.dumps(
        [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "title": titles.get(f.rule, ""),
            }
            for f in findings
        ],
        indent=2,
    )


def findings_to_sarif(findings) -> str:
    """Findings as a minimal SARIF 2.1.0 log -- one run, one rule entry
    per GT code, one result per finding -- the shape GitHub code
    scanning (and every SARIF viewer) ingests. Paths are emitted
    relative to the working directory when possible so the artifact is
    portable across checkouts."""
    import json

    titles = _rule_titles()
    cwd = os.getcwd()

    def _uri(path: str) -> str:
        try:
            rel = os.path.relpath(path, cwd)
        except ValueError:  # different drive (windows): keep absolute
            rel = path
        if rel.startswith(".."):
            rel = path
        return rel.replace(os.sep, "/")

    used = sorted({f.rule for f in findings})
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "geomesa-tpu-lint",
                        "informationUri": (
                            "https://github.com/geomesa/geomesa-tpu"
                        ),
                        "rules": [
                            {
                                "id": code,
                                "shortDescription": {
                                    "text": titles.get(code, code)
                                },
                            }
                            for code in used
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": _uri(f.path)
                                    },
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col,
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }
    return json.dumps(doc, indent=2)


def changed_paths(base: "str | None" = None) -> "list[str]":
    """Python files touched per git: ``git diff --name-only`` against
    ``base`` (default: the working tree + index vs HEAD, plus
    untracked ``*.py``) -- the ``lint --changed`` scope. Paths outside
    the repo's ``geomesa_tpu`` tree are kept (fixture trees lint too);
    deleted files are dropped. Raises ``RuntimeError`` when git is
    unavailable or the cwd is not a repository."""
    import subprocess

    def _git(*args: str) -> "list[str]":
        proc = subprocess.run(
            ("git",) + args,
            capture_output=True,
            text=True,
            check=False,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: {proc.stderr.strip()}"
            )
        return [ln for ln in proc.stdout.splitlines() if ln.strip()]

    names: "list[str]" = []
    if base:
        names += _git("diff", "--name-only", base, "--")
    else:
        names += _git("diff", "--name-only", "HEAD", "--")
        names += _git(
            "ls-files", "--others", "--exclude-standard", "--", "*.py"
        )
    out, seen = [], set()
    for n in names:
        if not n.endswith(".py") or n in seen:
            continue
        seen.add(n)
        if os.path.isfile(n):  # deleted files have nothing to lint
            out.append(n)
    return sorted(out)


def main(paths=None, out=print, fmt="text", changed=False) -> int:
    """CLI body (``geomesa-tpu lint``): 0 clean, 1 findings, 2 on an
    unreadable input path or an unusable ``--changed`` scope. ``fmt``
    picks the emitter (``text``/``json``/``sarif``); json and sarif
    ALWAYS emit a document, even when clean, so CI can upload the
    artifact unconditionally."""
    try:
        if changed:
            scope = changed_paths()
            findings = lint_paths(scope) if scope else []
        else:
            findings = lint_paths(paths) if paths else lint_package()
    except FileNotFoundError as e:
        out(f"error: no such file or directory: {e}")
        return 2
    except RuntimeError as e:
        out(f"error: {e}")
        return 2
    if fmt == "json":
        out(findings_to_json(findings))
    elif fmt == "sarif":
        out(findings_to_sarif(findings))
    elif findings:
        out(format_findings(findings))
        out(f"{len(findings)} finding(s)")
    return 1 if findings else 0
