"""Shared AST helpers for the rule modules."""

from __future__ import annotations

import ast

__all__ = ["terminal_name", "receiver_name", "walk_no_defs", "str_arg"]


def terminal_name(node) -> "str | None":
    """The rightmost identifier of a Name/Attribute chain
    (``self._mem_lock`` -> ``_mem_lock``; ``np.asarray`` -> ``asarray``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def receiver_name(node) -> "str | None":
    """The identifier the attribute hangs off (``os.replace`` -> ``os``;
    ``self._q.get`` -> ``_q``). None for non-attribute nodes."""
    if not isinstance(node, ast.Attribute):
        return None
    return terminal_name(node.value)


def walk_no_defs(node):
    """Walk a statement body WITHOUT descending into nested function /
    lambda definitions -- their bodies execute later, outside whatever
    lexical context (held lock, loop) is being analyzed."""
    stack = list(node) if isinstance(node, list) else [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def str_arg(call: ast.Call, index: int = 0) -> "str | None":
    """The call's ``index``-th positional arg when it is a string
    literal, else None."""
    if len(call.args) > index and isinstance(call.args[index], ast.Constant):
        v = call.args[index].value
        if isinstance(v, str):
            return v
    return None
