"""The GT001-GT012 rule modules, one per rule, plus shared AST helpers.

A rule module exposes ``CODE`` (the GTnnn id), ``TITLE`` (one line for
the README/CLI table) and ``check(ctx)`` yielding
:class:`~geomesa_tpu.analysis.lint.Finding`s. Register new rules by
appending the module to :data:`ALL_RULES`.
"""

from __future__ import annotations

from geomesa_tpu.analysis.astutil import (  # noqa: F401 (re-export)
    receiver_name,
    str_arg,
    terminal_name,
    walk_no_defs,
)
from geomesa_tpu.analysis.rules import (
    gt001_bare_locks,
    gt002_blocking_under_lock,
    gt003_wall_clock,
    gt004_host_sync,
    gt005_failpoint_names,
    gt006_metric_discipline,
    gt007_publish_fsync,
    gt008_conf_keys,
    gt009_slo_registries,
    gt010_blessed_spawn,
    gt011_taxonomy_bypass,
    gt012_unbucketed_dims,
)

ALL_RULES = (
    gt001_bare_locks,
    gt002_blocking_under_lock,
    gt003_wall_clock,
    gt004_host_sync,
    gt005_failpoint_names,
    gt006_metric_discipline,
    gt007_publish_fsync,
    gt008_conf_keys,
    gt009_slo_registries,
    gt010_blessed_spawn,
    gt011_taxonomy_bypass,
    gt012_unbucketed_dims,
)

RULE_TABLE = [(r.CODE, r.TITLE) for r in ALL_RULES]
