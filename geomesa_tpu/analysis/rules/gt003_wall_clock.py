"""GT003: ``time.time()`` used where a duration or deadline needs a
monotonic clock.

Wall clock jumps (NTP step, VM migration, manual reset) extend or
truncate anything computed as a ``time.time()`` difference -- the
pre-fix audit drain deadline could stall ``close()`` unboundedly on a
backwards jump. Durations and deadlines use ``time.monotonic()`` /
``time.perf_counter()``; the few INTENTIONAL epoch uses (timestamps
persisted into data or logs, the Perfetto trace anchor) carry a
reasoned ``# lint: disable=GT003(...)`` -- that comment IS the
allowlist, kept next to the use it justifies.
"""

from __future__ import annotations

import ast

CODE = "GT003"
TITLE = "time.time() for durations/deadlines -- use time.monotonic()"


def check(ctx):
    imported_time_fn = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    imported_time_fn.add(alias.asname or alias.name)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        flagged = (
            isinstance(func, ast.Attribute)
            and func.attr == "time"
            and isinstance(func.value, ast.Name)
            and func.value.id in ("time", "_time")
        ) or (isinstance(func, ast.Name) and func.id in imported_time_fn)
        if flagged:
            yield ctx.finding(
                CODE,
                node,
                "time.time() is wall-clock: durations and deadlines must "
                "use time.monotonic() (intentional epoch timestamps get a "
                "reasoned disable comment)",
            )
