"""GT009: SLO names, ledger fields and flight-recorder reasons must
come from their declared registries.

The SLO/ledger layer (ISSUE 9) keys everything by short strings: a
``charge("device_secconds", ...)`` typo would silently mint a cost
column nobody aggregates, an unregistered flight-recorder reason would
name bundle directories (and a metric label) outside the bounded enum,
and an unknown SLO name would KeyError at runtime on the first scrape.
Same static-parse discipline as GT006 metrics / GT008 conf keys: the
registries (``FIELDS`` in ledger.py, ``SLO_NAMES`` / ``FLIGHT_REASONS``
in slo.py) are parsed from source, never imported, and every literal
call-site argument is validated against them.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import (
    receiver_name,
    str_arg,
    terminal_name,
)

CODE = "GT009"
TITLE = (
    "SLO name / ledger field / flight-recorder reason not in its "
    "declared registry"
)


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node.func)
        arg = str_arg(node)
        if arg is None:
            continue
        if name == "charge" and ctx.ledger_fields:
            # ledger.charge / RequestCost.charge / cost.charge — all
            # take a FIELDS name first
            if arg not in ctx.ledger_fields:
                yield ctx.finding(
                    CODE,
                    node,
                    f"ledger field {arg!r} is not declared in "
                    "ledger.FIELDS -- declare it (and document what it "
                    "measures) or fix the name",
                )
        elif name == "trigger" and ctx.flight_reasons:
            # FlightRecorder.trigger: only flag receivers that are
            # clearly the flight recorder (FLIGHTREC.trigger,
            # self.flightrec.trigger, recorder.trigger) — a generic
            # .trigger() elsewhere is none of this rule's business
            recv = (receiver_name(node.func) or "").lower()
            if (
                ("flight" in recv or recv.endswith("rec"))
                and arg not in ctx.flight_reasons
            ):
                yield ctx.finding(
                    CODE,
                    node,
                    f"flight-recorder reason {arg!r} is not declared in "
                    "slo.FLIGHT_REASONS -- reasons are a bounded enum "
                    "(bundle dir names + metric label)",
                )
        elif name == "slo_def" and ctx.slo_names:
            if arg not in ctx.slo_names:
                yield ctx.finding(
                    CODE,
                    node,
                    f"SLO name {arg!r} is not declared in slo.SLO_NAMES "
                    "-- register it (and its slo.<name>.* conf keys) or "
                    "fix the name",
                )
