"""GT008: system-property keys used via ``conf`` must be declared in
the key registry (``conf._DEFS``).

``sys_prop("io.worker")`` (typo) raises at runtime -- but only on the
code path that reads it, possibly in production; and an env override
``GEOMESA_TPU_IO_WORKER`` for an undeclared key is silently ignored.
Declaring every key in one registry makes both failure modes
impossible: the linter validates literals against the registry (parsed
statically from conf.py), and conf warns once per process about unknown
``GEOMESA_TPU_*`` environment variables.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import str_arg, terminal_name

CODE = "GT008"
TITLE = "conf key literal not declared in the conf._DEFS key registry"

_CONF_FNS = {"sys_prop", "set_prop", "clear_prop", "prop_override"}


def check(ctx):
    if not ctx.conf_keys:
        return  # no registry found: nothing to validate against
    if ctx.rel.rsplit("/", 1)[-1] == "conf.py":
        return  # the registry itself
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if terminal_name(node.func) not in _CONF_FNS:
            continue
        key = str_arg(node)
        if key is not None and key not in ctx.conf_keys:
            yield ctx.finding(
                CODE,
                node,
                f"system property {key!r} is not declared in conf._DEFS "
                "-- declare it (default + parser + doc) or fix the key",
            )
