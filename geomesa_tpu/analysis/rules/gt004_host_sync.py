"""GT004: host synchronization inside loops on the device hot paths.

``np.asarray`` / ``jax.device_get`` / ``.block_until_ready()`` /
``.item()`` on a JAX array forces a device->host transfer and stalls
the dispatch pipeline; inside a loop that is one round trip PER
ITERATION -- the anti-pattern the fused/batched launches of PRs 1-2
exist to avoid. Scoped to the files where a loop is plausibly iterating
device work: ``ops/``, ``join/``, ``results/``, ``query/runner.py``,
``sched/fusion.py``, ``pubsub/matcher.py``, ``warmup.py``. Intended
sync points (the mask fetch that ends a launch) carry a reasoned
disable comment.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import receiver_name, walk_no_defs

CODE = "GT004"
TITLE = "host sync (np.asarray/device_get/block_until_ready/.item) in a device hot-path loop"

_HOT_PREFIXES = ("ops/", "join/", "results/")
_HOT_FILES = {
    "query/runner.py",
    "sched/fusion.py",
    "pubsub/matcher.py",
    "warmup.py",
}

_NP_SYNCS = {"asarray", "array"}
_ANY_SYNCS = {"block_until_ready", "item"}


def _applies(rel: str) -> bool:
    rel = rel.removeprefix("geomesa_tpu/")
    return rel in _HOT_FILES or any(rel.startswith(p) for p in _HOT_PREFIXES)


def _sync_call(call: ast.Call) -> "str | None":
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    recv = receiver_name(func) or ""
    if func.attr in _NP_SYNCS and recv in ("np", "numpy", "onp"):
        return f"{recv}.{func.attr}()"
    if func.attr == "device_get" and recv == "jax":
        return "jax.device_get()"
    if func.attr in _ANY_SYNCS:
        return f".{func.attr}()"
    return None


def check(ctx):
    if not _applies(ctx.rel):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for sub in walk_no_defs(node.body):
            if isinstance(sub, ast.Call):
                what = _sync_call(sub)
                if what:
                    yield ctx.finding(
                        CODE,
                        sub,
                        f"{what} inside a loop on a device hot path forces "
                        "one device->host round trip per iteration -- batch "
                        "the transfer outside the loop (an intended "
                        "per-launch sync point gets a reasoned disable)",
                    )
