"""GT011: serving-path ``except Exception`` that bypasses the fault
taxonomy.

The resilience layer (PR 7) threads ONE taxonomy through the serving
path: every fault is classified (``resilience.classify``) and then
retried, degraded (``note_degraded``) or surfaced typed. A handler that
catches ``Exception`` (or bare ``except``) and neither re-raises, nor
routes through the taxonomy, nor even USES the caught exception
swallows faults silently — the next device OOM or corrupt partition
vanishes instead of degrading visibly. Scoped to the serving-path
modules; an intentional swallow (best-effort observability, last-resort
guards) must carry a reasoned disable so the justification sits next to
the code.

A handler passes when its body (including nested handlers) re-raises,
calls ``classify``/``note_degraded``, or references the bound exception
name (surfacing the error via a response, log, trace stamp or typed
wrapper counts as routing it somewhere visible).
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import terminal_name

CODE = "GT011"
TITLE = (
    "serving-path `except Exception` swallows the fault -- re-raise, "
    "classify() / note_degraded(), or use the bound exception"
)

_HOT_PREFIXES = (
    "sched/",
    "store/",
    "query/",
    "pubsub/",
    "join/",
    "results/",
    "stream/",
)
_HOT_FILES = {
    "server.py",
    "router.py",
    "replica.py",
    "warmup.py",
}

#: taxonomy entry points: a call to any of these routes the fault
_TAXONOMY_CALLS = {"classify", "note_degraded", "is_oom"}

_BROAD = {"Exception", "BaseException"}


def _applies(rel: str) -> bool:
    rel = rel.removeprefix("geomesa_tpu/")
    return rel in _HOT_FILES or any(rel.startswith(p) for p in _HOT_PREFIXES)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _routes_fault(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            if terminal_name(node.func) in _TAXONOMY_CALLS:
                return True
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return True
    return False


def check(ctx):
    if not _applies(ctx.rel):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _routes_fault(node):
            continue
        yield ctx.finding(
            CODE,
            node,
            "broad except swallows the fault without classify()/"
            "note_degraded()/re-raise (and never uses the exception) -- "
            "route it through the resilience taxonomy, or justify the "
            "swallow with a reasoned disable",
        )
