"""GT007: an ``os.replace`` publish in ``store/`` must be preceded by a
durable write.

The crash-consistency contract (PR 3) is write-new -> fsync -> publish:
an ``os.replace`` that flips a manifest/sidecar into place without the
new content fsynced first can surface a published pointer to data the
page cache never wrote back -- the exact torn state the generation
machinery exists to prevent. Within the enclosing function, a durable
write is a call whose name mentions ``fsync`` or one of the known
durable helpers (``_write_file``, ``_write_part_file``, ``_fsync_dir``,
``_publish_manifest``) appearing BEFORE the replace.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import receiver_name, terminal_name

CODE = "GT007"
TITLE = "os.replace publish in store/ without a preceding fsync/durable write"

_DURABLE_HELPERS = {
    "_write_file",
    "_write_part_file",
    "_fsync_dir",
    "_publish_manifest",
}


def _applies(rel: str) -> bool:
    rel = rel.removeprefix("geomesa_tpu/")
    return rel.startswith("store/")


def check(ctx):
    if not _applies(ctx.rel):
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        durable_lines: list = []
        replaces: list = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func) or ""
            if name in _DURABLE_HELPERS or "fsync" in name:
                durable_lines.append(node.lineno)
            elif name in ("replace", "rename") and receiver_name(node.func) == "os":
                replaces.append(node)
        for node in replaces:
            if not any(line < node.lineno for line in durable_lines):
                yield ctx.finding(
                    CODE,
                    node,
                    "os.replace publish without a preceding durable write "
                    "-- fsync the new content (e.g. via _write_file) "
                    "before flipping it into place",
                )
