"""GT001: bare ``threading.Lock()`` / ``threading.RLock()`` outside the
checked factory.

Every in-process mutex must be built through
``locking.checked_lock(name)`` / ``checked_rlock(name)`` so the runtime
lock-order checker (analysis/lockcheck.py) can see it: a bare lock is
invisible to cycle detection and held-across-blocking accounting, which
is how the next ABBA deadlock ships unnoticed. ``locking.py`` itself is
the factory and exempt; references (``default_factory=threading.Lock``)
are flagged as well as calls.
"""

from __future__ import annotations

import ast

CODE = "GT001"
TITLE = (
    "bare threading.Lock()/RLock() -- use locking.checked_lock()/"
    "checked_rlock() so the lock-order checker can see it"
)

_FACTORY_FILES = ("locking.py",)


def check(ctx):
    if ctx.rel.rsplit("/", 1)[-1] in _FACTORY_FILES:
        return
    from_threading = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                if alias.name in ("Lock", "RLock"):
                    from_threading.add(alias.asname or alias.name)
    for node in ast.walk(ctx.tree):
        bare = None
        if (
            isinstance(node, ast.Attribute)
            and node.attr in ("Lock", "RLock")
            and isinstance(node.value, ast.Name)
            and node.value.id == "threading"
        ):
            bare = f"threading.{node.attr}"
        elif isinstance(node, ast.Name) and node.id in from_threading:
            bare = node.id
        if bare is not None:
            yield ctx.finding(
                CODE,
                node,
                f"bare {bare} -- build locks via locking.checked_lock(name)"
                " / checked_rlock(name) (runtime lock-order checking)",
            )
