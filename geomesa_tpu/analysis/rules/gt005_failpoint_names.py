"""GT005: failpoint name literals must be registered in
``failpoints.POINTS``.

A chaos test arming ``fail.flsh.before_publish`` (typo) silently tests
nothing -- the store evaluates a different name and the kill never
fires. Registration keeps the set of interesting instants reviewable in
one place; the registry is parsed statically from failpoints.py so the
linter never imports the package.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import str_arg, terminal_name

CODE = "GT005"
TITLE = "failpoint name literal not registered in failpoints.POINTS"

_FAIL_FNS = {
    "fail_point",
    "fail_hit",
    "set_failpoint",
    "clear_failpoint",
    "failpoint_override",
}


def check(ctx):
    if not ctx.failpoints:
        return  # no registry found: nothing to validate against
    if ctx.rel.rsplit("/", 1)[-1] == "failpoints.py":
        return  # the registry itself
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if terminal_name(node.func) not in _FAIL_FNS:
            continue
        name = str_arg(node)
        if name is not None and name not in ctx.failpoints:
            yield ctx.finding(
                CODE,
                node,
                f"failpoint {name!r} is not registered in "
                "failpoints.POINTS -- a typo here arms nothing; add it to "
                "the registry (or fix the name)",
            )
