"""GT006: metric-name / label discipline.

Names: every metric registered on a registry must carry the
``geomesa_`` prefix (one namespace on shared Prometheus infrastructure)
and be lower_snake_case. Labels: a label value built from an f-string
or string concatenation is a cardinality bomb -- each distinct value
mints a new time series, and an interpolated filter string or id turns
the registry into an unbounded allocation. Label values must be
bounded, str-typed enums or names.
"""

from __future__ import annotations

import ast
import re

from geomesa_tpu.analysis.astutil import receiver_name, str_arg

CODE = "GT006"
TITLE = "metric name without geomesa_ prefix, or unbounded (interpolated) label value"

_NAME_RE = re.compile(r"^geomesa_[a-z0-9_]+$")
_REGISTRY_FNS = {"counter", "gauge", "histogram"}
_LABELED_FNS = {"inc", "dec", "observe"}


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        attr = node.func.attr
        recv = (receiver_name(node.func) or "").lower()
        if attr in _REGISTRY_FNS and "registry" in recv:
            name = str_arg(node)
            if name is not None and not _NAME_RE.match(name):
                yield ctx.finding(
                    CODE,
                    node,
                    f"metric name {name!r} must match geomesa_[a-z0-9_]+ "
                    "(shared-namespace prefix, lower_snake_case)",
                )
        if attr in _LABELED_FNS:
            for kw in node.keywords:
                if isinstance(kw.value, (ast.JoinedStr, ast.BinOp)):
                    yield ctx.finding(
                        CODE,
                        kw.value,
                        f"label {kw.arg!r} is built by interpolation -- "
                        "every distinct value mints a new time series; "
                        "label values must be bounded str enums/names",
                    )
