"""GT002: blocking call while an in-process lock is held.

A lock held across file I/O, a queue wait, a socket operation or a
device sync serializes every other thread on the holder's I/O latency
-- the exact pathology PR 2 removed from the scan path (lock-free worker
reads under a consumer-held lock) and PR 1 designed the scheduler
around. Sites where holding IS the point (an append log whose lock
exists to order its writes) carry a reasoned disable comment and a
``blocking_ok=True`` checked-lock annotation for the runtime checker.

Heuristics (static analysis can only see lexical structure): a with-item
whose terminal identifier looks lock-ish (``...lock``, ``_cv``,
``...mutex``) opens a held region; direct calls in that region matching
the blocking table below are flagged. Calls behind helper functions are
the runtime checker's job.
"""

from __future__ import annotations

import ast
import re

from geomesa_tpu.analysis.astutil import receiver_name, terminal_name, walk_no_defs

CODE = "GT002"
TITLE = "blocking call (file/socket I/O, queue.get, sleep, device sync) under a held lock"

_LOCKISH = re.compile(r"(lock|mutex)$|^_?cv$")
_QUEUEISH = re.compile(r"^_?q$|queue$")
_FILEISH = re.compile(r"^_?(fh|f|file|sock)$|(fh|file|sock)$")

#: attribute calls that block regardless of receiver
_BLOCKING_ATTRS = {
    "fsync", "replace", "rename", "renames", "urlopen", "sleep",
    "block_until_ready", "accept", "recv", "send", "sendall", "connect",
}
#: attribute calls that block for specific receivers
_SUBPROCESS_ATTRS = {"run", "call", "check_call", "check_output"}


def _lockish_item(item: ast.withitem) -> bool:
    name = terminal_name(item.context_expr)
    return bool(name and _LOCKISH.search(name.lower()))


def _blocking(call: ast.Call) -> "str | None":
    func = call.func
    if isinstance(func, ast.Name) and func.id == "open":
        return "open()"
    if not isinstance(func, ast.Attribute):
        return None
    attr, recv = func.attr, (receiver_name(func) or "")
    if attr in _BLOCKING_ATTRS:
        return f"{recv + '.' if recv else ''}{attr}()"
    if attr in _SUBPROCESS_ATTRS and recv == "subprocess":
        return f"subprocess.{attr}()"
    if attr == "flock" and recv == "fcntl":
        return "fcntl.flock()"
    if attr == "get" and _QUEUEISH.search(recv.lower()):
        return f"{recv}.get()"
    if attr in ("write", "flush", "read", "readline", "readinto") and _FILEISH.search(
        recv.lower()
    ):
        return f"{recv}.{attr}()"
    return None


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        held = [
            terminal_name(i.context_expr)
            for i in node.items
            if _lockish_item(i)
        ]
        if not held:
            continue
        for sub in walk_no_defs(node.body):
            if isinstance(sub, ast.Call):
                what = _blocking(sub)
                if what:
                    yield ctx.finding(
                        CODE,
                        sub,
                        f"{what} while holding {held[0]!r} -- move the "
                        "blocking call outside the lock, or disable with "
                        "a reason AND mark the lock blocking_ok=True",
                    )
