"""GT010: raw thread/pool construction outside the blessed spawn helper.

Contextvars are per-thread: a raw ``threading.Thread`` /
``ThreadPoolExecutor`` silently drops the submitting request's full
context set (tracing span, ledger cost collector, degradation
collector, ``compile_scope``) — the PR 17 warmup-misattribution bug
class. Every spawn site must go through :mod:`geomesa_tpu.spawn`
(``spawn_thread`` / ``ContextPool``), which captures-and-attaches the
set (or explicitly declares a context-less service thread with
``context=False``) and is the instrumentation point for the runtime
context checker (``GEOMESA_TPU_CTXCHECK=1``). The factory's own backing
constructors carry reasoned disables, exactly like GT001's
``locking.py`` exemption.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import receiver_name

CODE = "GT010"
TITLE = (
    "raw threading.Thread/ThreadPoolExecutor -- use spawn.spawn_thread()/"
    "ContextPool so request contexts cross the pool boundary"
)

#: constructor names that create a thread of execution the request
#: contexts will not follow
_SPAWNERS = {
    "Thread",
    "Timer",
    "ThreadPoolExecutor",
    "ProcessPoolExecutor",
    "start_new_thread",
}

#: modules whose import makes a bare Name call a spawn site
_SPAWN_MODULES = ("threading", "concurrent.futures", "_thread")


def check(ctx):
    imported = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module in _SPAWN_MODULES:
            for alias in node.names:
                if alias.name in _SPAWNERS:
                    imported.add(alias.asname or alias.name)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        raw = None
        if isinstance(func, ast.Attribute) and func.attr in _SPAWNERS:
            recv = receiver_name(func) or ""
            if recv in ("threading", "futures", "_thread"):
                raw = f"{recv}.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in imported:
            raw = func.id
        if raw is not None:
            yield ctx.finding(
                CODE,
                node,
                f"raw {raw}() drops the request context set (trace, cost, "
                "degraded, compile_scope) at the pool boundary -- use "
                "spawn.spawn_thread()/spawn.ContextPool (context=False for "
                "service threads that attach per-item contexts themselves)",
            )
