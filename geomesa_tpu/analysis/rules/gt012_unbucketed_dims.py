"""GT012: hand-rolled capacity rounding on the compile-shape paths.

PR 17 killed the compile cliff by making every dynamic size that
reaches a jit cache key or pad/capacity computation pass through ONE
ladder (:func:`geomesa_tpu.bucketing.bucket_cap`): a closed, conf-tuned
shape set that warmup can pre-compile. A hand-rolled next-power-of-two
(``1 << (n - 1).bit_length()``, ``math.log2``/``ceil`` arithmetic) on
those paths silently regrows a per-shape compile cliff the ladder no
longer covers — and warmup cannot pre-compile shapes it cannot
enumerate. Scoped to the modules that build jit cache keys and padded
capacities: ``ops/``, ``device_cache.py``, ``join/``. A genuinely
non-shape use of ``bit_length`` there (bit math on key encodings)
carries a reasoned disable.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutil import receiver_name

CODE = "GT012"
TITLE = (
    "hand-rolled capacity rounding (bit_length/log2) on a compile-shape "
    "path -- route dynamic sizes through bucketing.bucket_cap()"
)

_SHAPE_PREFIXES = ("ops/", "join/")
_SHAPE_FILES = {"device_cache.py"}


def _applies(rel: str) -> bool:
    rel = rel.removeprefix("geomesa_tpu/")
    return rel in _SHAPE_FILES or any(
        rel.startswith(p) for p in _SHAPE_PREFIXES
    )


def _rolled(call: ast.Call) -> "str | None":
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "bit_length":
        return ".bit_length()"
    if func.attr == "log2" and (receiver_name(func) or "") in (
        "math",
        "np",
        "numpy",
    ):
        return f"{receiver_name(func)}.log2()"
    return None


def check(ctx):
    if not _applies(ctx.rel):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        what = _rolled(node)
        if what:
            yield ctx.finding(
                CODE,
                node,
                f"{what} rounds a dynamic size by hand on a compile-shape "
                "path -- bucketing.bucket_cap() keeps the shape set closed "
                "(and warmup pre-compilable); a non-shape bit-math use "
                "gets a reasoned disable",
            )
