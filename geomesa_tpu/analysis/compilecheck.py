"""Serving-path recompile tripwire: any backend compile outside the
allowed ``compile_scope`` namespace while serving is live is a hard
session-end failure.

PR 17 killed the compile cliff by routing every serving-path jit
through canonical shape buckets and pre-compiling them at warmup; the
invariant that keeps it killed is *no novel compiles while serving*.
This checker enforces it mechanically: it rides the existing
:class:`~geomesa_tpu.ledger.CompileLedger` jax.monitoring hook (the
backend-compile event fires synchronously on the thread that blocked on
it), and while at least one server is live — :func:`make_server` /
``_GeomesaHTTPServer.shutdown`` bracket the window — every compile must
carry an allowed ``compile_scope`` family (:data:`ALLOWED_FAMILIES`:
the :data:`~geomesa_tpu.ledger.SCOPE_FAMILIES` namespace plus the
``warmup`` / ``_system`` staging scopes). A scope-less compile is a
violation unless it is test-harness normality: on the main thread with
no request collector attached. A scope-less compile on a worker thread,
or one charged to a live (non-``_system``) request, is exactly the
shape-cliff regression the bucketing ladder exists to prevent.

Armed by ``GEOMESA_TPU_COMPILECHECK=1``; unset, the ledger's compile
observer list stays empty and the server lifecycle hooks are a single
env check — zero production overhead. The conftest arms it for the
whole tier-1 suite and fails the session on any violation; seeded tests
use a private :class:`CompileCheck` (or monkeypatch :data:`CHECKER`).
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "ENV_VAR",
    "ALLOWED_FAMILIES",
    "CHECKER",
    "CompileCheck",
    "enabled",
    "install",
]

ENV_VAR = "GEOMESA_TPU_COMPILECHECK"


def enabled() -> bool:
    """True when the environment arms the checker (read dynamically;
    the server lifecycle hooks check it per call)."""
    return os.environ.get(ENV_VAR, "").strip().lower() in (
        "1", "true", "t", "yes", "on",
    )


def _allowed_families() -> frozenset:
    from geomesa_tpu import ledger

    return frozenset(
        fam for fam, _ in ledger.SCOPE_FAMILIES
    ) | {"warmup", "_system"}


#: the allowed compile_scope namespace while serving is live: the
#: documented SCOPE_FAMILIES plus the warmup/_system staging scopes
ALLOWED_FAMILIES = _allowed_families()


def _family(signature: str) -> str:
    """The bounded family component of a scope signature
    (``fused.dim:r=64:q=8`` -> ``fused.dim``)."""
    return str(signature).split(":", 1)[0]


class CompileCheck:
    """Serving-window refcount plus the violation store. The
    module-level :data:`CHECKER` is the process-wide one; tests build
    private instances for seeded scenarios."""

    def __init__(self, name: str = "global"):
        self.name = name
        # the checker's own mutex must be invisible to lockcheck
        self._mu = threading.Lock()  # lint: disable=GT001(the checker's internal mutex cannot be a checked lock)
        self._serving = 0
        self._violations: list = []
        self._keys: set = set()
        self.compiles = 0
        self.serving_compiles = 0

    # -- serving window (bracketed by the server lifecycle) -----------------

    def serving_up(self) -> None:
        with self._mu:
            self._serving += 1

    def serving_down(self) -> None:
        with self._mu:
            self._serving = max(self._serving - 1, 0)

    @property
    def serving(self) -> bool:
        with self._mu:
            return self._serving > 0

    # -- recording (fed by the ledger compile-observer seam) ----------------

    def on_compile(self, scope, cost, dur_s: float) -> None:
        """One backend compile finished on the calling thread. ``scope``
        is the RAW active ``compile_scope`` (None when absent); ``cost``
        the active request collector."""
        with self._mu:
            self.compiles += 1
            if self._serving <= 0:
                return
            self.serving_compiles += 1
        tenant = getattr(cost, "tenant", "") if cost is not None else ""
        if scope is not None:
            fam = _family(scope)
            if fam in ALLOWED_FAMILIES:
                return
            self._record(
                (fam,),
                scope=str(scope),
                family=fam,
                thread=threading.current_thread().name,
                tenant=tenant,
                seconds=round(float(dur_s), 4),
                detail="compile under a scope family outside the "
                "documented SCOPE_FAMILIES namespace while serving",
            )
            return
        on_main = threading.current_thread() is threading.main_thread()
        if cost is None and on_main:
            return  # test-harness / interactive compiles are normal
        if cost is not None and tenant == "_system":
            return  # warmup / background staging legs compile on purpose
        self._record(
            (threading.current_thread().name, tenant),
            scope=None,
            thread=threading.current_thread().name,
            tenant=tenant,
            seconds=round(float(dur_s), 4),
            detail="scope-less backend compile while serving: a live "
            "request (or a worker thread) hit a jit cache miss outside "
            "every compile_scope -- a per-shape compile cliff regrowing",
        )

    def _record(self, key: tuple, **detail) -> None:
        with self._mu:
            if key in self._keys:
                return
            self._keys.add(key)
            self._violations.append(dict(detail))

    # -- read side ----------------------------------------------------------

    def report(self) -> dict:
        """The violations document plus activity counters; pushes the
        ``geomesa_compilecheck_*`` gauges for the global checker."""
        with self._mu:
            doc = {
                "checker": self.name,
                "compiles": int(self.compiles),
                "serving_compiles": int(self.serving_compiles),
                "serving": self._serving > 0,
                "violations": [dict(v) for v in self._violations],
            }
        self._publish(doc)
        return doc

    def _publish(self, doc: dict) -> None:
        if self is not CHECKER:
            return  # private (seeded-test) checkers stay off the metrics
        try:
            from geomesa_tpu import metrics

            metrics.compilecheck_compiles.set(doc["serving_compiles"])
            metrics.compilecheck_violations.set(len(doc["violations"]))
        except Exception:  # pragma: no cover - observability must not break
            pass

    def clear(self) -> None:
        with self._mu:
            self._violations.clear()
            self._keys.clear()
            self.compiles = 0
            self.serving_compiles = 0


CHECKER = CompileCheck()


def _on_compile(scope, cost, dur_s):
    # dispatches to the CURRENT module attribute so tests can swap
    # CHECKER for a private instance without re-arming the seam
    CHECKER.on_compile(scope, cost, dur_s)


_installed = False


def install() -> None:
    """Arm the ledger compile-observer seam and the jax.monitoring
    listener (idempotent; conftest calls this when the env is set)."""
    global _installed
    if _installed:
        return
    _installed = True
    from geomesa_tpu import ledger

    ledger.add_compile_observer(_on_compile)
    ledger.install()
