"""Runtime lock-order checker: the thread-sanitizer analog for the
repo's in-process locks.

Every lock built through ``locking.checked_lock(name)`` /
``checked_rlock(name)`` is, when ``GEOMESA_TPU_LOCKCHECK`` is set in the
environment, a drop-in instrumented wrapper that records the process's
lock acquisition graph:

- **Order edges.** Acquiring B while holding A records the edge
  ``A -> B`` (by lock NAME, so per-instance locks like per-trace span
  locks collapse into one bounded node). The first edge that closes a
  cycle (``A -> B`` and, from another code path, ``B -> A``) is an ABBA
  deadlock POTENTIAL: the two paths merely have to run concurrently
  once. Recorded immediately with both paths' thread names -- no actual
  deadlock required to catch it.
- **Held-across-blocking events.** :func:`install_probes` wraps a small
  set of blocking primitives (``open``, ``time.sleep``, ``os.fsync``,
  ``os.replace``, ``queue.Queue.get``); each probe checks this thread's
  held-lock stack and records an event for every held lock not created
  with ``blocking_ok=True`` (locks whose PURPOSE is to order blocking
  writes -- append logs, first-touch device staging -- opt out at the
  declaration, where a reviewer can see the justification next to the
  GT002 disable comment).

Off by default: with the env unset, ``checked_lock`` returns a plain
``threading.Lock`` -- zero per-acquisition overhead in production. The
test suite switches it on process-wide via the conftest (which sets the
env before any package import, so module-level locks instrument too);
``CHECKER.report()`` is the session's findings, and the
``geomesa_lockcheck_*`` gauges mirror it for scrapes.

Seeding tests build a private :class:`LockCheck` and pass it to
:class:`CheckedLock` -- edges and events only ever record into the
checker of the locks involved, so a deliberately-inverted pair in a test
cannot pollute the global report.
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "ENV_VAR",
    "CHECKER",
    "CheckedLock",
    "LockCheck",
    "enabled",
    "install_probes",
]

ENV_VAR = "GEOMESA_TPU_LOCKCHECK"


def enabled() -> bool:
    """True when the environment arms the checker (read dynamically --
    but locks already built as plain ``threading.Lock`` stay plain, so
    set the env before the process imports the package)."""
    return os.environ.get(ENV_VAR, "").strip().lower() in (
        "1", "true", "t", "yes", "on",
    )


_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class LockCheck:
    """One acquisition graph + findings store. The module-level
    :data:`CHECKER` is the process-wide one every ``checked_lock`` uses;
    tests build private instances for seeded scenarios."""

    def __init__(self, name: str = "global"):
        self.name = name
        # the checker's own mutex must be invisible to itself
        self._mu = threading.Lock()  # lint: disable=GT001(the checker's internal mutex cannot be a checked lock)
        self._order: "dict[str, set]" = {}  # name -> names acquired after
        self._edges: "dict[tuple, dict]" = {}  # (a, b) -> first context
        self._cycles: list = []
        self._cycle_keys: set = set()
        self._blocking: list = []
        self._blocking_keys: set = set()
        self._locks: set = set()
        self.acquisitions = 0

    # -- recording (called by CheckedLock / the probes) --------------------

    def _register(self, lock: "CheckedLock") -> None:
        with self._mu:
            self._locks.add(lock.name)

    def _on_acquired(self, lock: "CheckedLock") -> None:
        held = _held()
        self.acquisitions += 1
        if held:
            thread = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h.checker is not self or h.name == lock.name:
                        continue  # cross-checker pairs never mix reports
                    key = (h.name, lock.name)
                    if key in self._edges:
                        continue
                    self._edges[key] = {"thread": thread}
                    self._order.setdefault(h.name, set()).add(lock.name)
                    cycle = self._find_path(lock.name, h.name)
                    if cycle:
                        self._record_cycle(cycle + [lock.name], thread)
        held.append(lock)

    def _on_released(self, lock: "CheckedLock") -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _find_path(self, start: str, target: str) -> "list | None":
        """A path start ->* target in the order graph (callers hold
        ``_mu``). Non-None means the new edge target->start... closed a
        cycle; returns the path for the report."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == target:
                return path
            for nxt in self._order.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _record_cycle(self, path: list, thread: str) -> None:
        key = frozenset(path)
        if key in self._cycle_keys:
            return
        self._cycle_keys.add(key)
        self._cycles.append(
            {
                "locks": list(path),
                "thread": thread,
                "edges": {
                    f"{a}->{b}": self._edges.get((a, b), {}).get("thread")
                    for a, b in zip(path, path[1:])
                },
            }
        )

    def _record_blocking(self, lock: "CheckedLock", op: str, detail: str) -> None:
        key = (lock.name, op)
        with self._mu:
            if key in self._blocking_keys:
                return
            self._blocking_keys.add(key)
            self._blocking.append(
                {
                    "lock": lock.name,
                    "op": op,
                    "detail": detail,
                    "thread": threading.current_thread().name,
                }
            )

    # -- read side ---------------------------------------------------------

    def report(self) -> dict:
        """The findings document: registered locks, order-edge count,
        lock-order cycles (ABBA potentials) and held-across-blocking
        events. Also pushes the ``geomesa_lockcheck_*`` gauges."""
        with self._mu:
            doc = {
                "checker": self.name,
                "acquisitions": int(self.acquisitions),
                "locks": sorted(self._locks),
                "edges": sorted(f"{a}->{b}" for a, b in self._edges),
                "cycles": [dict(c) for c in self._cycles],
                "blocking": [dict(b) for b in self._blocking],
            }
        self._publish(doc)
        return doc

    def _publish(self, doc: dict) -> None:
        if self is not CHECKER:
            return  # private (seeded-test) checkers stay off the metrics
        try:
            from geomesa_tpu import metrics

            metrics.lockcheck_locks.set(len(doc["locks"]))
            metrics.lockcheck_edges.set(len(doc["edges"]))
            metrics.lockcheck_cycles.set(len(doc["cycles"]))
            metrics.lockcheck_blocking.set(len(doc["blocking"]))
        except Exception:  # pragma: no cover - observability must not break
            pass

    def clear(self) -> None:
        with self._mu:
            self._order.clear()
            self._edges.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
            self._blocking.clear()
            self._blocking_keys.clear()
            self.acquisitions = 0


CHECKER = LockCheck()


class CheckedLock:
    """Instrumented drop-in for ``threading.Lock`` / ``RLock``
    (``reentrant=True``). ``blocking_ok`` exempts the lock from
    held-across-blocking events (NOT from cycle detection) -- for locks
    whose purpose is to order blocking writes."""

    __slots__ = ("name", "checker", "blocking_ok", "reentrant", "_lock")

    def __init__(
        self,
        name: str,
        checker: "LockCheck | None" = None,
        reentrant: bool = False,
        blocking_ok: bool = False,
    ):
        self.name = name
        self.checker = checker if checker is not None else CHECKER
        self.blocking_ok = blocking_ok
        self.reentrant = reentrant
        self._lock = (
            threading.RLock() if reentrant else threading.Lock()  # lint: disable=GT001(this IS the checked factory's backing lock)
        )
        self.checker._register(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self.checker._on_acquired(self)
        return ok

    def release(self) -> None:
        self.checker._on_released(self)
        self._lock.release()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name!r} reentrant={self.reentrant}>"


# -- blocking-call probes ----------------------------------------------------

_probes_installed = False
_orig: dict = {}


def _note_blocking(op: str, detail: str = "") -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return  # the fast path: virtually every call in the process
    for lock in held:
        if not lock.blocking_ok:
            lock.checker._record_blocking(lock, op, str(detail)[:120])


def install_probes() -> None:
    """Wrap the blocking primitives (idempotent). Each wrapper is a
    thread-local-read when no checked lock is held, so the patched
    process stays test-suite fast."""
    global _probes_installed
    if _probes_installed:
        return
    _probes_installed = True
    import builtins
    import queue as _queue
    import time as _time

    _orig["open"] = builtins.open
    _orig["sleep"] = _time.sleep
    _orig["fsync"] = os.fsync
    _orig["replace"] = os.replace
    _orig["queue_get"] = _queue.Queue.get

    def open_probe(file, *a, **k):
        _note_blocking("open", file)
        return _orig["open"](file, *a, **k)

    def sleep_probe(secs):
        _note_blocking("time.sleep", secs)
        return _orig["sleep"](secs)

    def fsync_probe(fd):
        _note_blocking("os.fsync", fd)
        return _orig["fsync"](fd)

    def replace_probe(src, dst, *a, **k):
        _note_blocking("os.replace", dst)
        return _orig["replace"](src, dst, *a, **k)

    def queue_get_probe(self, block=True, timeout=None):
        if block:
            _note_blocking("queue.get")
        return _orig["queue_get"](self, block, timeout)

    builtins.open = open_probe
    _time.sleep = sleep_probe
    os.fsync = fsync_probe
    os.replace = replace_probe
    _queue.Queue.get = queue_get_probe


def uninstall_probes() -> None:
    """Restore the wrapped primitives (test hygiene only)."""
    global _probes_installed
    if not _probes_installed:
        return
    import builtins
    import queue as _queue
    import time as _time

    builtins.open = _orig["open"]
    _time.sleep = _orig["sleep"]
    os.fsync = _orig["fsync"]
    os.replace = _orig["replace"]
    _queue.Queue.get = _orig["queue_get"]
    _probes_installed = False
