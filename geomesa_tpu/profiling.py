"""Timing + tracing instrumentation.

Ref role: geomesa-utils MethodProfiling.profile(...) wrappers (debug-log
timings around planning/scan phases) and the ``explain`` output as the
de-facto query profiler [UNVERIFIED - empty reference mount]; SURVEY.md
section 5 maps these to ``jax.profiler`` traces plus host-side timers.

- :func:`profile` -- context manager / decorator accumulating wall-time
  per label into a process-wide registry (the MethodProfiling analog)
- :func:`timings` / :func:`reset` -- read back / clear the registry
- :func:`device_trace` -- wrap a block in a ``jax.profiler`` trace dump
  (TensorBoard-loadable) for kernel-level inspection
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field

from geomesa_tpu.locking import checked_lock


@dataclass
class _Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def observe(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)


@dataclass
class _Registry:
    timers: dict = field(default_factory=lambda: defaultdict(_Timer))
    lock: object = field(
        default_factory=lambda: checked_lock("profiling.registry")
    )


_REG = _Registry()


@contextmanager
def profile(label: str):
    """``with profile("planning"): ...`` -- accumulate wall time under a
    label. Nestable and thread-safe; negligible overhead when unused."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _REG.lock:
            _REG.timers[label].observe(dt)


def profiled(label: "str | None" = None):
    """Decorator form of :func:`profile`."""

    def deco(fn):
        import functools

        name = label or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with profile(name):
                return fn(*a, **kw)

        return wrapper

    return deco


def timings() -> dict:
    """label -> {count, total_ms, mean_ms, max_ms} snapshot."""
    with _REG.lock:
        return {
            label: {
                "count": t.count,
                "total_ms": round(t.total_s * 1e3, 3),
                "mean_ms": round(t.total_s / t.count * 1e3, 3) if t.count else 0.0,
                "max_ms": round(t.max_s * 1e3, 3),
            }
            for label, t in _REG.timers.items()
        }


def reset() -> None:
    with _REG.lock:
        _REG.timers.clear()


def report() -> str:
    """Human-readable table of accumulated timings."""
    rows = sorted(timings().items(), key=lambda kv: -kv[1]["total_ms"])
    if not rows:
        return "(no profile data)"
    out = [f"{'label':<40} {'count':>7} {'total ms':>10} {'mean ms':>9} {'max ms':>9}"]
    for label, t in rows:
        out.append(
            f"{label:<40} {t['count']:>7} {t['total_ms']:>10.1f} "
            f"{t['mean_ms']:>9.2f} {t['max_ms']:>9.2f}"
        )
    return "\n".join(out)


@contextmanager
def device_trace(log_dir: str):
    """Dump a jax.profiler trace for the enclosed block (kernel timings,
    HBM traffic; open with TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
