"""Minimal WKT reader/writer for the geometry subset.

Supports POINT, LINESTRING, POLYGON, MULTIPOINT, MULTILINESTRING,
MULTIPOLYGON and GeoTools' ENVELOPE(x1, x2, y1, y2) extension (note the
GeoTools argument order: xmin, xmax, ymin, ymax -- used by CQL BBOX
literals). (ref: geomesa-utils .../text/WKTUtils [UNVERIFIED].)
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

_TOKEN = re.compile(r"\s*([A-Za-z]+|\(|\)|,|-?\d+\.?\d*(?:[eE][-+]?\d+)?)")


class _Tokens:
    def __init__(self, s: str):
        self.toks = _TOKEN.findall(s)
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of WKT")
        self.i += 1
        return t

    def expect(self, t):
        got = self.next()
        if got != t:
            raise ValueError(f"expected {t!r}, got {got!r}")


def _number(tk: _Tokens) -> float:
    return float(tk.next())


def _coord_seq(tk: _Tokens) -> np.ndarray:
    tk.expect("(")
    coords = []
    while True:
        x = _number(tk)
        y = _number(tk)
        coords.append((x, y))
        t = tk.next()
        if t == ")":
            break
        if t != ",":
            raise ValueError(f"bad coordinate separator {t!r}")
    return np.array(coords, dtype=np.float64)


def _rings(tk: _Tokens) -> list[np.ndarray]:
    tk.expect("(")
    rings = [_coord_seq(tk)]
    while tk.peek() == ",":
        tk.next()
        rings.append(_coord_seq(tk))
    tk.expect(")")
    return rings


def parse_wkt(s: str) -> Geometry | Envelope:
    tk = _Tokens(s)
    tag = tk.next().upper()
    if tag == "POINT":
        c = _coord_seq(tk)
        return Point(float(c[0, 0]), float(c[0, 1]))
    if tag == "LINESTRING":
        return LineString(_coord_seq(tk))
    if tag == "POLYGON":
        rings = _rings(tk)
        return Polygon(rings[0], tuple(rings[1:]))
    if tag == "MULTIPOINT":
        # both MULTIPOINT(1 2, 3 4) and MULTIPOINT((1 2), (3 4)) appear
        tk.expect("(")
        pts = []
        while True:
            if tk.peek() == "(":
                c = _coord_seq(tk)
                pts.append(Point(float(c[0, 0]), float(c[0, 1])))
            else:
                pts.append(Point(_number(tk), _number(tk)))
            t = tk.next()
            if t == ")":
                break
            if t != ",":
                raise ValueError(f"bad separator {t!r}")
        return MultiPoint(tuple(pts))
    if tag == "MULTILINESTRING":
        return MultiLineString(tuple(LineString(r) for r in _rings(tk)))
    if tag == "MULTIPOLYGON":
        tk.expect("(")
        polys = [Polygon(r[0], tuple(r[1:])) for r in [_rings(tk)]]
        while tk.peek() == ",":
            tk.next()
            r = _rings(tk)
            polys.append(Polygon(r[0], tuple(r[1:])))
        tk.expect(")")
        return MultiPolygon(tuple(polys))
    if tag == "ENVELOPE":
        tk.expect("(")
        x1 = _number(tk)
        tk.expect(",")
        x2 = _number(tk)
        tk.expect(",")
        y1 = _number(tk)
        tk.expect(",")
        y2 = _number(tk)
        tk.expect(")")
        return Envelope(x1, y1, x2, y2)
    raise ValueError(f"unsupported WKT type {tag!r}")


def _fmt(v: float) -> str:
    return f"{v:.10g}"


def _seq_wkt(coords: np.ndarray) -> str:
    return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in coords) + ")"


def to_wkt(g) -> str:
    if isinstance(g, Point):
        return f"POINT ({_fmt(g.x)} {_fmt(g.y)})"
    if isinstance(g, LineString):
        return "LINESTRING " + _seq_wkt(g.coords)
    if isinstance(g, Polygon):
        return "POLYGON (" + ", ".join(_seq_wkt(r) for r in g.rings()) + ")"
    if isinstance(g, MultiPoint):
        return "MULTIPOINT (" + ", ".join(
            f"({_fmt(p.x)} {_fmt(p.y)})" for p in g.points
        ) + ")"
    if isinstance(g, MultiLineString):
        return "MULTILINESTRING (" + ", ".join(_seq_wkt(l.coords) for l in g.lines) + ")"
    if isinstance(g, MultiPolygon):
        return "MULTIPOLYGON (" + ", ".join(
            "(" + ", ".join(_seq_wkt(r) for r in p.rings()) + ")" for p in g.polygons
        ) + ")"
    if isinstance(g, Envelope):
        return (
            f"ENVELOPE ({_fmt(g.xmin)}, {_fmt(g.xmax)}, {_fmt(g.ymin)}, {_fmt(g.ymax)})"
        )
    raise TypeError(f"cannot write WKT for {type(g)}")
