"""GeoHash encode/decode (ref: geomesa-utils .../geohash/ -- GeoHash
class, base-32 text codec, bbox coverage helpers [UNVERIFIED - empty
reference mount]).

A geohash is an interleaved lon/lat binary prefix rendered in base-32 --
the same bit-interleave family as the Z2 curve (curves/zorder.py), so the
vectorized encoder reuses the Morton spread and just re-chunks bits into
5-bit base-32 glyphs. Encoding is vectorized over numpy arrays; decode
returns the cell center plus error bounds like the reference.
"""

from __future__ import annotations

import numpy as np

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_DECODE = {c: i for i, c in enumerate(_BASE32)}


def encode(lon, lat, precision: int = 9):
    """Vectorized geohash of (lon, lat) -> array of strings (or one str
    for scalars) at the given character precision (5 bits/char)."""
    scalar = np.isscalar(lon) and np.isscalar(lat)
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    nbits = precision * 5
    lon_bits = (nbits + 1) // 2  # even bit positions start with lon
    lat_bits = nbits // 2
    # quantize each dimension to its bit budget
    qlon = _quantize(lon, -180.0, 180.0, lon_bits)
    qlat = _quantize(lat, -90.0, 90.0, lat_bits)
    # interleave: lon gets bits 0,2,4.. (msb-first), lat 1,3,5..
    z = np.zeros(len(lon), dtype=np.uint64)
    for i in range(lon_bits):
        bit = (qlon >> np.uint64(lon_bits - 1 - i)) & np.uint64(1)
        z |= bit << np.uint64(nbits - 1 - 2 * i)
    for i in range(lat_bits):
        bit = (qlat >> np.uint64(lat_bits - 1 - i)) & np.uint64(1)
        z |= bit << np.uint64(nbits - 2 - 2 * i)
    out = np.empty(len(lon), dtype=object)
    for j in range(len(lon)):
        v = int(z[j])
        out[j] = "".join(
            _BASE32[(v >> (nbits - 5 * (k + 1))) & 31] for k in range(precision)
        )
    return out[0] if scalar else out


def _quantize(v: np.ndarray, lo: float, hi: float, bits: int) -> np.ndarray:
    n = np.uint64(1) << np.uint64(bits)
    frac = (np.clip(v, lo, hi) - lo) / (hi - lo)
    q = np.floor(frac * float(n)).astype(np.uint64)
    return np.minimum(q, n - np.uint64(1))


def decode(gh: str):
    """geohash -> (lon, lat) cell center."""
    (lon0, lon1), (lat0, lat1) = decode_bbox(gh)
    return (lon0 + lon1) / 2.0, (lat0 + lat1) / 2.0


def decode_bbox(gh: str):
    """geohash -> ((lonmin, lonmax), (latmin, latmax)) cell bounds."""
    lon0, lon1 = -180.0, 180.0
    lat0, lat1 = -90.0, 90.0
    even = True
    for c in gh.lower():
        try:
            v = _DECODE[c]
        except KeyError:
            raise ValueError(f"invalid geohash character {c!r}") from None
        for k in range(4, -1, -1):
            bit = (v >> k) & 1
            if even:
                mid = (lon0 + lon1) / 2.0
                if bit:
                    lon0 = mid
                else:
                    lon1 = mid
            else:
                mid = (lat0 + lat1) / 2.0
                if bit:
                    lat0 = mid
                else:
                    lat1 = mid
            even = not even
    return (lon0, lon1), (lat0, lat1)


def neighbors(gh: str) -> list:
    """The 8 adjacent cells (clamped at the poles, wrapped at the
    antimeridian), excluding gh itself."""
    (lon0, lon1), (lat0, lat1) = decode_bbox(gh)
    dlon = lon1 - lon0
    dlat = lat1 - lat0
    clon = (lon0 + lon1) / 2.0
    clat = (lat0 + lat1) / 2.0
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            lat = clat + dy * dlat
            if not -90.0 <= lat <= 90.0:
                continue
            lon = clon + dx * dlon
            if lon > 180.0:
                lon -= 360.0
            elif lon < -180.0:
                lon += 360.0
            n = encode(lon, lat, precision=len(gh))
            if n != gh and n not in out:
                out.append(n)
    return out


def bbox_geohashes(
    xmin: float, ymin: float, xmax: float, ymax: float, precision: int
) -> list:
    """All geohash cells at ``precision`` intersecting the bbox (ref
    coverage helper used for geohash-keyed lookups); grid-walks cell
    centers so it is exact, not a prefix approximation."""
    (lon0, lon1), (lat0, lat1) = decode_bbox(encode(xmin, ymin, precision))
    dlon = lon1 - lon0
    dlat = lat1 - lat0
    out = []
    lat = (lat0 + lat1) / 2.0
    while lat < ymax + dlat / 2 and lat <= 90.0:
        lon = (lon0 + lon1) / 2.0
        while lon < xmax + dlon / 2 and lon <= 180.0:
            out.append(encode(lon, lat, precision))
            lon += dlon
        lat += dlat
    return out
