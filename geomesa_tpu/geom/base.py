"""Geometry value types: immutable, numpy-backed coordinate arrays."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np


@dataclass(frozen=True)
class Envelope:
    """Axis-aligned bounding box (inclusive)."""

    xmin: float
    ymin: float
    xmax: float
    ymax: float

    def intersects(self, other: "Envelope") -> bool:
        return not (
            other.xmin > self.xmax
            or other.xmax < self.xmin
            or other.ymin > self.ymax
            or other.ymax < self.ymin
        )

    def contains_env(self, other: "Envelope") -> bool:
        return (
            self.xmin <= other.xmin
            and self.xmax >= other.xmax
            and self.ymin <= other.ymin
            and self.ymax >= other.ymax
        )

    def intersection(self, other: "Envelope") -> "Envelope | None":
        xmin, xmax = max(self.xmin, other.xmin), min(self.xmax, other.xmax)
        ymin, ymax = max(self.ymin, other.ymin), min(self.ymax, other.ymax)
        if xmin > xmax or ymin > ymax:
            return None
        return Envelope(xmin, ymin, xmax, ymax)

    def expand(self, other: "Envelope") -> "Envelope":
        return Envelope(
            min(self.xmin, other.xmin),
            min(self.ymin, other.ymin),
            max(self.xmax, other.xmax),
            max(self.ymax, other.ymax),
        )

    @staticmethod
    def world() -> "Envelope":
        return Envelope(-180.0, -90.0, 180.0, 90.0)


class Geometry:
    """Base class; subclasses expose ``envelope`` and coordinate arrays."""

    @property
    def envelope(self) -> Envelope:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class Point(Geometry):
    x: float
    y: float

    @property
    def envelope(self) -> Envelope:
        return Envelope(self.x, self.y, self.x, self.y)


def _coords_array(coords) -> np.ndarray:
    a = np.asarray(coords, dtype=np.float64)
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError(f"expected (n, 2) coordinates, got {a.shape}")
    return a


@dataclass(frozen=True)
class LineString(Geometry):
    coords: np.ndarray  # (n, 2)

    def __post_init__(self):
        object.__setattr__(self, "coords", _coords_array(self.coords))

    @property
    def envelope(self) -> Envelope:
        c = self.coords
        return Envelope(c[:, 0].min(), c[:, 1].min(), c[:, 0].max(), c[:, 1].max())


@dataclass(frozen=True)
class Polygon(Geometry):
    """Exterior shell plus optional interior rings (holes). Rings are closed
    (first == last coordinate) per WKT convention."""

    shell: np.ndarray  # (n, 2)
    holes: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "shell", _coords_array(self.shell))
        object.__setattr__(
            self, "holes", tuple(_coords_array(h) for h in self.holes)
        )

    @property
    def envelope(self) -> Envelope:
        c = self.shell
        return Envelope(c[:, 0].min(), c[:, 1].min(), c[:, 0].max(), c[:, 1].max())

    def rings(self):
        return (self.shell, *self.holes)


@dataclass(frozen=True)
class MultiPoint(Geometry):
    points: tuple

    @property
    def envelope(self) -> Envelope:
        e = self.points[0].envelope
        for p in self.points[1:]:
            e = e.expand(p.envelope)
        return e


@dataclass(frozen=True)
class MultiLineString(Geometry):
    lines: tuple

    @property
    def envelope(self) -> Envelope:
        e = self.lines[0].envelope
        for l in self.lines[1:]:
            e = e.expand(l.envelope)
        return e


@dataclass(frozen=True)
class MultiPolygon(Geometry):
    polygons: tuple

    @property
    def envelope(self) -> Envelope:
        e = self.polygons[0].envelope
        for p in self.polygons[1:]:
            e = e.expand(p.envelope)
        return e

    def rings(self):
        out = []
        for p in self.polygons:
            out.extend(p.rings())
        return tuple(out)
