"""WKB + TWKB geometry codecs (ref: geomesa-utils WKBUtils and the Kryo
geometry serialization's TWKB-like compact encoding,
KryoGeometrySerialization [UNVERIFIED - empty reference mount]).

WKB follows OGC 99-049 (little-endian by default, both orders read).
TWKB is the compact varint format the reference uses inside Kryo values:
zigzag delta-encoded coordinates at a configurable decimal precision --
typically 4-6x smaller than WKB for tracks and polygons.
"""

from __future__ import annotations

import io
import struct

import numpy as np

from geomesa_tpu.geom.base import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

_WKB_POINT = 1
_WKB_LINESTRING = 2
_WKB_POLYGON = 3
_WKB_MULTIPOINT = 4
_WKB_MULTILINESTRING = 5
_WKB_MULTIPOLYGON = 6


# -- WKB ---------------------------------------------------------------------


def to_wkb(geom: Geometry) -> bytes:
    buf = io.BytesIO()
    _write_wkb(buf, geom)
    return buf.getvalue()


def _write_wkb(buf, geom) -> None:
    buf.write(b"\x01")  # little-endian

    def header(code):
        buf.write(struct.pack("<I", code))

    def coords(arr):
        a = np.asarray(arr, dtype="<f8")
        buf.write(struct.pack("<I", len(a)))
        buf.write(a.tobytes())

    if isinstance(geom, Point):
        header(_WKB_POINT)
        buf.write(struct.pack("<dd", geom.x, geom.y))
    elif isinstance(geom, LineString):
        header(_WKB_LINESTRING)
        coords(geom.coords)
    elif isinstance(geom, Polygon):
        header(_WKB_POLYGON)
        rings = geom.rings()
        buf.write(struct.pack("<I", len(rings)))
        for r in rings:
            coords(r)
    elif isinstance(geom, MultiPoint):
        header(_WKB_MULTIPOINT)
        buf.write(struct.pack("<I", len(geom.points)))
        for p in geom.points:
            _write_wkb(buf, p)
    elif isinstance(geom, MultiLineString):
        header(_WKB_MULTILINESTRING)
        buf.write(struct.pack("<I", len(geom.lines)))
        for l in geom.lines:
            _write_wkb(buf, l)
    elif isinstance(geom, MultiPolygon):
        header(_WKB_MULTIPOLYGON)
        buf.write(struct.pack("<I", len(geom.polygons)))
        for p in geom.polygons:
            _write_wkb(buf, p)
    else:
        raise TypeError(f"cannot WKB-encode {type(geom)}")


def from_wkb(data: "bytes | io.BytesIO") -> Geometry:
    buf = io.BytesIO(data) if isinstance(data, (bytes, bytearray)) else data
    return _read_wkb(buf)


def _read_wkb(buf) -> Geometry:
    bo = buf.read(1)
    end = "<" if bo == b"\x01" else ">"
    (code,) = struct.unpack(end + "I", buf.read(4))
    code &= 0xFF  # strip EWKB/Z flags

    def ncoords():
        (n,) = struct.unpack(end + "I", buf.read(4))
        a = np.frombuffer(buf.read(16 * n), dtype=end + "f8").reshape(n, 2)
        return a.astype(np.float64)

    if code == _WKB_POINT:
        x, y = struct.unpack(end + "dd", buf.read(16))
        return Point(x, y)
    if code == _WKB_LINESTRING:
        return LineString(ncoords())
    if code == _WKB_POLYGON:
        (n,) = struct.unpack(end + "I", buf.read(4))
        rings = [ncoords() for _ in range(n)]
        return Polygon(rings[0], tuple(rings[1:]))
    (n,) = struct.unpack(end + "I", buf.read(4))
    parts = [_read_wkb(buf) for _ in range(n)]
    if code == _WKB_MULTIPOINT:
        return MultiPoint(tuple(parts))
    if code == _WKB_MULTILINESTRING:
        return MultiLineString(tuple(parts))
    if code == _WKB_MULTIPOLYGON:
        return MultiPolygon(tuple(parts))
    raise ValueError(f"unsupported WKB geometry code {code}")


# -- TWKB --------------------------------------------------------------------


def _zz(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzz(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _wv(buf, n: int) -> None:  # unsigned varint
    n &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _rv(buf) -> int:
    shift = acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return acc
        shift += 7


class _DeltaWriter:
    def __init__(self, buf, scale: float):
        self.buf = buf
        self.scale = scale
        self.px = 0
        self.py = 0

    def write(self, arr) -> None:
        a = np.asarray(arr, dtype=np.float64)
        q = np.round(a * self.scale).astype(np.int64)
        _wv(self.buf, len(q))
        for x, y in q:
            _wv(self.buf, _zz(int(x) - self.px))
            _wv(self.buf, _zz(int(y) - self.py))
            self.px, self.py = int(x), int(y)


class _DeltaReader:
    def __init__(self, buf, scale: float):
        self.buf = buf
        self.scale = scale
        self.px = 0
        self.py = 0

    def read(self) -> np.ndarray:
        n = _rv(self.buf)
        out = np.empty((n, 2), dtype=np.float64)
        for i in range(n):
            self.px += _unzz(_rv(self.buf))
            self.py += _unzz(_rv(self.buf))
            out[i] = (self.px / self.scale, self.py / self.scale)
        return out


def to_twkb(geom: Geometry, precision: int = 7) -> bytes:
    """Compact varint encoding; precision = decimal digits kept (7 ~ cm at
    the equator, the reference's default for Kryo geometry payloads)."""
    buf = io.BytesIO()
    code = {
        Point: _WKB_POINT,
        LineString: _WKB_LINESTRING,
        Polygon: _WKB_POLYGON,
        MultiPoint: _WKB_MULTIPOINT,
        MultiLineString: _WKB_MULTILINESTRING,
        MultiPolygon: _WKB_MULTIPOLYGON,
    }[type(geom)]
    buf.write(bytes([code | (precision << 4)]))
    w = _DeltaWriter(buf, 10.0**precision)
    if isinstance(geom, Point):
        w.write([(geom.x, geom.y)])
    elif isinstance(geom, LineString):
        w.write(geom.coords)
    elif isinstance(geom, Polygon):
        _wv(buf, len(geom.rings()))
        for r in geom.rings():
            w.write(r)
    elif isinstance(geom, MultiPoint):
        w.write([(p.x, p.y) for p in geom.points])
    elif isinstance(geom, MultiLineString):
        _wv(buf, len(geom.lines))
        for l in geom.lines:
            w.write(l.coords)
    else:  # MultiPolygon
        _wv(buf, len(geom.polygons))
        for p in geom.polygons:
            _wv(buf, len(p.rings()))
            for r in p.rings():
                w.write(r)
    return buf.getvalue()


def from_twkb(data: bytes) -> Geometry:
    buf = io.BytesIO(data)
    (head,) = buf.read(1)
    code = head & 0x0F
    precision = head >> 4
    r = _DeltaReader(buf, 10.0**precision)
    if code == _WKB_POINT:
        (xy,) = r.read()
        return Point(float(xy[0]), float(xy[1]))
    if code == _WKB_LINESTRING:
        return LineString(r.read())
    if code == _WKB_POLYGON:
        n = _rv(buf)
        rings = [r.read() for _ in range(n)]
        return Polygon(rings[0], tuple(rings[1:]))
    if code == _WKB_MULTIPOINT:
        pts = r.read()
        return MultiPoint(tuple(Point(float(x), float(y)) for x, y in pts))
    if code == _WKB_MULTILINESTRING:
        n = _rv(buf)
        return MultiLineString(tuple(LineString(r.read()) for _ in range(n)))
    if code == _WKB_MULTIPOLYGON:
        n = _rv(buf)
        polys = []
        for _ in range(n):
            m = _rv(buf)
            rings = [r.read() for _ in range(m)]
            polys.append(Polygon(rings[0], tuple(rings[1:])))
        return MultiPolygon(tuple(polys))
    raise ValueError(f"unsupported TWKB geometry code {code}")
