"""GeoJSON geometry codec (ref: geomesa-spark-sql st_geomFromGeoJSON /
st_asGeoJSON UDFs and the GeoTools GeoJSON writers used by export
[UNVERIFIED - empty reference mount])."""

from __future__ import annotations

import json

import numpy as np

from geomesa_tpu.geom.base import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)


def _coords(a: np.ndarray) -> list:
    return [[float(x), float(y)] for x, y in np.asarray(a)]


def to_geojson(g: Geometry) -> dict:
    """Geometry -> GeoJSON geometry dict."""
    if isinstance(g, Point):
        return {"type": "Point", "coordinates": [float(g.x), float(g.y)]}
    if isinstance(g, LineString):
        return {"type": "LineString", "coordinates": _coords(g.coords)}
    if isinstance(g, Polygon):
        return {
            "type": "Polygon",
            "coordinates": [_coords(g.shell)] + [_coords(h) for h in g.holes],
        }
    if isinstance(g, MultiPoint):
        return {
            "type": "MultiPoint",
            "coordinates": [[float(p.x), float(p.y)] for p in g.points],
        }
    if isinstance(g, MultiLineString):
        return {
            "type": "MultiLineString",
            "coordinates": [_coords(l.coords) for l in g.lines],
        }
    if isinstance(g, MultiPolygon):
        return {
            "type": "MultiPolygon",
            "coordinates": [
                [_coords(p.shell)] + [_coords(h) for h in p.holes]
                for p in g.polygons
            ],
        }
    raise ValueError(f"cannot encode {type(g).__name__} as GeoJSON")


def from_geojson(doc) -> Geometry:
    """GeoJSON geometry (dict or JSON string) -> Geometry."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    t = doc.get("type")
    c = doc.get("coordinates")
    if t == "Point":
        return Point(float(c[0]), float(c[1]))
    if t == "LineString":
        return LineString(np.asarray(c, dtype=np.float64))
    if t == "Polygon":
        rings = [np.asarray(r, dtype=np.float64) for r in c]
        return Polygon(rings[0], tuple(rings[1:]))
    if t == "MultiPoint":
        return MultiPoint(tuple(Point(float(p[0]), float(p[1])) for p in c))
    if t == "MultiLineString":
        return MultiLineString(
            tuple(LineString(np.asarray(p, dtype=np.float64)) for p in c)
        )
    if t == "MultiPolygon":
        parts = []
        for rings in c:
            rs = [np.asarray(r, dtype=np.float64) for r in rings]
            parts.append(Polygon(rs[0], tuple(rs[1:])))
        return MultiPolygon(tuple(parts))
    raise ValueError(f"cannot decode GeoJSON type {t!r}")
