"""Polygon boolean operations (intersection / union / difference).

Ref role: the reference gets ``st_intersection`` / ``st_difference`` and
friends from JTS's overlay engine (geomesa-spark-jts [UNVERIFIED - empty
reference mount]). This is a from-scratch Greiner-Hormann clipper:
concave shapes are fine; MultiPolygons distribute over their disjoint
components. All four ops (intersection, union, difference,
symDifference) support holes on either side; difference and union may
CREATE holes/voids in their output (a union that encloses a void routes
through the exact A + (B \\ A) decomposition). The remaining loud
refusals are genuinely pathological: hole-region merges that enclose a
void during subtraction, and multipolygons with a component inside
another component's hole.

Degeneracies (a vertex exactly on the other polygon's edge, collinear
overlapping edges) are handled the standard practical way: the clip
polygon is retried with a deterministic perturbation that starts at
1e-8 of the bbox scale and escalates to 1e-7 on the second retry,
CAPPED there (further retries re-roll at the cap with a new seed).
For geographic data 1e-7 of a bbox span is at most ~cm-scale —
still below meaningful coordinate precision; the test suite validates
results against a Monte-Carlo point-membership oracle built on
points_in_polygon.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geom.base import MultiPolygon, Polygon


class _Node:
    __slots__ = (
        "xy", "next", "prev", "neighbor", "is_inter", "entry", "visited",
        "alpha",
    )

    def __init__(self, xy, alpha=0.0, is_inter=False):
        self.xy = xy
        self.next = None
        self.prev = None
        self.neighbor = None
        self.is_inter = is_inter
        self.entry = False
        self.visited = False
        self.alpha = alpha


def _norm_ring(ring) -> np.ndarray:
    """Closed-or-open ring -> OPEN CCW-normalized float64 ring."""
    c = np.asarray(ring, np.float64)
    if np.array_equal(c[0], c[-1]):
        c = c[:-1]
    area2 = np.sum(c[:, 0] * np.roll(c[:, 1], -1) - np.roll(c[:, 0], -1) * c[:, 1])
    if area2 < 0:
        c = c[::-1]
    return c


def _ring_of(poly: Polygon) -> np.ndarray:
    rings = list(poly.rings())
    if len(rings) > 1:
        raise NotImplementedError(
            "this polygon boolean op does not support holes (v1); "
            "intersection does — or subtract the holes explicitly"
        )
    return _norm_ring(rings[0])


def _components(g) -> list:
    """(Multi)Polygon -> [(open shell ring, [open hole rings...]), ...]."""
    out = []
    for p in _as_polys(g):
        rings = list(p.rings())
        out.append((
            _norm_ring(rings[0]), [_norm_ring(h) for h in rings[1:]]
        ))
    return out


def _build_list(ring: np.ndarray) -> _Node:
    nodes = [_Node(tuple(p)) for p in ring]
    for i, nd in enumerate(nodes):
        nd.next = nodes[(i + 1) % len(nodes)]
        nd.prev = nodes[i - 1]
    return nodes[0]


def _vertices(head: _Node):
    n = head
    while True:
        yield n
        n = n.next
        if n is head:
            break


def _orig_edges(head: _Node):
    """(node, next_original_node) pairs over the ORIGINAL polygon edges."""
    orig = [n for n in _vertices(head) if not n.is_inter]
    for i, a in enumerate(orig):
        yield a, orig[(i + 1) % len(orig)]


def _seg_inter(p1, p2, q1, q2):
    """(t, u) of the proper crossing of segments p1p2 and q1q2, or None.
    Returns None for parallel/degenerate configurations (endpoint
    touches are 'degenerate' and trigger the perturbation retry)."""
    r = (p2[0] - p1[0], p2[1] - p1[1])
    s = (q2[0] - q1[0], q2[1] - q1[1])
    rxs = r[0] * s[1] - r[1] * s[0]
    if rxs == 0:
        qp = (q1[0] - p1[0], q1[1] - p1[1])
        if qp[0] * r[1] - qp[1] * r[0] == 0:
            # collinear: overlap is degenerate, separation is a miss
            return "degenerate" if _collinear_overlap(p1, p2, q1, q2) else None
        return None
    qp = (q1[0] - p1[0], q1[1] - p1[1])
    t = (qp[0] * s[1] - qp[1] * s[0]) / rxs
    u = (qp[0] * r[1] - qp[1] * r[0]) / rxs
    eps = 1e-13
    if -eps < t < eps or 1 - eps < t < 1 + eps or \
       -eps < u < eps or 1 - eps < u < 1 + eps:
        if -eps < t < 1 + eps and -eps < u < 1 + eps:
            return "degenerate"  # endpoint on the other segment
        return None
    if 0 < t < 1 and 0 < u < 1:
        return (t, u)
    return None


def _collinear_overlap(p1, p2, q1, q2) -> bool:
    if p1[0] == p2[0]:  # vertical: compare on y
        a = sorted((p1[1], p2[1]))
        b = sorted((q1[1], q2[1]))
    else:
        a = sorted((p1[0], p2[0]))
        b = sorted((q1[0], q2[0]))
    return a[0] < b[1] and b[0] < a[1]


def _point_in_ring(pt, ring: np.ndarray) -> bool:
    from geomesa_tpu.geom.predicates import points_in_polygon

    closed = np.concatenate([ring, ring[:1]], axis=0)
    return bool(
        points_in_polygon(
            np.array([pt[0]]), np.array([pt[1]]), [closed]
        )[0]
    )


def _insert_intersections(head_a: _Node, head_b: _Node) -> int:
    """Find all proper crossings, link neighbor nodes. Returns the count;
    raises _Degenerate on non-generic configurations."""
    count = 0
    for a1, a2 in list(_orig_edges(head_a)):
        for b1, b2 in list(_orig_edges(head_b)):
            got = _seg_inter(a1.xy, a2.xy, b1.xy, b2.xy)
            if got is None:
                continue
            if got == "degenerate":
                raise _Degenerate()
            t, u = got
            xy = (
                a1.xy[0] + t * (a2.xy[0] - a1.xy[0]),
                a1.xy[1] + t * (a2.xy[1] - a1.xy[1]),
            )
            na = _Node(xy, alpha=t, is_inter=True)
            nb = _Node(xy, alpha=u, is_inter=True)
            na.neighbor = nb
            nb.neighbor = na
            _insert_sorted(a1, a2, na)
            _insert_sorted(b1, b2, nb)
            count += 1
    return count


class _Degenerate(Exception):
    pass


def _insert_sorted(start: _Node, end_orig: _Node, node: _Node) -> None:
    """Insert an intersection node between two ORIGINAL vertices, keeping
    intersection nodes ordered by alpha."""
    cur = start
    while (
        cur.next is not end_orig
        and cur.next.is_inter
        and cur.next.alpha < node.alpha
    ):
        cur = cur.next
    node.next = cur.next
    node.prev = cur
    cur.next.prev = node
    cur.next = node


def _mark_entries(head: _Node, other_ring: np.ndarray, invert: bool) -> None:
    """Classic GH phase 2: walking the polygon, each crossing toggles
    containment in the other polygon; a node is an ENTRY if we were
    outside before crossing (XOR ``invert`` for union/difference)."""
    inside = _point_in_ring(head.xy, other_ring)
    entry = not inside
    for n in _vertices(head):
        if n.is_inter:
            n.entry = entry ^ invert
            entry = not entry


def _traverse(head_a: _Node) -> list:
    """GH phase 3: walk unvisited intersection nodes into result rings."""
    rings = []
    inters = [n for n in _vertices(head_a) if n.is_inter]
    for start in inters:
        if start.visited:
            continue
        ring = []
        cur = start
        while not cur.visited:
            cur.visited = True
            cur.neighbor.visited = True
            ring.append(cur.xy)
            if cur.entry:
                nxt = cur.next
                while not nxt.is_inter:
                    ring.append(nxt.xy)
                    nxt = nxt.next
            else:
                nxt = cur.prev
                while not nxt.is_inter:
                    ring.append(nxt.xy)
                    nxt = nxt.prev
            cur = nxt.neighbor
        if len(ring) >= 3:
            rings.append(np.array(ring + [ring[0]], np.float64))
    return rings


def _clip_once(ra: np.ndarray, rb: np.ndarray, op: str):
    head_a = _build_list(ra)
    head_b = _build_list(rb)
    n_inter = _insert_intersections(head_a, head_b)
    if n_inter == 0:
        a_in_b = _point_in_ring(ra[0], rb)
        b_in_a = _point_in_ring(rb[0], ra)
        if op == "intersection":
            if a_in_b:
                return [np.concatenate([ra, ra[:1]])]
            if b_in_a:
                return [np.concatenate([rb, rb[:1]])]
            return []
        if op == "union":
            if a_in_b:
                return [np.concatenate([rb, rb[:1]])]
            if b_in_a:
                return [np.concatenate([ra, ra[:1]])]
            return [np.concatenate([ra, ra[:1]]),
                    np.concatenate([rb, rb[:1]])]
        # difference a - b
        if a_in_b:
            return []
        if b_in_a:
            raise NotImplementedError(
                "difference would create a hole (clip polygon strictly "
                "inside the subject); holes are unsupported in v1"
            )
        return [np.concatenate([ra, ra[:1]])]
    # entry-mark inversion table (Kim & Kim formulation): intersection
    # marks both normally; union inverts both; difference inverts the
    # SUBJECT's marks (flipping the walk direction along A is equivalent
    # to clipping A against B's reversed ring — validated against the
    # Monte-Carlo membership oracle in tests/test_clip.py)
    inv_a, inv_b = {
        "intersection": (False, False),
        "union": (True, True),
        "difference": (True, False),
    }[op]
    _mark_entries(head_a, rb, inv_a)
    _mark_entries(head_b, ra, inv_b)
    return _traverse(head_a)


def _perturb(ring: np.ndarray, k: int, scale: float) -> np.ndarray:
    rng = np.random.default_rng(0xC11F + k)
    return ring + (rng.random(ring.shape) - 0.5) * scale


def clip_rings(ra: np.ndarray, rb: np.ndarray, op: str) -> list:
    """Boolean op over two simple open rings -> list of closed rings.
    Retries with a deterministic perturbation of the clip ring on
    degenerate (vertex-on-edge / collinear-overlap) inputs, escalating
    1e-8 -> 1e-7 of the bbox span (capped; later retries re-roll at the
    cap with a fresh seed). The scale is floored at a few ULP of the
    coordinate MAGNITUDE — a small polygon far from the origin (e.g.
    EPSG:3857 metres) would otherwise round the perturbation away
    entirely and retry the identical degenerate input."""
    span = max(
        float(np.ptp(ra[:, 0])), float(np.ptp(ra[:, 1])),
        float(np.ptp(rb[:, 0])), float(np.ptp(rb[:, 1])), 1e-9,
    )
    mag = max(
        float(np.abs(ra).max()), float(np.abs(rb).max()), 1.0
    )
    base = max(span * 1e-9, float(np.spacing(mag)) * 4)
    for k in range(6):
        try:
            return _clip_once(ra, rb if k == 0 else _perturb(
                rb, k, base * (10 ** min(k, 2))
            ), op)
        except _Degenerate:
            continue
    raise ValueError(
        "polygon boolean op did not reach a generic configuration after "
        "perturbation retries"
    )


def _as_polys(g):
    if isinstance(g, Polygon):
        return [g]
    if isinstance(g, MultiPolygon):
        return list(g.polygons)
    raise ValueError(
        f"polygon boolean ops need (Multi)Polygon, got {type(g).__name__}"
    )


def _wrap_parts(parts: list):
    """[(closed ring, [closed holes...])] -> (Multi)Polygon; one policy
    for the empty/single/multi wrapping across every op."""
    polys = [
        Polygon(r, tuple(hs)) if hs else Polygon(r)
        for r, hs in parts
        if abs(_ring_area2(r)) > 0
    ]
    if not polys:
        return MultiPolygon(())
    if len(polys) == 1:
        return polys[0]
    return MultiPolygon(tuple(polys))


def _wrap(rings: list):
    return _wrap_parts([(r, []) for r in rings])


def _ring_area2(r: np.ndarray) -> float:
    return float(
        np.sum(r[:-1, 0] * r[1:, 1] - r[1:, 0] * r[:-1, 1])
    )


def _merge_regions(regions: list) -> list:
    """Fold possibly-overlapping simple regions (open rings) into disjoint
    ones via pairwise union. A union whose pieces nest (two horseshoes
    closing a void) is refused — that topology needs full hole-aware
    union."""
    merged: list = []  # open rings, pairwise disjoint
    for h in regions:
        cur = h
        out = []
        for ex in merged:
            got = clip_rings(ex, cur, "union")
            if len(got) == 1:
                cur = _norm_ring(got[0])  # overlapped: fold and continue
                continue
            # 2+ rings: either genuinely disjoint inputs, or an
            # interlocking union that ENCLOSED A VOID (two horseshoes) —
            # the void ring nests inside the outer ring. The nested case
            # must refuse: emitting both rings as "holes" would
            # double-count the void under even-odd membership.
            for g1 in got:
                for g2 in got:
                    if g1 is not g2 and _point_in_ring(
                        _norm_ring(g1)[0], _norm_ring(g2)
                    ):
                        raise NotImplementedError(
                            "merged hole regions enclose a void "
                            "(interlocking union); this topology is "
                            "not supported"
                        )
            out.append(ex)  # disjoint: keep apart
        out.append(cur)
        merged = out
    return merged


def _subtract_regions(rings: list, regions: list) -> list:
    """Closed simple rings minus disjoint simple regions (open rings) ->
    [(closed shell, [closed holes...])]. Regions crossing a ring's
    boundary trim/split it; regions strictly inside attach as holes;
    disjoint regions are no-ops — all three cases fall out of the
    simple-ring difference (whose 'would create a hole' refusal IS the
    attach signal)."""
    pieces = list(rings)
    pending: list = []
    for h in regions:
        nxt = []
        for r in pieces:
            try:
                # re-normalize: traversal outputs carry arbitrary
                # orientation, the clip contract wants CCW open rings
                nxt.extend(clip_rings(_norm_ring(r), h, "difference"))
            except NotImplementedError:
                nxt.append(r)  # strictly inside: attach after splitting
                pending.append(h)
        pieces = nxt
    out = []
    for r in pieces:
        holes = [
            np.concatenate([h, h[:1]])
            for h in pending
            if _point_in_ring(h[0], r[:-1])
        ]
        out.append((r, holes))
    return out


def polygon_intersection(a, b):
    """A ∩ B over (Multi)Polygons, WITH hole support: per component pair
    the shells intersect via Greiner-Hormann, then both sides' hole
    regions (merged where they overlap) subtract from the result —
    crossing holes trim the rings, contained holes carry through as
    holes of the output. Multipolygon components distribute (parts are
    disjoint by construction)."""
    parts = []
    comps_b = _components(b)
    merged_cache: dict = {}
    for i, (sa, ha) in enumerate(_components(a)):
        for j, (sb, hb) in enumerate(comps_b):
            got = clip_rings(sa, sb, "intersection")
            if not got:
                continue
            if ha or hb:
                if (i, j) not in merged_cache:
                    merged_cache[(i, j)] = _merge_regions(ha + hb)
                holes = merged_cache[(i, j)]
            else:
                holes = []
            parts += _subtract_regions(got, holes)
    return _wrap_parts(parts)


def _union_via_difference(a, b):
    """A ∪ B as A + (B \\ A): pieces have pairwise disjoint INTERIORS by
    construction (they may touch along A's boundary), so membership and
    area are exact for any topology the hole-aware difference accepts —
    including unions that enclose a void and holed inputs. The trade-off
    is aesthetic: an overlapping pair yields two touching components
    instead of one merged ring."""
    parts = []
    for g in (a, polygon_difference(b, a)):
        if _is_empty(g):
            continue
        for shell, holes in _components(g):
            parts.append((
                np.concatenate([shell, shell[:1]]),
                [np.concatenate([h, h[:1]]) for h in holes],
            ))
    return _wrap_parts(parts)


def polygon_union(a, b):
    """A ∪ B. Simple inputs fold pairwise through the Greiner-Hormann
    union (one merged ring where shapes overlap); holed inputs — and
    simple pairs whose union ENCLOSES A VOID (interlocking horseshoes,
    where the fold would silently emit overlapping rings) — route
    through the exact disjoint decomposition A + (B \\ A)."""
    comps_a = _components(a)
    comps_b = _components(b)
    if any(h for _, h in comps_a) or any(h for _, h in comps_b):
        return _union_via_difference(a, b)
    parts = [s for s, _ in comps_a]
    for rb, _ in comps_b:
        merged = False
        out = []
        for ra in parts:
            if not merged:
                got = clip_rings(ra, rb, "union")
                if len(got) == 1:
                    rb = _norm_ring(got[0])  # merged: keep folding
                    merged = True
                    continue
                # 2+ rings: disjoint inputs, OR an interlocking union
                # that enclosed a void (one output ring nests inside
                # another) — the fold cannot represent that; use the
                # exact decomposition for the whole operation
                for g1 in got:
                    for g2 in got:
                        if g1 is not g2 and _point_in_ring(
                            _norm_ring(g1)[0], _norm_ring(g2)
                        ):
                            return _union_via_difference(a, b)
            out.append(ra)
        out.append(rb)
        parts = out
    return _wrap([np.concatenate([r, r[:1]]) for r in parts])


def _check_no_island_in_hole(comps: list) -> None:
    """Refuse multipolygons where one component sits inside another
    component's hole (donut-with-island): the difference decomposition's
    hole add-back would resurrect the island's area."""
    for j, (_, hj) in enumerate(comps):
        for k, (sk, _) in enumerate(comps):
            if j == k:
                continue
            for h in hj:
                if _point_in_ring(sk[0], h):
                    raise NotImplementedError(
                        "a multipolygon component lies inside another "
                        "component's hole; this topology is not supported"
                    )


def polygon_difference(a, b):
    """A \\ B, WITH hole support on both sides.

    Decomposition (all pieces pairwise disjoint, so no degenerate
    adjacencies): since B = ∪_j (shell_j − holes_j),

        A \\ B  =  (shell_A − merge(holes_A ∪ shells_B))  ∪
                   (A ∩ holes_B)

    — the first term over-subtracts B's full shells, the second adds
    back what survives inside B's holes (a holed INTERSECTION, already
    supported). Component-inside-another's-hole multipolygons refuse.
    """
    comps_a = _components(a)
    comps_b = _components(b)
    _check_no_island_in_hole(comps_a)
    _check_no_island_in_hole(comps_b)
    parts = []
    shells_b = [sb for sb, _ in comps_b]
    for sa, ha in comps_a:
        merged = _merge_regions(list(ha) + shells_b)
        parts += _subtract_regions(
            [np.concatenate([sa, sa[:1]])], merged
        )
    for sb, hb in comps_b:
        for h in hb:
            got = polygon_intersection(
                a, Polygon(np.concatenate([h, h[:1]]))
            )
            parts += [
                (np.asarray(list(p.rings())[0], np.float64),
                 [np.asarray(r, np.float64) for r in list(p.rings())[1:]])
                for p in _as_polys(got)
            ]
    return _wrap_parts(parts)


def polygon_sym_difference(a, b):
    """(A \\ B) ∪ (B \\ A) — returned as the (possibly Multi) collection
    of both directional differences (they are disjoint by construction;
    holes on either input ride through the hole-aware difference)."""
    parts = []
    for g in (polygon_difference(a, b), polygon_difference(b, a)):
        if _is_empty(g):
            continue
        for shell, holes in _components(g):
            parts.append((
                np.concatenate([shell, shell[:1]]),
                [np.concatenate([h, h[:1]]) for h in holes],
            ))
    return _wrap_parts(parts)


def _is_empty(g) -> bool:
    return isinstance(g, MultiPolygon) and len(g.polygons) == 0
