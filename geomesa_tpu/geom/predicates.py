"""Vectorized geometry predicates.

The device-side predicate set (SURVEY.md section 7 hard part #3): bbox
compare is trivial columnar math; point-in-polygon uses the crossing-number
test over packed edge lists, identical semantics host (numpy) and device
(jax). Boundary behavior: points exactly on a horizontal-crossing vertex
follow the half-open rule (a vertex counts for the edge whose y-interval is
[min, max)); points on edges may test either way at float precision -- same
caveat as JTS's RayCrossingCounter fast path.
"""

from __future__ import annotations

import numpy as np


def polygon_edges(rings) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack closed rings into edge arrays (x1, y1, x2, y2)."""
    x1, y1, x2, y2 = [], [], [], []
    for ring in rings:
        r = np.asarray(ring, dtype=np.float64)
        a = r[:-1]
        b = r[1:]
        x1.append(a[:, 0])
        y1.append(a[:, 1])
        x2.append(b[:, 0])
        y2.append(b[:, 1])
    return (
        np.concatenate(x1),
        np.concatenate(y1),
        np.concatenate(x2),
        np.concatenate(y2),
    )


def points_in_polygon(px, py, rings) -> np.ndarray:
    """Crossing-number containment for (n,) point arrays against a polygon
    given as closed rings (shell + holes: odd crossings = inside)."""
    x1, y1, x2, y2 = polygon_edges(rings)
    px = np.asarray(px, dtype=np.float64)[:, None]
    py = np.asarray(py, dtype=np.float64)[:, None]
    # edge straddles the horizontal ray (half-open to dodge vertex double count)
    straddle = (y1[None, :] > py) != (y2[None, :] > py)
    with np.errstate(divide="ignore", invalid="ignore"):
        xint = x1 + (py - y1) * (x2 - x1) / (y2 - y1)
    crossing = straddle & (px < xint)
    return crossing.sum(axis=1) % 2 == 1


def points_in_polygon_jax(px, py, rings):
    """Same crossing-number test on device. Edge list is packed host-side;
    px/py are device arrays."""
    import jax.numpy as jnp

    x1, y1, x2, y2 = polygon_edges(rings)
    x1 = jnp.asarray(x1, dtype=px.dtype)
    y1 = jnp.asarray(y1, dtype=px.dtype)
    x2 = jnp.asarray(x2, dtype=px.dtype)
    y2 = jnp.asarray(y2, dtype=px.dtype)
    pxc = px[:, None]
    pyc = py[:, None]
    straddle = (y1[None, :] > pyc) != (y2[None, :] > pyc)
    denom = y2 - y1
    denom = jnp.where(denom == 0, 1.0, denom)  # straddle==False masks these
    xint = x1 + (pyc - y1) * (x2 - x1) / denom
    crossings = jnp.sum(straddle & (pxc < xint), axis=1)
    return crossings % 2 == 1


def _segments_of(geom) -> "np.ndarray | None":
    """(m, 4) [x1, y1, x2, y2] segment array for a line/polygon geometry."""
    from geomesa_tpu.geom.base import (
        LineString,
        MultiLineString,
        MultiPolygon,
        Polygon,
    )

    if isinstance(geom, LineString):
        c = geom.coords
        return np.concatenate([c[:-1], c[1:]], axis=1)
    if isinstance(geom, (Polygon, MultiPolygon)):
        x1, y1, x2, y2 = polygon_edges(geom.rings())
        return np.stack([x1, y1, x2, y2], axis=1)
    if isinstance(geom, MultiLineString):
        return np.concatenate([_segments_of(l) for l in geom.lines], axis=0)
    return None


def _expand_pairs(sa: np.ndarray, sb: np.ndarray):
    """All (m*k, 4) segment pairs of sa x sb, or None when either is
    empty -- the one place the pairwise expansion lives."""
    if sa is None or sb is None or len(sa) == 0 or len(sb) == 0:
        return None
    m, k = len(sa), len(sb)
    return np.repeat(sa, k, axis=0), np.tile(sb, (m, 1))


def _cross(ox, oy, px_, py_, qx, qy):
    """Cross product of (p - o) x (q - o): the single orientation
    primitive every predicate shares (any robustness/tolerance fix
    happens here)."""
    return (px_ - ox) * (qy - oy) - (py_ - oy) * (qx - ox)


def _orient(ox, oy, px_, py_, qx, qy):
    return np.sign(_cross(ox, oy, px_, py_, qx, qy))


def _any_segments_cross(sa: np.ndarray, sb: np.ndarray) -> bool:
    """Do any segments of (m,4) array sa intersect any of (k,4) sb."""
    pairs = _expand_pairs(sa, sb)
    if pairs is None:
        return False
    A, B = pairs
    hits = segments_intersect(
        A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 0], B[:, 1], B[:, 2], B[:, 3]
    )
    return bool(hits.any())


def _poly_contains_point(geom, x: float, y: float) -> bool:
    from geomesa_tpu.geom.base import MultiPolygon, Polygon

    if isinstance(geom, Polygon):
        return bool(points_in_polygon(np.array([x]), np.array([y]), geom.rings())[0])
    if isinstance(geom, MultiPolygon):
        return any(_poly_contains_point(p, x, y) for p in geom.polygons)
    return False


def geometry_intersects(a, b) -> bool:
    """Exact intersects for the supported geometry subset (host-side
    residual; the device path prefilters with bboxes).

    Handles Point / LineString / Polygon / Multi* pairs via: bbox reject,
    any-segments-cross, or either containing a vertex of the other.
    Boundary behavior at float precision matches the crossing-number caveat
    in the module docstring (JTS-robustness is out of scope).
    """
    from geomesa_tpu.geom.base import (
        MultiLineString,
        MultiPoint,
        MultiPolygon,
        Point,
        Polygon,
    )

    if not a.envelope.intersects(b.envelope):
        return False
    if isinstance(a, MultiPoint):
        return any(geometry_intersects(p, b) for p in a.points)
    if isinstance(b, MultiPoint):
        return any(geometry_intersects(a, p) for p in b.points)
    if isinstance(a, Point) and isinstance(b, Point):
        return a.x == b.x and a.y == b.y
    if isinstance(a, Point) or isinstance(b, Point):
        pt, other = (a, b) if isinstance(a, Point) else (b, a)
        if isinstance(other, (Polygon, MultiPolygon)):
            if _poly_contains_point(other, pt.x, pt.y):
                return True
        return _on_any_segment(pt.x, pt.y, _segments_of(other))
    sa, sb = _segments_of(a), _segments_of(b)
    if _any_segments_cross(sa, sb):
        return True
    # containment without boundary crossing: a component lies entirely
    # inside the other geometry -- test one vertex of EVERY component (a
    # multi-part geometry can have one far part and one contained part)
    if isinstance(a, (Polygon, MultiPolygon)) and any(
        _poly_contains_point(a, float(vx), float(vy))
        for vx, vy in _component_vertices(b)
    ):
        return True
    if isinstance(b, (Polygon, MultiPolygon)) and any(
        _poly_contains_point(b, float(vx), float(vy))
        for vx, vy in _component_vertices(a)
    ):
        return True
    return False


def _component_vertices(geom):
    """One representative vertex per connected component."""
    from geomesa_tpu.geom.base import (
        LineString,
        MultiLineString,
        MultiPolygon,
        Polygon,
    )

    if isinstance(geom, LineString):
        yield geom.coords[0, 0], geom.coords[0, 1]
    elif isinstance(geom, Polygon):
        yield geom.shell[0, 0], geom.shell[0, 1]
    elif isinstance(geom, MultiPolygon):
        for p in geom.polygons:
            yield p.shell[0, 0], p.shell[0, 1]
    elif isinstance(geom, MultiLineString):
        for l in geom.lines:
            yield l.coords[0, 0], l.coords[0, 1]


def geometry_within(inner, outer) -> bool:
    """Is ``inner`` entirely within ``outer`` (interior-contained, boundary
    tolerance per the crossing-number caveat)? Supported for polygon/line/
    point inner vs polygon outer."""
    from geomesa_tpu.geom.base import MultiPolygon, Point, Polygon

    if not isinstance(outer, (Polygon, MultiPolygon)):
        return False
    if isinstance(inner, Point):
        return _poly_contains_point(outer, inner.x, inner.y)
    if not outer.envelope.contains_env(inner.envelope):
        return False
    si = _segments_of(inner)
    so = _segments_of(outer)
    if si is None:
        return False
    if _any_segments_cross(si, so):
        return False
    # no boundary crossings: containment decided per component vertex
    return all(
        _poly_contains_point(outer, float(vx), float(vy))
        for vx, vy in _component_vertices(inner)
    )


def segments_intersect(ax, ay, bx, by, cx, cy, dx, dy) -> np.ndarray:
    """Vectorized proper/improper segment intersection AB vs CD (orientation
    sign tests, inclusive of touching endpoints)."""
    d1 = _orient(cx, cy, dx, dy, ax, ay)
    d2 = _orient(cx, cy, dx, dy, bx, by)
    d3 = _orient(ax, ay, bx, by, cx, cy)
    d4 = _orient(ax, ay, bx, by, dx, dy)
    proper = (d1 * d2 < 0) & (d3 * d4 < 0)

    def on_seg(ox, oy, px_, py_, qx, qy):
        return (
            (_orient(ox, oy, px_, py_, qx, qy) == 0)
            & (np.minimum(ox, px_) <= qx)
            & (qx <= np.maximum(ox, px_))
            & (np.minimum(oy, py_) <= qy)
            & (qy <= np.maximum(oy, py_))
        )

    touch = (
        on_seg(cx, cy, dx, dy, ax, ay)
        | on_seg(cx, cy, dx, dy, bx, by)
        | on_seg(ax, ay, bx, by, cx, cy)
        | on_seg(ax, ay, bx, by, dx, dy)
    )
    return proper | touch


# -- DE-9IM-lite relation algebra --------------------------------------------
# (ref: geomesa-spark SpatialRelationFunctions + JTS RelateOp [UNVERIFIED -
# empty reference mount]). Exact for the common cases (shared edges built
# from the same coordinates, proper crossings, containment); the documented
# lite caveats: float-precision boundary contact is measure-zero fuzzy, and
# a crossing that passes exactly through interior VERTICES of both
# polylines (orientation tests all zero) is classified as touching.
# Line-in-line coverage refines its samples at the covering line's
# component endpoints, so gaps between collinear components are detected.


def geometry_dimension(g) -> int:
    """Topological dimension: 0 points, 1 lines, 2 areas."""
    from geomesa_tpu.geom.base import (
        LineString,
        MultiLineString,
        MultiPoint,
        MultiPolygon,
        Point,
        Polygon,
    )

    if isinstance(g, (Point, MultiPoint)):
        return 0
    if isinstance(g, (LineString, MultiLineString)):
        return 1
    if isinstance(g, (Polygon, MultiPolygon)):
        return 2
    raise TypeError(f"unsupported geometry {type(g).__name__}")


def _points_of(g):
    from geomesa_tpu.geom.base import MultiPoint, Point

    if isinstance(g, Point):
        return [g]
    if isinstance(g, MultiPoint):
        return list(g.points)
    return []


def _polygons_of(g):
    from geomesa_tpu.geom.base import MultiPolygon, Polygon

    if isinstance(g, Polygon):
        return [g]
    if isinstance(g, MultiPolygon):
        return list(g.polygons)
    return []


def _line_components(g):
    from geomesa_tpu.geom.base import LineString, MultiLineString

    if isinstance(g, LineString):
        return [g]
    if isinstance(g, MultiLineString):
        return list(g.lines)
    return []


def _on_any_segment(x: float, y: float, segs) -> bool:
    if segs is None or len(segs) == 0:
        return False
    px = np.full(len(segs), x)
    py = np.full(len(segs), y)
    return bool(
        segments_intersect(
            px, py, px, py, segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
        ).any()
    )


def _strict_in_area(area, x: float, y: float) -> bool:
    """Strictly inside (interior): odd-crossing inside and not on a ring."""
    if _on_any_segment(x, y, _segments_of(area)):
        return False
    return _poly_contains_point(area, x, y)


def _in_or_on_area(area, x: float, y: float) -> bool:
    return _poly_contains_point(area, x, y) or _on_any_segment(
        x, y, _segments_of(area)
    )


def interior_point(poly) -> "tuple[float, float]":
    """A point strictly inside the polygon (mid-scanline construction:
    works for concave shells and respects holes)."""
    ys = np.unique(
        np.concatenate([np.asarray(r)[:, 1] for r in poly.rings()])
    )
    candidates = (ys[:-1] + ys[1:]) / 2.0 if len(ys) > 1 else np.array([])
    segs = _segments_of(poly)
    for yc in candidates:
        y1, y2 = segs[:, 1], segs[:, 3]
        straddle = (y1 > yc) != (y2 > yc)
        if not straddle.any():
            continue
        with np.errstate(divide="ignore", invalid="ignore"):
            xs = segs[:, 0] + (yc - y1) * (segs[:, 2] - segs[:, 0]) / (
                y2 - y1
            )
        xs = np.sort(xs[straddle])
        for x1, x2 in zip(xs[:-1], xs[1:]):
            xm = (float(x1) + float(x2)) / 2.0
            if _strict_in_area(poly, xm, yc):
                return xm, float(yc)
    # degenerate (zero-area) polygon: fall back to the first vertex
    return float(poly.shell[0, 0]), float(poly.shell[0, 1])


def _proper_cross_any(sa, sb) -> bool:
    """Any strictly-proper segment crossing (interiors pass through)."""
    pairs = _expand_pairs(sa, sb)
    if pairs is None:
        return False
    A, B = pairs
    d1 = _orient(B[:, 0], B[:, 1], B[:, 2], B[:, 3], A[:, 0], A[:, 1])
    d2 = _orient(B[:, 0], B[:, 1], B[:, 2], B[:, 3], A[:, 2], A[:, 3])
    d3 = _orient(A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 0], B[:, 1])
    d4 = _orient(A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 2], B[:, 3])
    return bool(((d1 * d2 < 0) & (d3 * d4 < 0)).any())


def _collinear_overlap_any(sa, sb) -> bool:
    """Any pair of collinear segments sharing positive-length extent."""
    pairs = _expand_pairs(sa, sb)
    if pairs is None:
        return False
    A, B = pairs
    col = (
        (_cross(A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 0], B[:, 1]) == 0)
        & (_cross(A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 2], B[:, 3]) == 0)
    )
    # project onto the dominant axis of A and require positive overlap
    dx = np.abs(A[:, 2] - A[:, 0])
    dy = np.abs(A[:, 3] - A[:, 1])
    use_x = dx >= dy
    a_lo = np.where(use_x, np.minimum(A[:, 0], A[:, 2]), np.minimum(A[:, 1], A[:, 3]))
    a_hi = np.where(use_x, np.maximum(A[:, 0], A[:, 2]), np.maximum(A[:, 1], A[:, 3]))
    b_lo = np.where(use_x, np.minimum(B[:, 0], B[:, 2]), np.minimum(B[:, 1], B[:, 3]))
    b_hi = np.where(use_x, np.maximum(B[:, 0], B[:, 2]), np.maximum(B[:, 1], B[:, 3]))
    overlap = np.minimum(a_hi, b_hi) - np.maximum(a_lo, b_lo)
    return bool((col & (overlap > 0)).any())


def _line_boundary_points(g):
    """Boundary of a line = the endpoints of its open components (a closed
    ring has no boundary). Lite: interior vertices of even degree across
    components are not cancelled (mod-2 rule applied per component only)."""
    pts = []
    for comp in _line_components(g):
        c = comp.coords
        if len(c) and not (c[0, 0] == c[-1, 0] and c[0, 1] == c[-1, 1]):
            pts.append((float(c[0, 0]), float(c[0, 1])))
            pts.append((float(c[-1, 0]), float(c[-1, 1])))
    return pts


def _line_sample_points(g):
    """Interior samples of a polyline: segment midpoints + interior
    vertices (endpoints excluded -- they are boundary)."""
    out = []
    boundary = set(_line_boundary_points(g))
    for comp in _line_components(g):
        c = comp.coords
        mids = (c[:-1] + c[1:]) / 2.0
        out.extend((float(x), float(y)) for x, y in mids)
        out.extend(
            (float(x), float(y))
            for x, y in c
            if (float(x), float(y)) not in boundary
        )
    return out


def _line_interior_intersects_area(line, area) -> bool:
    sl = _segments_of(line)
    if _proper_cross_any(sl, _segments_of(area)):
        return True
    return any(_strict_in_area(area, x, y) for x, y in _line_sample_points(line))


def _covered(a, b) -> bool:
    """Is a within the closure of b (lite: sample-point based)."""
    da, db = geometry_dimension(a), geometry_dimension(b)
    if da > db:
        return False  # higher dim can't be covered by lower
    if da == 0:
        return all(geometry_intersects(p, b) for p in _points_of(a))
    if da == 1:
        sa = _segments_of(a)
        samples = _line_sample_points(a) + _line_boundary_points(a)
        if db == 1:
            sb = _segments_of(b)
            # refine: cut every segment of a at b's vertices that lie on
            # it, and sample the cut midpoints -- a gap in b always starts
            # and ends at b vertices, so midpoint samples between
            # consecutive cuts expose it (plain midpoints would not)
            bverts = np.unique(
                np.concatenate([sb[:, :2], sb[:, 2:]], axis=0), axis=0
            )
            for x1, y1, x2, y2 in sa:
                ts = [0.0, 1.0]
                dx, dy = x2 - x1, y2 - y1
                L2 = dx * dx + dy * dy
                if L2 == 0:
                    continue
                for vx, vy in bverts:
                    if (vx - x1) * dy - (vy - y1) * dx != 0:
                        continue  # not on this segment's line
                    t = ((vx - x1) * dx + (vy - y1) * dy) / L2
                    if 0.0 < t < 1.0:
                        ts.append(float(t))
                ts.sort()
                for t0, t1 in zip(ts[:-1], ts[1:]):
                    tm = (t0 + t1) / 2.0
                    samples.append((x1 + tm * dx, y1 + tm * dy))
            return all(_on_any_segment(x, y, sb) for x, y in samples)
        # line in area: every sample in-or-on, and no proper escape
        # through the boundary
        if _proper_cross_any(sa, _segments_of(b)):
            return False
        return all(_in_or_on_area(b, x, y) for x, y in samples)
    # area in area
    if _proper_cross_any(_segments_of(a), _segments_of(b)):
        return False
    for vx, vy in np.concatenate([r[:-1] for r in a.rings()]):
        if not _in_or_on_area(b, float(vx), float(vy)):
            return False
    return all(
        _in_or_on_area(b, *interior_point(p)) for p in _polygons_of(a)
    )


def _area_interiors_intersect(a, b) -> bool:
    if _proper_cross_any(_segments_of(a), _segments_of(b)):
        return True
    for p in _polygons_of(a):
        if _strict_in_area(b, *interior_point(p)):
            return True
    for p in _polygons_of(b):
        if _strict_in_area(a, *interior_point(p)):
            return True
    return False


def _interiors_intersect(a, b) -> bool:
    """Do the interiors of a and b share a point (the II cell of DE-9IM)?
    For a point geometry the interior is the point itself."""
    da, db = geometry_dimension(a), geometry_dimension(b)
    if da > db:
        return _interiors_intersect(b, a)
    if da == 0:
        if db == 0:
            bpts = {(p.x, p.y) for p in _points_of(b)}
            return any((p.x, p.y) in bpts for p in _points_of(a))
        if db == 1:
            boundary = set(_line_boundary_points(b))
            return any(
                (p.x, p.y) not in boundary
                and _on_any_segment(p.x, p.y, _segments_of(b))
                for p in _points_of(a)
            )
        return any(_strict_in_area(b, p.x, p.y) for p in _points_of(a))
    if da == 1:
        if db == 1:
            sa, sb = _segments_of(a), _segments_of(b)
            if _proper_cross_any(sa, sb) or _collinear_overlap_any(sa, sb):
                return True
            # an interior sample of one lying on the interior of the other
            # (both directions: the contact point may be a vertex of either)
            bb = set(_line_boundary_points(b))
            if any(
                _on_any_segment(x, y, sb) and (x, y) not in bb
                for x, y in _line_sample_points(a)
            ):
                return True
            ba = set(_line_boundary_points(a))
            return any(
                _on_any_segment(x, y, sa) and (x, y) not in ba
                for x, y in _line_sample_points(b)
            )
        return _line_interior_intersects_area(a, b)
    return _area_interiors_intersect(a, b)


def geometry_touches(a, b) -> bool:
    """Geometries intersect but their interiors do not (OGC touches).
    Always False for point/point pairs."""
    if geometry_dimension(a) == 0 and geometry_dimension(b) == 0:
        return False
    if not geometry_intersects(a, b):
        return False
    return not _interiors_intersect(a, b)


def geometry_crosses(a, b) -> bool:
    """OGC crosses: interiors intersect in a lower dimension than the
    geometries' max, and each geometry has parts outside the other.
    Defined for point/line, point/area, line/area, line/line."""
    da, db = geometry_dimension(a), geometry_dimension(b)
    if da > db:
        return geometry_crosses(b, a)
    if da == 0 and db == 0:
        return False
    if da == 0:
        pts = _points_of(a)
        if len(pts) < 2:
            return False  # a single point cannot also have an exterior part
        inside = _interiors_intersect(a, b)
        outside = any(not geometry_intersects(p, b) for p in pts)
        return inside and outside
    if da == 1 and db == 1:
        sa, sb = _segments_of(a), _segments_of(b)
        return _proper_cross_any(sa, sb) and not _collinear_overlap_any(
            sa, sb
        )
    if da == 1 and db == 2:
        if not _line_interior_intersects_area(a, b):
            return False
        samples = _line_sample_points(a) + _line_boundary_points(a)
        return any(not _in_or_on_area(b, x, y) for x, y in samples)
    return False  # area/area never crosses


def geometry_overlaps(a, b) -> bool:
    """OGC overlaps: same dimension, interiors intersect with that same
    dimension, and neither is covered by the other."""
    da, db = geometry_dimension(a), geometry_dimension(b)
    if da != db:
        return False
    if da == 0:
        apts = {(p.x, p.y) for p in _points_of(a)}
        bpts = {(p.x, p.y) for p in _points_of(b)}
        return bool(apts & bpts) and bool(apts - bpts) and bool(bpts - apts)
    if da == 1:
        sa, sb = _segments_of(a), _segments_of(b)
        if not _collinear_overlap_any(sa, sb):
            return False
        return not _covered(a, b) and not _covered(b, a)
    if not _area_interiors_intersect(a, b):
        return False
    return not _covered(a, b) and not _covered(b, a)


def _boundary_geom(g):
    """The topological boundary as a geometry (None = empty set):
    area -> its rings as lines; open line -> its endpoints; point -> empty."""
    from geomesa_tpu.geom.base import LineString, MultiLineString, MultiPoint, Point

    d = geometry_dimension(g)
    if d == 0:
        return None
    if d == 1:
        pts = _line_boundary_points(g)
        if not pts:
            return None
        return MultiPoint(tuple(Point(x, y) for x, y in pts))
    return MultiLineString(tuple(LineString(r) for r in g.rings()))


def _relate_cells(a, b):
    """The 9 DE-9IM cells as lazy thunks, row-major over
    (Interior, Boundary, Exterior) of a x b."""
    ba, bb = _boundary_geom(a), _boundary_geom(b)
    return (
        lambda: _interiors_intersect(a, b),
        lambda: bb is not None and _interiors_intersect(a, bb),
        lambda: not _covered(a, b),
        lambda: ba is not None and _interiors_intersect(ba, b),
        lambda: ba is not None
        and bb is not None
        and geometry_intersects(ba, bb),
        lambda: ba is not None and not _covered(ba, b),
        lambda: not _covered(b, a),
        lambda: bb is not None and not _covered(bb, a),
        lambda: True,
    )


def geometry_relate(a, b) -> str:
    """DE-9IM-lite matrix: 9 chars over (Interior, Boundary, Exterior) of
    a x b, row-major -- 'T' = the sets intersect, 'F' = they do not.
    Dimension digits are NOT computed (see relate_matches: pattern digits
    match any non-empty cell)."""
    return "".join("T" if cell() else "F" for cell in _relate_cells(a, b))


def validate_de9im_pattern(pattern: str) -> str:
    """Normalize + validate a DE-9IM pattern (the one shared rule: 9 chars
    of ``*TF012``). Returns the uppercased pattern; raises ValueError.
    Used by the matchers here and by the ECQL parser's parse-time check."""
    p = pattern.upper()
    if len(p) != 9 or any(c not in "*TF012" for c in p):
        raise ValueError(
            f"bad DE-9IM pattern {pattern!r} (9 chars of *TF012)"
        )
    return p


def relate_matches(matrix: str, pattern: str) -> bool:
    """Match a DE-9IM-lite matrix against a pattern. '*' matches anything;
    'T' and dimension digits '0'/'1'/'2' match any non-empty cell; 'F'
    matches empty. (Lite: we do not distinguish intersection dimensions.)"""
    if len(matrix) != 9:
        raise ValueError(f"DE-9IM matrix must be 9 chars: {matrix!r}")
    for m, p in zip(matrix.upper(), validate_de9im_pattern(pattern)):
        if p == "*":
            continue
        # a matrix cell is empty iff 'F' -- 'T' and dimension digits
        # ('0'/'1'/'2', as standard JTS matrices carry) are all non-empty
        if (m != "F") != (p != "F"):
            return False
    return True


def geometry_relate_matches(a, b, pattern: str) -> bool:
    """Pattern match without materializing the full matrix: only the cells
    the pattern constrains are computed (most masks constrain 2-3 of 9,
    and each cell costs segment-pair geometry work)."""
    pattern = validate_de9im_pattern(pattern)
    for p, cell in zip(pattern, _relate_cells(a, b)):
        if p == "*":
            continue
        if cell() != (p != "F"):
            return False
    return True
