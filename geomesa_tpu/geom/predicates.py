"""Vectorized geometry predicates.

The device-side predicate set (SURVEY.md section 7 hard part #3): bbox
compare is trivial columnar math; point-in-polygon uses the crossing-number
test over packed edge lists, identical semantics host (numpy) and device
(jax). Boundary behavior: points exactly on a horizontal-crossing vertex
follow the half-open rule (a vertex counts for the edge whose y-interval is
[min, max)); points on edges may test either way at float precision -- same
caveat as JTS's RayCrossingCounter fast path.
"""

from __future__ import annotations

import numpy as np


def polygon_edges(rings) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack closed rings into edge arrays (x1, y1, x2, y2)."""
    x1, y1, x2, y2 = [], [], [], []
    for ring in rings:
        r = np.asarray(ring, dtype=np.float64)
        a = r[:-1]
        b = r[1:]
        x1.append(a[:, 0])
        y1.append(a[:, 1])
        x2.append(b[:, 0])
        y2.append(b[:, 1])
    return (
        np.concatenate(x1),
        np.concatenate(y1),
        np.concatenate(x2),
        np.concatenate(y2),
    )


def points_in_polygon(px, py, rings) -> np.ndarray:
    """Crossing-number containment for (n,) point arrays against a polygon
    given as closed rings (shell + holes: odd crossings = inside)."""
    x1, y1, x2, y2 = polygon_edges(rings)
    px = np.asarray(px, dtype=np.float64)[:, None]
    py = np.asarray(py, dtype=np.float64)[:, None]
    # edge straddles the horizontal ray (half-open to dodge vertex double count)
    straddle = (y1[None, :] > py) != (y2[None, :] > py)
    with np.errstate(divide="ignore", invalid="ignore"):
        xint = x1 + (py - y1) * (x2 - x1) / (y2 - y1)
    crossing = straddle & (px < xint)
    return crossing.sum(axis=1) % 2 == 1


def points_in_polygon_jax(px, py, rings):
    """Same crossing-number test on device. Edge list is packed host-side;
    px/py are device arrays."""
    import jax.numpy as jnp

    x1, y1, x2, y2 = polygon_edges(rings)
    x1 = jnp.asarray(x1, dtype=px.dtype)
    y1 = jnp.asarray(y1, dtype=px.dtype)
    x2 = jnp.asarray(x2, dtype=px.dtype)
    y2 = jnp.asarray(y2, dtype=px.dtype)
    pxc = px[:, None]
    pyc = py[:, None]
    straddle = (y1[None, :] > pyc) != (y2[None, :] > pyc)
    denom = y2 - y1
    denom = jnp.where(denom == 0, 1.0, denom)  # straddle==False masks these
    xint = x1 + (pyc - y1) * (x2 - x1) / denom
    crossings = jnp.sum(straddle & (pxc < xint), axis=1)
    return crossings % 2 == 1


def _segments_of(geom) -> "np.ndarray | None":
    """(m, 4) [x1, y1, x2, y2] segment array for a line/polygon geometry."""
    from geomesa_tpu.geom.base import (
        LineString,
        MultiLineString,
        MultiPolygon,
        Polygon,
    )

    if isinstance(geom, LineString):
        c = geom.coords
        return np.concatenate([c[:-1], c[1:]], axis=1)
    if isinstance(geom, (Polygon, MultiPolygon)):
        x1, y1, x2, y2 = polygon_edges(geom.rings())
        return np.stack([x1, y1, x2, y2], axis=1)
    if isinstance(geom, MultiLineString):
        return np.concatenate([_segments_of(l) for l in geom.lines], axis=0)
    return None


def _any_segments_cross(sa: np.ndarray, sb: np.ndarray) -> bool:
    """Do any segments of (m,4) array sa intersect any of (k,4) sb."""
    m, k = len(sa), len(sb)
    if m == 0 or k == 0:
        return False
    A = np.repeat(sa, k, axis=0)
    B = np.tile(sb, (m, 1))
    hits = segments_intersect(
        A[:, 0], A[:, 1], A[:, 2], A[:, 3], B[:, 0], B[:, 1], B[:, 2], B[:, 3]
    )
    return bool(hits.any())


def _poly_contains_point(geom, x: float, y: float) -> bool:
    from geomesa_tpu.geom.base import MultiPolygon, Polygon

    if isinstance(geom, Polygon):
        return bool(points_in_polygon(np.array([x]), np.array([y]), geom.rings())[0])
    if isinstance(geom, MultiPolygon):
        return any(_poly_contains_point(p, x, y) for p in geom.polygons)
    return False


def geometry_intersects(a, b) -> bool:
    """Exact intersects for the supported geometry subset (host-side
    residual; the device path prefilters with bboxes).

    Handles Point / LineString / Polygon / Multi* pairs via: bbox reject,
    any-segments-cross, or either containing a vertex of the other.
    Boundary behavior at float precision matches the crossing-number caveat
    in the module docstring (JTS-robustness is out of scope).
    """
    from geomesa_tpu.geom.base import (
        MultiLineString,
        MultiPoint,
        MultiPolygon,
        Point,
        Polygon,
    )

    if not a.envelope.intersects(b.envelope):
        return False
    if isinstance(a, MultiPoint):
        return any(geometry_intersects(p, b) for p in a.points)
    if isinstance(b, MultiPoint):
        return any(geometry_intersects(a, p) for p in b.points)
    if isinstance(a, Point) and isinstance(b, Point):
        return a.x == b.x and a.y == b.y
    if isinstance(a, Point) or isinstance(b, Point):
        pt, other = (a, b) if isinstance(a, Point) else (b, a)
        if isinstance(other, (Polygon, MultiPolygon)):
            if _poly_contains_point(other, pt.x, pt.y):
                return True
        segs = _segments_of(other)
        if segs is None:
            return False
        px = np.full(len(segs), pt.x)
        py = np.full(len(segs), pt.y)
        on = segments_intersect(
            px, py, px, py, segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
        )
        return bool(on.any())
    sa, sb = _segments_of(a), _segments_of(b)
    if _any_segments_cross(sa, sb):
        return True
    # containment without boundary crossing: a component lies entirely
    # inside the other geometry -- test one vertex of EVERY component (a
    # multi-part geometry can have one far part and one contained part)
    if isinstance(a, (Polygon, MultiPolygon)) and any(
        _poly_contains_point(a, float(vx), float(vy))
        for vx, vy in _component_vertices(b)
    ):
        return True
    if isinstance(b, (Polygon, MultiPolygon)) and any(
        _poly_contains_point(b, float(vx), float(vy))
        for vx, vy in _component_vertices(a)
    ):
        return True
    return False


def _component_vertices(geom):
    """One representative vertex per connected component."""
    from geomesa_tpu.geom.base import (
        LineString,
        MultiLineString,
        MultiPolygon,
        Polygon,
    )

    if isinstance(geom, LineString):
        yield geom.coords[0, 0], geom.coords[0, 1]
    elif isinstance(geom, Polygon):
        yield geom.shell[0, 0], geom.shell[0, 1]
    elif isinstance(geom, MultiPolygon):
        for p in geom.polygons:
            yield p.shell[0, 0], p.shell[0, 1]
    elif isinstance(geom, MultiLineString):
        for l in geom.lines:
            yield l.coords[0, 0], l.coords[0, 1]


def geometry_within(inner, outer) -> bool:
    """Is ``inner`` entirely within ``outer`` (interior-contained, boundary
    tolerance per the crossing-number caveat)? Supported for polygon/line/
    point inner vs polygon outer."""
    from geomesa_tpu.geom.base import MultiPolygon, Point, Polygon

    if not isinstance(outer, (Polygon, MultiPolygon)):
        return False
    if isinstance(inner, Point):
        return _poly_contains_point(outer, inner.x, inner.y)
    if not outer.envelope.contains_env(inner.envelope):
        return False
    si = _segments_of(inner)
    so = _segments_of(outer)
    if si is None:
        return False
    if _any_segments_cross(si, so):
        return False
    # no boundary crossings: containment decided per component vertex
    return all(
        _poly_contains_point(outer, float(vx), float(vy))
        for vx, vy in _component_vertices(inner)
    )


def segments_intersect(ax, ay, bx, by, cx, cy, dx, dy) -> np.ndarray:
    """Vectorized proper/improper segment intersection AB vs CD (orientation
    sign tests, inclusive of touching endpoints)."""

    def orient(ox, oy, px_, py_, qx, qy):
        return np.sign((px_ - ox) * (qy - oy) - (py_ - oy) * (qx - ox))

    d1 = orient(cx, cy, dx, dy, ax, ay)
    d2 = orient(cx, cy, dx, dy, bx, by)
    d3 = orient(ax, ay, bx, by, cx, cy)
    d4 = orient(ax, ay, bx, by, dx, dy)
    proper = (d1 * d2 < 0) & (d3 * d4 < 0)

    def on_seg(ox, oy, px_, py_, qx, qy):
        return (
            (orient(ox, oy, px_, py_, qx, qy) == 0)
            & (np.minimum(ox, px_) <= qx)
            & (qx <= np.maximum(ox, px_))
            & (np.minimum(oy, py_) <= qy)
            & (qy <= np.maximum(oy, py_))
        )

    touch = (
        on_seg(cx, cy, dx, dy, ax, ay)
        | on_seg(cx, cy, dx, dy, bx, by)
        | on_seg(ax, ay, bx, by, cx, cy)
        | on_seg(ax, ay, bx, by, dx, dy)
    )
    return proper | touch
