"""Lightweight geometry model (JTS-subset) for geomesa-tpu.

The reference uses JTS via GeoTools (ref: geomesa-utils .../geotools/
GeometryUtils + locationtech JTS [UNVERIFIED - empty reference mount]). This
rebuild needs only: WKT parse/format, envelopes, and the predicates that feed
device kernels (bbox intersects, vectorized point-in-polygon by crossing
number). Exact JTS-style DE-9IM is out of scope; the query path uses
bbox/convex prefilters on device plus these exact tests for the supported
predicate set (SURVEY.md section 7 hard part #3).
"""

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geom.predicates import (
    points_in_polygon,
    points_in_polygon_jax,
    segments_intersect,
)
from geomesa_tpu.geom.wkt import parse_wkt, to_wkt

__all__ = [
    "Envelope",
    "Geometry",
    "Point",
    "LineString",
    "Polygon",
    "MultiPoint",
    "MultiLineString",
    "MultiPolygon",
    "parse_wkt",
    "to_wkt",
    "points_in_polygon",
    "points_in_polygon_jax",
    "segments_intersect",
]
