"""HTTP serving bridge: WFS-shaped JSON + Arrow IPC endpoints.

Ref role: geomesa-gs-plugin -- the GeoServer packaging that exposes stores
over OGC protocols -- plus the WPS process endpoints (geomesa-process)
[UNVERIFIED - empty reference mount]. The reference keeps the serving
layer out of the query hot path (GeoServer calls the same DataStore API);
this bridge does the same: a thin stdlib ThreadingHTTPServer over any
store object, with all planning/scan work done by the store.

Endpoints (all GET):

- ``/capabilities``                 -- type names + schemas (GetCapabilities)
- ``/features/<type>?cql=&maxFeatures=&properties=&f=geojson|arrow``
                                     -- GetFeature; Arrow IPC when f=arrow
- ``/count/<type>?cql=``            -- hit count
- ``/explain/<type>?cql=``          -- query plan text
- ``/density/<type>?cql=&bbox=&width=&height=`` -- heatmap grid (WPS
  DensityProcess analog), JSON {"counts": [[...]], "bbox": [...]}
- ``/stats/<type>?cql=&stats=<Stat-DSL spec>&loose=`` -- server-side
  aggregation (StatsProcess / StatsIterator analog), JSON stat list
- ``/knn/<type>?x=&y=&k=&cql=&maxRadius=`` -- k nearest features with
  distances (KNearestNeighborSearchProcess analog; resident mode = one
  fused distance+top_k dispatch)
- ``/tube/<type>?track=x,y,t;...&buffer=&maxDt=&cql=`` -- corridor
  search around a track (TubeSelectProcess analog)
- ``/proximity/<type>?points=x,y;...&distance=&cql=`` -- features near
  any input point, with distances (ProximitySearchProcess analog)
- ``/metrics``                      -- Prometheus exposition text
- ``/healthz``                      -- liveness: 200 while the process
  is up, draining included (only readiness flips on drain)
- ``/readyz``                       -- readiness: breaker states per
  failure domain, scheduler pressure, degraded domains; 503 while
  draining (load balancers pull the instance), 200 otherwise — a
  DEGRADED instance keeps serving and says so in the body
- ``/stats/sched``                  -- device query scheduler counters
  (sched mode: queue depth, wait time, fusion factor, rejections)
- ``/stats/store``                  -- store durability/integrity snapshot
  (FS stores: generations, quarantined partitions, recovery counters)
- ``/stats/mesh``                   -- serving-mesh topology + per-type
  shard residency (rows/bytes/Z-key range per shard, build engine)
- ``/stats/slo``                    -- windowed SLO engine: per-SLO
  objective/threshold, fast+slow burn rates, burning flags, and
  windowed p50/p99/p999 per endpoint/lane (slo.py)
- ``/stats/ledger``                 -- per-request cost ledger roll-up:
  per-tenant and per-shape cost aggregates, the top-K most expensive
  requests (with trace ids), and the compile-attribution table
  (ledger.py)
- ``/stats``                        -- roll-up: sched + store + mesh +
  slo + ledger + persistent compile-cache hit/miss in one scrape
- ``/debug/traces``                 -- recent request traces (summaries;
  ``?limit=``)
- ``/debug/traces/<id>``            -- one trace's full span tree;
  ``?format=perfetto`` emits Chrome-trace/Perfetto JSON
- ``/refresh/<type>``               -- restage a resident type after writes
- ``/wal/<type>?from=&waitMs=&follower=`` -- replication ship: chunked
  stream of checksummed WAL records (on-disk framing) with seq >= from;
  long-polls when empty, 410 Gone below the compaction watermark
- ``/stats/replica``                -- replication role/lag/failover doc
  (replica.py; {"enabled": false} when unreplicated)

POST ``/append/<type>`` ingests into the streaming live layer (WAL-first
ack; followers answer 503 + the leader's URL), and POST
``/admin/shutdown`` triggers the draining shutdown remotely (the fleet
rolling-restart drain trigger; the response acks before draining
starts).

Tracing: every non-debug request runs under a root span (tracing.py) —
an inbound ``X-Request-Id`` header becomes the trace id (echoed on the
response; generated when absent), spans from the scheduler, planner,
device launches and store reads nest beneath it, and retention follows
``trace.sample`` / ``trace.slow_ms`` (slow requests also append to the
store's ``_slow_queries.jsonl``, full trace embedded).

Scheduler mode (``make_server(store, sched=True)`` or a SchedConfig, CLI
``serve --sched``) routes query/count/density/knn/stats work through the
device query scheduler (:mod:`geomesa_tpu.sched`): bounded admission
(queue-full -> 429 + Retry-After), per-request deadlines (``deadlineMs=``
-> 504 on expiry), priority lanes (``lane=interactive|batch``),
per-tenant fairness (``tenant=``, defaulting to the client address), and
micro-batch fusion — compatible concurrent resident bbox queries execute
as ONE stacked device launch instead of N.

Resident mode (``make_server(store, resident=True)``, CLI ``serve
--resident``) pins each type's scan columns AND index-key planes in
device memory (DeviceIndex, the tablet-server block-cache analog):
``/count``, ``/features`` and ``/stats`` answer from HBM in one fused
dispatch, and ``loose=1`` switches bbox(+during) filters to the key-only
cell-granular scan (geomesa.loose.bbox). The resident copy is a
SNAPSHOT: after writing to the backing store, hit ``/refresh/<type>``
(or restart) to restage — the durable store stays the source of truth,
exactly the DeviceIndex contract.

Fault tolerance (resilience.py, ISSUE 7): device-rung work (resident
count/features/stats/density) runs behind the ``device`` circuit
breaker with jittered retries of transient faults; when the breaker is
open, a launch fails or the resident cache cannot stage, requests fall
down the degradation ladder (resident -> store scan; exact -> chunk
pre-aggregates under brownout) instead of failing — every degraded
response carries an ``X-Degraded: <reason,...>`` header and the audit
event records the same reasons. Shutdown DRAINS: admission stops
(query endpoints 503 + Retry-After, ``/readyz`` flips 503 while
``/healthz`` stays 200 so the orchestrator de-routes without killing),
in-flight scheduler work finishes, audit/slow logs flush, then the
accept loop stops.

SLOs + cost accounting (slo.py / ledger.py, ISSUE 9): every query
request is measured against its lane's SLO (``slo.<lane>.*`` conf
keys) in time-rotated latency windows, multi-window burn rates ride
``/stats/slo`` and ``/readyz`` (burning = degraded detail, NOT
unready), and a per-request cost ledger — device launches/seconds,
compile attribution, host I/O, chunks pruned, retries, degradations —
aggregates per tenant/shape on ``/stats/ledger``. When the fast-window
burn crosses ``slo.flightrec.burn`` or a breaker opens, the flight
recorder snapshots a postmortem bundle to ``<root>/_flightrec/``.

Errors return JSON ``{"error": ...}`` with 4xx/5xx status; 429/504/5xx
responses carry ``X-Request-Id`` too, and shed / deadline-expired
requests are stamped into the audit log (outcome field).
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from geomesa_tpu.spawn import spawn_thread


class _GeomesaHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose ``shutdown`` is a DRAINING shutdown:
    admission stops first (the ``draining`` event flips query endpoints
    to 503 + Retry-After and ``/readyz`` to 503; ``/healthz`` liveness
    stays 200 so the orchestrator de-routes, not kills), in-flight
    scheduler work finishes (``QueryScheduler.close`` — bounded, joins
    the workers; leaving workers mid-device-launch lets a CLI/test
    process exit with work half-executed), the audit and slow-query
    logs flush, and only then does the accept loop stop."""

    scheduler = None
    store = None  # wired by make_server (audit flush at drain)
    stream_layer = None  # StreamingStore, when the live layer is on
    replica = None  # Replicator, when this server is in a group
    pubsub = None  # PubSubHub, when the push tier is on

    def __init__(self, *args, **kwargs):
        self.draining = threading.Event()
        # compilecheck serving-window bracket (set by make_server once
        # the server is fully wired; flag keeps double-shutdown balanced)
        self._ccheck_live = False
        super().__init__(*args, **kwargs)

    def shutdown(self):
        self.draining.set()  # stop admission BEFORE finishing in-flight
        if self.replica is not None:
            # stop tailing/failover first: a follower must not promote
            # because ITS OWN drain made the leader look dead
            try:
                self.replica.close()
            except Exception:  # lint: disable=GT011(shutdown teardown: a failing close must not stop the drain)  # close is best-effort on the way down
                pass
        if self.scheduler is not None:
            self.scheduler.close(timeout=5.0)
        if self.pubsub is not None:
            # detach the matcher from the stream and wake every push
            # connection BEFORE the live layer seals its WAL
            try:
                self.pubsub.close()
            except Exception:  # lint: disable=GT011(shutdown teardown: a failing close must not stop the drain)  # close is best-effort on the way down
                pass
        if self.stream_layer is not None:
            # stop the compactor and seal the WAL; acked-but-uncompacted
            # rows stay durable in the log and replay on the next open
            try:
                self.stream_layer.close()
            except Exception:  # lint: disable=GT011(shutdown teardown: a failing close must not stop the drain)  # close is best-effort on the way down
                pass
        aw = getattr(self.store, "audit_writer", None)
        if aw is not None:
            try:
                aw.flush()
            except Exception:  # lint: disable=GT011(shutdown teardown: a failing audit flush must not stop the drain)  # flush is best-effort on the way down
                pass
        super().shutdown()
        if self._ccheck_live:
            # after the accept loop stops: compiles during the drain are
            # still serving-path compiles and stay checked
            self._ccheck_live = False
            from geomesa_tpu.analysis import compilecheck

            compilecheck.CHECKER.serving_down()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1: chunked transfer encoding for the streamed result
    # plane (first record batch flushes while later batches are still
    # assembling); every buffered response carries Content-Length so
    # keep-alive semantics hold. The socket timeout bounds how long an
    # IDLE keep-alive connection may pin a handler thread (the stdlib
    # turns the timeout into close_connection) — without it every
    # half-open client would hold a ThreadingHTTPServer thread forever.
    # make_server resolves the declared ``http.keepalive.s`` conf key
    # over this class default (router→backend persistent connections
    # share the same knob)
    protocol_version = "HTTP/1.1"
    timeout = 60

    store = None  # injected by make_server
    resident = False  # serve from device-pinned DeviceIndex caches
    mesh = False  # shard resident indexes across the device mesh
    scheduler = None  # QueryScheduler (admission + micro-batch fusion)
    stream = None  # StreamingStore live layer (None = batch-only)
    replica = None  # Replicator (None = unreplicated single process)
    pubsub = None  # PubSubHub continuous-query tier (needs stream)
    _resident_cache: dict = {}  # per-server-class: type -> DeviceIndex
    _resident_lock = None  # per-server-class construction lock

    def _di(self, type_name: str):
        """Resident index for a type (resident mode only). Streaming
        flavor: its internal lock serializes refresh against concurrent
        handler-thread scans. The dict read is the GIL-safe fast path;
        the construction lock only guards first-touch builds (a duplicate
        build would stage the whole dataset into device memory twice).

        First-touch builds run behind the ``cache`` circuit breaker
        (resilience.py): a staging failure (device OOM, store fault)
        degrades the request to the store path — returns None, stamped
        — instead of 500ing, and repeated failures open the breaker so
        requests stop paying the staging attempt until its half-open
        probe. A breaker-gated failure never evicts an ALREADY-staged
        healthy index (the dict hit above short-circuits)."""
        if not self.resident:
            return None
        di = self._resident_cache.get(type_name)
        if di is not None:
            return di
        from geomesa_tpu import resilience

        if not resilience.degrade_allowed():
            return self._build_locked(type_name)[0]
        br = resilience.cache_breaker()
        if not br.allow():
            resilience.note_degraded("cache-breaker-open")
            return None
        try:
            di = self._build_locked(type_name)[0]
        except Exception as e:
            if resilience.classify(e) == resilience.FATAL:
                # unknown type / bad request: surface, not degrade —
                # and free a held half-open probe slot (no health
                # signal either way)
                br.release_probe()
                raise
            br.record_failure()
            resilience.note_degraded("resident-unavailable")
            return None
        br.record_success()
        return di

    @staticmethod
    def _loose(q: dict) -> "bool | None":
        v = q.get("loose")
        return None if v is None else v.lower() in ("1", "true", "yes")

    @staticmethod
    def _auths(q: dict) -> tuple:
        """Request authorizations (``auths=A,B``); absent = none — labeled
        features hide, fail closed, on both serving paths."""
        v = q.get("auths")
        if not v:
            return ()
        return tuple(a for a in (s.strip() for s in v.split(",")) if a)

    @staticmethod
    def _cap(q: dict) -> "int | None":
        """Result cap with interceptor parity, shared by every resident
        endpoint: an EXPLICIT maxFeatures (including 0) overrides the
        global query.max.features, which applies only when the request is
        unbounded (MaxFeaturesInterceptor semantics). None = uncapped."""
        mf = q.get("maxFeatures")
        if mf is not None:
            return max(0, int(mf))  # negatives behave like 0 (plain path)
        from geomesa_tpu.conf import sys_prop

        g = int(sys_prop("query.max.features") or 0)
        return g if g > 0 else None

    def _build_locked(self, type_name: str):
        """First-touch resident build under the construction lock;
        returns (index, built_now). Mesh mode (``mesh.enabled`` or
        ``make_server(mesh=True)``) with more than one visible device
        stages a :class:`~geomesa_tpu.device_cache.ShardedDeviceIndex`
        — the type's planes shard across the serving mesh by global
        Z-key range and every scan launches mesh-wide."""
        cache = self._resident_cache
        with self._resident_lock:
            if type_name in cache:
                return cache[type_name], False
            di = _make_resident_index(
                self.store, type_name, self.mesh,
                streaming=self.stream is not None,
            )
            cache[type_name] = di
            return di, True

    def _observe_resident(self, type_name: str, cql: str, t0, t1, hits):
        """Metrics + audit parity with the store query pipeline (resident
        scans bypass store.query, which would otherwise record these)."""
        try:
            from geomesa_tpu.audit import AuditedEvent
            from geomesa_tpu.metrics import queries_run, query_seconds
            from geomesa_tpu.resilience import current_degraded
            from geomesa_tpu.tracing import current_trace_id

            queries_run.inc(store="resident", type=type_name)
            query_seconds.observe(t1 - t0)
            if self.scheduler is None:
                # unscheduled resident serving: the scheduler would have
                # charged the ledger for this launch — do it here instead
                from geomesa_tpu import ledger

                ledger.charge("device_launches", 1)
                ledger.charge("device_seconds", t1 - t0)
                ledger.charge("fusion_width", 1)
            aw = getattr(self.store, "audit_writer", None)
            if aw is not None:
                aw.write(AuditedEvent(
                    store="resident", type_name=type_name, filter=cql,
                    planning_ms=0.0, scanning_ms=(t1 - t0) * 1e3, hits=hits,
                    trace_id=current_trace_id(),
                    degraded=",".join(current_degraded()),
                ))
        except Exception:  # pragma: no cover - observability must not break  # lint: disable=GT011(audit emission is observability; a failed write must not fail the query it records)
            pass

    # quiet default request logging; hook point for real deployments
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _stamp_response_headers(self, code: int, headers=()) -> None:
        """The shared response stamping between ``send_response`` and
        ``end_headers``: ledger status, request-id echo, degradation
        header — identical for buffered and streamed responses."""
        cost = getattr(self, "_cost", None)
        if cost is not None:
            # the ledger/SLO layer classifies good vs bad by this code
            cost.status = code
        tr = getattr(self, "_trace", None)
        if tr is not None:
            # the trace id rides the response whether or not the trace
            # was retained — clients correlate logs by it either way
            self.send_header("X-Request-Id", tr.trace_id)
            tr.root.set(status=code)
        else:
            # untraced paths (parse errors, monitoring endpoints) still
            # echo a sanitized inbound id: a client correlating a 400/
            # 429/5xx against its own logs needs it most on errors
            from geomesa_tpu.tracing import _clean_id

            rid = _clean_id(self.headers.get("X-Request-Id"))
            if rid:
                self.send_header("X-Request-Id", rid)
        reasons = getattr(self, "_degraded", None)
        if reasons:
            # the degradation contract: an approximate or partial answer
            # is never silent — the client can see (and log) the rung
            self.send_header("X-Degraded", ",".join(reasons))
            if tr is not None:
                tr.root.set(degraded=",".join(reasons))
        for name, value in headers:
            self.send_header(name, value)

    def _send(self, code: int, body: bytes, ctype: str, headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self._stamp_response_headers(code, headers)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, doc) -> None:
        self._send(code, json.dumps(doc).encode("utf-8"), "application/json")

    def _observe_encode(self, fmt: str, enc_s: float, write_s: float,
                        total: int, rows, batches: int) -> None:
        """Fold one response's serialization cost into the ledger
        (GT009 fields), the results metrics, and two SIBLING spans —
        ``http.encode`` (serialization only) and ``http.write`` (socket
        only), split so a slow client can no longer pollute encode
        attribution in the slow-query log or ``/stats/ledger``."""
        import time as _time

        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.tracing import capture, record_span

        now = _time.perf_counter()
        parent = capture()
        record_span(
            parent, "http.encode", now - enc_s - write_s, enc_s,
            fmt=fmt, rows=rows, batches=batches, bytes=total,
        )
        record_span(parent, "http.write", now - write_s, write_s,
                    bytes=total)
        ledger.charge("encode_seconds", enc_s)
        ledger.charge("response_bytes", total)
        metrics.results_encode_seconds.observe(enc_s)
        metrics.results_write_seconds.observe(write_s)
        metrics.results_batches.inc(batches, fmt=fmt)
        metrics.results_bytes.inc(total, fmt=fmt)

    def _send_encoded(self, code: int, body: bytes, ctype: str, fmt: str,
                      enc_s: float, rows=None, headers=()) -> None:
        """Buffered response whose serialization the caller already
        timed (``enc_s``); the socket write is measured here."""
        import time as _time

        t0 = _time.perf_counter()
        self._send(code, body, ctype, headers=headers)
        self._observe_encode(
            fmt, enc_s, _time.perf_counter() - t0, len(body), rows, 1
        )

    @staticmethod
    def _timed_batches(batches, cell: list):
        """Wrap a batch iterator, accumulating time spent PRODUCING
        batches (store partition read/decode on the streamed store
        rung) into ``cell[0]`` — _send_stream subtracts it so
        encode_seconds stays pure serialization time (the store's own
        instrumentation already charges read/decode fields; counting
        those seconds as encode would re-pollute the very attribution
        the encode/write split exists to clean up)."""
        import time as _time

        it = iter(batches)
        try:
            while True:
                t0 = _time.perf_counter()
                b = next(it, None)
                cell[0] += _time.perf_counter() - t0
                if b is None:
                    return
                yield b
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _send_stream(self, code: int, ctype: str, chunks, fmt: str,
                     rows=None, headers=(), upstream: "list | None" = None,
                     ) -> None:
        """Chunked streaming response: the FIRST chunk is produced
        before the status line goes out (late planning/encode errors
        still surface as clean HTTP errors), every later chunk flushes
        to the socket while the next is still assembling. Serialization
        time (pulling the generator) and socket-write time accumulate
        separately for the encode/write span split. A mid-stream
        failure AFTER headers cannot become an error response — the
        chunked stream ends WITHOUT its terminating 0-chunk and the
        connection drops, so clients detect truncation instead of
        parsing a partial result as complete."""
        import time as _time

        it = iter(chunks)
        t0 = _time.perf_counter()
        first = next(it, b"")
        enc = _time.perf_counter() - t0
        if self.request_version < "HTTP/1.1":
            # RFC 9112: never send chunked framing to a 1.0 peer — it
            # would read the hex chunk sizes as body bytes. Buffer the
            # whole stream (the pre-streaming behavior) and close.
            t1 = _time.perf_counter()
            body = first + b"".join(it)
            enc += _time.perf_counter() - t1
            if upstream is not None:
                enc = max(enc - upstream[0], 0.0)
            self.close_connection = True
            return self._send_encoded(
                code, body, ctype, fmt, enc, rows=rows, headers=headers
            )
        write_s = 0.0
        total = 0
        nchunks = 0
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self._stamp_response_headers(code, headers)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        clean = False
        try:
            piece = first
            while True:
                if piece:
                    nchunks += 1
                    t1 = _time.perf_counter()
                    self.wfile.write(b"%x\r\n" % len(piece))
                    self.wfile.write(piece)
                    self.wfile.write(b"\r\n")
                    write_s += _time.perf_counter() - t1
                    total += len(piece)
                t1 = _time.perf_counter()
                piece = next(it, None)
                enc += _time.perf_counter() - t1
                if piece is None:
                    clean = True
                    break
        except BrokenPipeError:
            self.close_connection = True
        except Exception as e:
            # headers are gone: signal truncation, never a fake success
            self.close_connection = True
            tr = getattr(self, "_trace", None)
            if tr is not None:
                tr.root.set(stream_error=f"{type(e).__name__}: {e}")
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                # deterministic teardown on abandonment: the encoder's
                # finally closes its writer and the partition stream
                # joins its prefetch workers NOW, not at GC time
                close()
        if clean:
            try:
                self.wfile.write(b"0\r\n\r\n")
            except BrokenPipeError:
                self.close_connection = True
        if upstream is not None:
            # generator pulls included upstream batch PRODUCTION time
            # (partition read/decode); encode keeps serialization only
            enc = max(enc - upstream[0], 0.0)
        self._observe_encode(fmt, enc, write_s, total, rows, nchunks)

    def _sched_run(self, q: dict, fn=None, fuse=None, device=None):
        """Route one unit of query work through the device query
        scheduler when one is configured (admission control, deadlines,
        micro-batch fusion for compatible resident queries); direct
        execution otherwise. Request knobs: ``lane=interactive|batch``,
        ``tenant=`` (defaults to the client address, the per-tenant
        fairness key), ``deadlineMs=``."""
        sched = self.scheduler
        if sched is None:
            if fn is not None:
                return fn()
            return fuse.run_serial()
        dl = q.get("deadlineMs")
        tenant = q.get("tenant")
        if not tenant and self.client_address:
            tenant = str(self.client_address[0])
        kw = {}
        if dl:  # absent: the scheduler's configured default applies
            kw["deadline_ms"] = float(dl)
        return sched.run(
            fn=fn,
            fuse=fuse,
            lane=q.get("lane", "interactive"),
            tenant=tenant or "",
            device=device,
            **kw,
        )

    def _degradable(self, q: dict, reason: str, fallback, fn=None,
                    fuse=None):
        """Run device-rung work with the full fault discipline: the
        ``device`` circuit breaker gates entry (open -> straight to the
        fallback rung, stamped — nobody queues behind a dead device),
        transient faults retry with jittered backoff
        (``resilience.retries``), and a non-retryable / still-failing
        launch falls to ``fallback`` with ``reason`` noted. Flow-control
        signals (429/504) and FATAL faults (bad requests) always
        propagate — backpressure and errors are part of the client
        contract, not something to degrade away. The fallback runs
        OUTSIDE the scheduler by design: it is the emergency rung, and
        the scheduler meters the device it no longer touches."""
        from geomesa_tpu import resilience
        from geomesa_tpu.sched import DeadlineExpired, RejectedError

        if not resilience.enabled():
            return self._sched_run(q, fn=fn, fuse=fuse, device=True)
        br = resilience.device_breaker()
        can_fall = fallback is not None and resilience.degrade_allowed()
        if can_fall and not br.allow():
            resilience.note_degraded("device-breaker-open")
            return fallback()
        try:
            res = resilience.retry_call(
                lambda: self._sched_run(q, fn=fn, fuse=fuse, device=True),
                domain="device",
            )
        except (RejectedError, DeadlineExpired):
            # a shed/expired half-open probe carried no health signal:
            # free the slot so the next caller probes immediately, or a
            # saturated queue would pin the breaker half-open (and all
            # traffic on the degraded rung) one full cooldown per shed
            if can_fall:
                br.release_probe()
            raise
        except Exception as e:
            if resilience.classify(e) == resilience.FATAL:
                # a bad REQUEST says nothing about device health: free
                # a held half-open probe slot instead of pinning the
                # breaker (and all traffic on the degraded rung) for
                # another cooldown
                if can_fall:
                    br.release_probe()
                raise
            stuck = isinstance(e, resilience.LaunchStuckError)
            if not stuck:
                # the watchdog already charged the stuck launch to the
                # breaker — once per FAULT; re-recording here would add
                # one count per fused rider and open the breaker after
                # a single wedged group
                br.record_failure()
            if not can_fall:
                raise
            resilience.note_degraded("launch-stuck" if stuck else reason)
            return fallback()
        br.record_success()
        return res

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        try:
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
        except Exception as e:
            # clear ALL per-request state: on a keep-alive connection
            # this handler instance served the previous request, and a
            # stale cost/degraded carry-over would mis-stamp this 400
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._json(400, {"error": str(e)})
        # observability endpoints are not themselves traced — scrapes,
        # trace reads and the stats snapshots must not churn the trace
        # ring (a monitoring poll would evict real query traces).
        # /stats/<type> with a real type name IS a query and stays
        # traced; the same disambiguation _dispatch routes by.
        untraced = (
            parts and parts[0] in ("metrics", "debug", "healthz", "readyz")
        ) or (
            parts == ["stats", "sched"] and self.scheduler is not None
        ) or (
            parts == ["stats", "store"]
            and hasattr(self.store, "store_stats")
        ) or parts == ["stats", "mesh"] or parts == ["stats", "slo"] \
            or parts == ["stats", "ledger"] or parts == ["stats", "stream"] \
            or parts == ["stats", "replica"] or parts[:1] == ["wal"] \
            or parts[:1] == ["snapshot"] or parts == ["stats"] \
            or parts == ["stats", "pubsub"] or parts[:1] == ["subscribe"]
        if untraced:
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._dispatch_safe(url, parts, q)
        from geomesa_tpu import ledger, resilience
        from geomesa_tpu.tracing import TRACER

        tenant = q.get("tenant") or (
            str(self.client_address[0]) if self.client_address else ""
        )
        # error handling lives INSIDE the trace: the error response is
        # sent (status attr stamped, its time counted) before the trace
        # finishes and retention / the slow-query log fire. The
        # degradation collector wraps the same scope: any layer that
        # answers below the requested rung notes a reason here, and the
        # response/audit stamping reads it back. The cost collector
        # rides along too — it is finalized AFTER the trace completes
        # (the span tree is whole at that point) and folded into the
        # process ledger + the SLO engine's latency windows.
        with TRACER.trace(
            f"GET {url.path}",
            trace_id=self.headers.get("X-Request-Id"),
            attrs={"path": url.path, "query": url.query[:512]},
        ) as tr, resilience.collect_degraded() as reasons, \
                ledger.collect_cost(
                    tenant=tenant,
                    endpoint=_cost_endpoint(parts),
                    lane=q.get("lane", "interactive"),
                    shape=_query_shape(parts, q),
                ) as cost:
            self._trace = tr
            self._degraded = reasons
            self._cost = cost
            if cost is not None:
                # stamped NOW (not at finish) so a mid-request compile
                # ledger entry can name the trace that blocked on it
                cost.trace_id = tr.trace_id
            self._dispatch_safe(url, parts, q)
        ledger.finish_request(cost, tr)

    def _admin_authorized(self) -> bool:
        """Gate for operator-plane endpoints (``/admin/*``). With
        ``admin.token`` set, the caller must present the exact shared
        secret in ``X-Admin-Token`` (compared constant-time). With no
        token configured the plane stays usable for local tooling but
        only from loopback peers — a reachable serving port must not
        expose an unauthenticated kill switch."""
        import hmac

        from geomesa_tpu.conf import sys_prop

        token = str(sys_prop("admin.token"))
        if token:
            offered = self.headers.get("X-Admin-Token") or ""
            return hmac.compare_digest(offered, token)
        peer = str(self.client_address[0]) if self.client_address else ""
        return peer in ("127.0.0.1", "::1", "::ffff:127.0.0.1")

    def do_POST(self) -> None:  # noqa: N802 (stdlib API)
        """POST ``/append/<type>``: the streaming-ingest endpoint. Body
        ``{"columns": {...}, "fids": [...], "visibilities": [...]}``;
        the response acks rows that are WAL-durable and queryable NOW
        (no flush/restage on this path). Backpressure surfaces as 429 +
        Retry-After — from the scheduler's admission bound or the live
        layer's ``wal.max.generations`` read-amplification bound."""
        from geomesa_tpu.conf import sys_prop

        try:
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            length = int(self.headers.get("Content-Length") or 0)
            cap = int(sys_prop("stream.append.max.bytes"))
            if cap and length > cap:
                # bounded-everything discipline: one append becomes one
                # WAL record and one memtable run — refuse BEFORE
                # buffering (nothing is read, nothing is acked)
                self._trace = None
                self._degraded = None
                self._cost = None
                return self._json(413, {
                    "error": f"append body {length} bytes exceeds "
                             f"stream.append.max.bytes={cap}"
                })
            body = self.rfile.read(length) if length else b""
        except Exception as e:
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._json(400, {"error": str(e)})
        if parts == ["admin", "shutdown"]:
            # the fleet-restart drain trigger: respond FIRST (the
            # orchestrator needs the ack), then run the draining
            # shutdown off-thread — shutdown() joins in-flight work
            # and would deadlock the handler thread serving this very
            # request
            self._trace = None
            self._degraded = None
            self._cost = None
            if not self._admin_authorized():
                return self._json(403, {
                    "error": "admin endpoint refused: present the "
                             "X-Admin-Token header (admin.token), or "
                             "call from loopback when no token is "
                             "configured"
                })
            self._json(200, {"draining": True})
            spawn_thread(
                self.server.shutdown, name="admin-shutdown", context=False
            ).start()
            return
        if len(parts) == 2 and parts[0] == "subscribe":
            # subscription CRUD is control-plane traffic: untraced (like
            # the ship endpoints), leader-pinned (the registry WAL must
            # not fork), replicated to followers via /wal/_pubsub
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._run_safe(
                lambda: self._subscribe_post(parts, q, body), parts, q
            )
        if len(parts) != 2 or parts[0] != "append":
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._json(
                404, {"error": f"no such POST endpoint {url.path!r}"}
            )
        # appends default to the dedicated ingest lane (top priority:
        # sub-ms host work must not queue behind device scans)
        q.setdefault("lane", "ingest")
        from geomesa_tpu import ledger, resilience
        from geomesa_tpu.tracing import TRACER

        tenant = q.get("tenant") or (
            str(self.client_address[0]) if self.client_address else ""
        )
        with TRACER.trace(
            f"POST {url.path}",
            trace_id=self.headers.get("X-Request-Id"),
            attrs={"path": url.path, "bytes": len(body)},
        ) as tr, resilience.collect_degraded() as reasons, \
                ledger.collect_cost(
                    tenant=tenant,
                    endpoint="append",
                    lane=q["lane"],
                    shape="append",
                ) as cost:
            self._trace = tr
            self._degraded = reasons
            self._cost = cost
            if cost is not None:
                cost.trace_id = tr.trace_id
            self._run_safe(
                lambda: self._append_post(parts, q, body), parts, q
            )
        ledger.finish_request(cost, tr)

    def _append_post(self, parts: list, q: dict, body: bytes) -> None:
        from geomesa_tpu.features.batch import FeatureBatch

        type_name = unquote(parts[1])
        if self._draining():
            return self._send(
                503,
                json.dumps(
                    {"error": "server is draining"}
                ).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        rep = self.replica
        if rep is not None and not rep.is_leader():
            # appends pin to the leader: a follower taking writes would
            # fork the WAL seq space. 503 + Retry-After (not 4xx) —
            # during promotion the SAME url becomes writable, so the
            # client/router should retry, not give up
            # the bounce carries the epoch alongside the leader url so
            # the router/load-driver re-discover in one hop, without a
            # /stats/replica round trip — and can ignore a bounce from
            # a staler epoch than one they already followed
            return self._send(
                503,
                json.dumps({
                    "error": "not the leader "
                             f"(role={rep.role}); appends go to the "
                             "leader",
                    "leader": rep.leader_url,
                    "epoch": int(rep.epoch),
                }).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        stream = self.stream
        if stream is None:
            return self._json(
                400,
                {"error": "server is not running with the streaming "
                          "live layer (stream.enabled / serve --stream)"},
            )
        doc = json.loads(body.decode("utf-8")) if body else {}
        cols = doc.get("columns")
        if not isinstance(cols, dict) or not cols:
            raise ValueError(
                'append body needs {"columns": {...}, "fids": [...]}'
            )
        sft = self.store.get_schema(type_name)  # KeyError -> 404
        batch = FeatureBatch.from_columns(sft, cols, doc.get("fids"))
        vis = doc.get("visibilities")
        if vis is not None:
            batch = batch.with_visibility(vis)
        res = self._sched_run(
            q, fn=lambda: stream.append(type_name, batch)
        )
        replicated = None
        if rep is not None and rep.ack_mode() == "replica" \
                and int(res["rows"]):
            from geomesa_tpu.conf import sys_prop
            from geomesa_tpu.resilience import note_degraded

            replicated = rep.await_replicated(
                type_name, int(res["seq"]),
                float(sys_prop("replica.ack.timeout.s")),
            )
            if not replicated:
                # acked local-durable only: rows are WAL-safe here but
                # a leader loss before ship could lose them — stamp the
                # response degraded instead of failing a durable write
                note_degraded("replica-lag")
        doc = {"acked": int(res["rows"]), "seq": int(res["seq"])}
        if rep is not None:
            # fencing token: a client (or router) holding a higher
            # epoch from elsewhere can spot a stale leader in the ack
            doc["epoch"] = int(rep.epoch)
        if replicated is not None:
            doc["replicated"] = bool(replicated)
        self._json(200, doc)

    # -- continuous queries (the pubsub push tier) -------------------------

    def _pubsub_hub(self):
        if self.pubsub is None:
            raise ValueError(
                "server is not running the continuous-query push tier "
                "(needs the streaming live layer: stream.enabled / "
                "serve --stream)"
            )
        return self.pubsub

    def _subscribe_post(self, parts: list, q: dict, body: bytes) -> None:
        """POST ``/subscribe/<type>``: register a standing continuous
        query. Body: any of ``{"bbox": [...], "cql": "...", "dwithin":
        {"x","y","distance"}, "auths": [...]}``. The response carries
        the subscription id and its initial cursor (the data-WAL seq it
        is armed from). Leader-pinned: the registry WAL replicates to
        followers, so the same 503 + leader bounce as appends."""
        hub = self._pubsub_hub()
        if self._draining():
            return self._send(
                503,
                json.dumps({"error": "server is draining"}).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        rep = self.replica
        if rep is not None and not rep.is_leader():
            return self._send(
                503,
                json.dumps({
                    "error": "not the leader "
                             f"(role={rep.role}); subscriptions go to "
                             "the leader",
                    "leader": rep.leader_url,
                    "epoch": int(rep.epoch),
                }).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        type_name = unquote(parts[1])
        doc = json.loads(body.decode("utf-8")) if body else {}
        tenant = q.get("tenant") or (
            str(self.client_address[0]) if self.client_address else ""
        )
        auths = doc.get("auths")
        if auths is None:
            auths = self._auths(q)
        out = hub.subscribe(type_name, doc, tenant=tenant, auths=auths)
        if rep is not None:
            out["epoch"] = int(rep.epoch)
        self._json(200, out)

    def do_DELETE(self) -> None:  # noqa: N802 (stdlib API)
        """DELETE ``/subscribe/<type>?id=<sub>``: cancel a standing
        subscription (leader-pinned, replicated like registration)."""
        try:
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
        except Exception as e:
            self._trace = None
            self._degraded = None
            self._cost = None
            return self._json(400, {"error": str(e)})
        self._trace = None
        self._degraded = None
        self._cost = None
        if len(parts) != 2 or parts[0] != "subscribe":
            return self._json(
                404, {"error": f"no such DELETE endpoint {url.path!r}"}
            )
        return self._run_safe(
            lambda: self._subscribe_delete(parts, q), parts, q
        )

    def _subscribe_delete(self, parts: list, q: dict) -> None:
        hub = self._pubsub_hub()
        rep = self.replica
        if rep is not None and not rep.is_leader():
            return self._send(
                503,
                json.dumps({
                    "error": f"not the leader (role={rep.role})",
                    "leader": rep.leader_url,
                    "epoch": int(rep.epoch),
                }).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        sub_id = q.get("id")
        if not sub_id:
            raise ValueError("DELETE /subscribe/<type> needs ?id=<sub>")
        if not hub.cancel(sub_id):
            raise KeyError(sub_id)
        self._json(200, {"cancelled": sub_id})

    def _subscribe_stream(self, type_name: str, q: dict) -> None:
        """GET ``/subscribe/<type>?id=&from=&f=``: the long-lived push
        stream. ``from`` (or the SSE ``Last-Event-ID`` header) is the
        subscriber's acked seq watermark — delivery resumes exactly-once
        above it; omitted, it defaults to the subscription's creation
        cursor. Formats ride the results plane: geojson = SSE ``match``
        events with ``:keepalive`` heartbeats, arrow = IPC stream with a
        ``match_seq`` column, bin = track records (resume via explicit
        ``from=``)."""
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.pubsub import CursorGoneError
        from geomesa_tpu.pubsub.delivery import (
            arrow_push_chunks,
            bin_push_chunks,
            sse_chunks,
        )
        from geomesa_tpu.results import PUSH_CONTENT_TYPES, negotiate_format

        hub = self._pubsub_hub()
        if self._draining():
            return self._send(
                503,
                json.dumps({"error": "server is draining"}).encode("utf-8"),
                "application/json",
                headers=(("Retry-After", "1"),),
            )
        sub_id = q.get("id")
        if not sub_id:
            raise ValueError("GET /subscribe/<type> needs ?id=<sub>")
        sub = hub.registry.get(sub_id)
        if sub is None or sub.type_name != type_name:
            raise KeyError(sub_id)
        fmt = negotiate_format(q, self.headers.get("Accept"))
        frm = q.get("from")
        if frm is None:
            frm = self.headers.get("Last-Event-ID")
        from_seq = int(frm) if frm is not None else int(sub.created_seq)
        sft = self.store.get_schema(type_name)
        try:
            events = hub.events(
                type_name, sub_id, from_seq,
                float(sys_prop("sub.heartbeat.s")),
            )
        except CursorGoneError as e:
            return self._json(410, {"error": str(e)})
        # a push connection is idle ON PURPOSE between matches: exempt
        # it from the keep-alive reap (heartbeats bound detection of a
        # dead peer instead) and never reuse the socket afterwards
        self.connection.settimeout(None)
        self.close_connection = True
        if fmt == "arrow":
            chunks = arrow_push_chunks(events, sft)
        elif fmt == "bin":
            track = q.get("track") or sft.attribute_names[0]
            chunks = bin_push_chunks(events, track)
        else:
            chunks = sse_chunks(events, type_name, sub_id)
        ctype = PUSH_CONTENT_TYPES[fmt]
        self._send_stream(
            200, ctype, self._deliver_guard(chunks, sub), fmt,
            headers=(("Cache-Control", "no-cache"),),
        )

    def _deliver_guard(self, chunks, sub):
        """Per-chunk delivery wrapper: the ``fail.sub.deliver`` fault
        hook plus byte accounting charged to the subscriber tenant."""
        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.failpoints import fail_point

        sent = 0
        try:
            for piece in chunks:
                fail_point("fail.sub.deliver")
                sent += len(piece)
                yield piece
        finally:
            if sent:
                metrics.pubsub_deliver_bytes.inc(float(sent))
                if ledger.enabled():
                    cost = ledger.RequestCost(
                        tenant=sub.tenant,
                        endpoint="subscribe",
                        lane="interactive",
                        shape="push-stream",
                    )
                    cost.status = 200
                    cost.charge("sub_deliver_bytes", float(sent))
                    ledger.LEDGER.record(cost)

    def _registry_ship(self, q: dict) -> None:
        """``GET /wal/_pubsub?from=``: ship the subscription-registry
        WAL to followers. Same framing as the data ship, but the
        registry log is never truncated (bounded by subscription churn)
        so there is no watermark and no 410 — a follower can always
        catch up from any position."""
        from geomesa_tpu.store.wal import pack_record

        hub = self._pubsub_hub()
        wal = hub.registry.wal
        frm = max(int(q.get("from", 0)), 0)
        rep = self.replica
        if rep is not None:
            try:
                rep.observe_epoch(int(q.get("epoch", 0)))
            except (TypeError, ValueError):
                pass
        nxt = int(wal.next_seq)

        def chunks():
            buf = bytearray()
            for seq, payload in wal.read_from(frm - 1):
                if seq >= nxt:
                    break
                buf += pack_record(seq, payload)
                if len(buf) >= (512 << 10):
                    yield bytes(buf)
                    buf.clear()
            if buf:
                yield bytes(buf)

        role = rep.role if rep is not None else "leader"
        self._send_stream(
            200, "application/x-geomesa-wal", chunks(), "wal",
            headers=(
                ("X-Wal-Next-Seq", str(nxt)),
                ("X-Wal-Watermark", "-1"),
                ("X-Replica-Role", role),
                ("X-Replica-Epoch",
                 str(rep.epoch if rep is not None else 0)),
            ),
        )

    def _audit_outcome(self, parts: list, q: dict, outcome: str) -> None:
        """Stamp a shed (429) or deadline-expired (504) request into the
        audit log — operators sizing admission need the requests that
        did NOT run, not just the ones that did. Best-effort: auditing
        must never break the error response it annotates."""
        try:
            aw = getattr(self.store, "audit_writer", None)
            if aw is None:
                return
            from geomesa_tpu.audit import AuditedEvent
            from geomesa_tpu.resilience import current_degraded
            from geomesa_tpu.tracing import current_trace_id

            aw.write(AuditedEvent(
                store="server",
                type_name=parts[1] if len(parts) > 1 else "",
                filter=q.get("cql", ""),
                hits=0,
                trace_id=current_trace_id(),
                outcome=outcome,
                degraded=",".join(current_degraded()),
            ))
        except Exception:  # pragma: no cover - observability must not break  # lint: disable=GT011(audit emission is observability; a failed write must not fail the query it records)
            pass

    def _dispatch_safe(self, url, parts: list, q: dict) -> None:
        return self._run_safe(
            lambda: self._dispatch(url, parts, q), parts, q
        )

    def _run_safe(self, fn, parts: list, q: dict) -> None:
        try:
            return fn()
        except KeyError as e:
            self._json(404, {"error": f"unknown schema or attribute {e}"})
        except ValueError as e:
            self._json(400, {"error": str(e)})
        except BrokenPipeError:
            pass
        except Exception as e:
            from geomesa_tpu.sched import DeadlineExpired, RejectedError
            from geomesa_tpu.store.stream import WalUnavailableError

            if isinstance(e, WalUnavailableError):
                # the wal breaker is open: appends fail fast until its
                # half-open probe — 503 says "not you, come back"
                return self._send(
                    503,
                    json.dumps({"error": str(e)}).encode("utf-8"),
                    "application/json",
                    headers=(("Retry-After", "1"),),
                )
            if isinstance(e, RejectedError):
                # backpressure: shed load explicitly instead of queueing
                # unboundedly; clients should honor Retry-After (derived
                # from live queue depth + drain rate, jittered — see
                # QueryScheduler._retry_after_locked)
                self._audit_outcome(parts, q, "shed")
                return self._send(
                    429,
                    json.dumps({"error": str(e)}).encode("utf-8"),
                    "application/json",
                    # RFC 9110 delta-seconds is integral: standard client
                    # retry machinery (urllib3 et al.) rejects fractions.
                    # Ceil keeps the estimate an upper bound; the jitter
                    # survives rounding at multi-second queue depths
                    headers=(
                        ("Retry-After", str(math.ceil(e.retry_after_s))),
                    ),
                )
            if isinstance(e, DeadlineExpired):
                self._audit_outcome(parts, q, "deadline-expired")
                return self._json(504, {"error": str(e)})
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def _draining(self) -> bool:
        ev = getattr(self.server, "draining", None)
        return ev is not None and ev.is_set()

    def _healthz(self) -> None:
        """Liveness: 200 for as long as the process is up — INCLUDING
        while draining. Failing liveness makes an orchestrator KILL the
        instance (restart, not de-route), which would lose exactly the
        in-flight work the draining shutdown exists to finish; traffic
        removal is ``/readyz``'s job, and it flips 503 the moment
        draining starts."""
        self._json(
            200, {"status": "draining" if self._draining() else "ok"}
        )

    def _readyz(self) -> None:
        """Readiness, driven by breaker state: the body reports every
        failure domain's breaker, the open (unhealthy) domains,
        scheduler queue pressure and any BURNING SLOs. A DEGRADED or
        burning instance is still READY (200) — it serves, just
        lower-rung or over budget, and says so; only draining,
        mid-reprovision, and (with ``compile.warmup.gate=ready``) a
        still-running AOT warmup pass flip 503 (nothing new should be
        routed here)."""
        from geomesa_tpu import resilience, slo

        breakers = resilience.snapshot()
        degraded = sorted(
            d for d, s in breakers.items()
            if isinstance(s, dict) and s.get("state") != "closed"
        )
        if breakers.get("partition_open"):
            degraded.append("partition")
        # burning SLOs are degraded DETAIL, never unready: pulling a
        # burning instance from rotation would shift its load onto the
        # others and burn THEIR budgets faster
        burning = slo.ENGINE.burning() if slo.enabled() else []
        doc = {
            "ready": not self._draining(),
            "draining": self._draining(),
            "degraded_domains": degraded,
            "slo_burning": burning,
            "breakers": breakers,
        }
        if self.scheduler is not None:
            queued, max_queue = self.scheduler.queue_pressure()
            doc["sched"] = {"queued": queued, "max_queue": max_queue}
        if self.replica is not None:
            # the router's health poll keys append-routing off this
            doc["replica_role"] = self.replica.role
            inst = self.replica.reprovisioning
            if inst:
                # mid-reprovision this node's store is being swapped
                # out from under its query surface: not-ready, so the
                # router routes reads to healthy replicas until the
                # install finishes and lag returns to 0
                doc["ready"] = False
                doc["reprovisioning"] = inst
        if getattr(self, "_warmup_started", False):
            from geomesa_tpu import warmup
            from geomesa_tpu.conf import sys_prop

            gate = str(sys_prop("compile.warmup.gate"))
            if gate != "off" and warmup.warming():
                # the AOT pre-compile pass over the bucket x
                # kernel-family set is still running: gate="ready"
                # holds readiness so a rolling restart (fleet
                # wait_ready) never routes traffic at a cold process;
                # gate="stamp" serves immediately but says so
                doc["warming"] = True
                if gate == "ready":
                    doc["ready"] = False
        self._json(200 if doc["ready"] else 503, doc)

    def _dispatch(self, url, parts: list, q: dict) -> None:
        if parts == ["capabilities"]:
            return self._capabilities()
        if parts == ["healthz"]:
            return self._healthz()
        if parts == ["readyz"]:
            return self._readyz()
        if parts == ["metrics"]:
            from geomesa_tpu.metrics import REGISTRY

            # content negotiation: exemplars (trace-id suffixes) are
            # only valid in the OpenMetrics format — the classic 0.0.4
            # parser would fail the WHOLE scrape on one suffixed line
            om = "application/openmetrics-text" in (
                self.headers.get("Accept") or ""
            )
            return self._send(
                200,
                REGISTRY.prometheus_text(openmetrics=om).encode("utf-8"),
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8" if om else "text/plain; version=0.0.4",
            )
        if parts[:2] == ["debug", "traces"]:
            return self._debug_traces(parts, q)
        if parts == ["stats", "sched"] and self.scheduler is not None:
            return self._json(200, self.scheduler.snapshot())
        if parts == ["stats", "store"] and hasattr(
            self.store, "store_stats"
        ):
            return self._json(200, self.store.store_stats())
        if parts == ["stats", "mesh"]:
            return self._json(200, self._mesh_stats())
        if parts == ["stats", "slo"]:
            from geomesa_tpu import slo

            return self._json(200, slo.ENGINE.snapshot())
        if parts == ["stats", "ledger"]:
            from geomesa_tpu.ledger import LEDGER

            return self._json(200, LEDGER.snapshot())
        if parts == ["stats", "stream"]:
            return self._json(
                200,
                self.stream.stream_stats()
                if self.stream is not None
                else {"enabled": False},
            )
        if parts == ["stats", "replica"]:
            return self._json(
                200,
                self.replica.stats()
                if self.replica is not None
                else {"enabled": False},
            )
        if parts == ["stats", "pubsub"]:
            return self._json(
                200,
                self.pubsub.stats()
                if self.pubsub is not None
                else {"enabled": False},
            )
        if len(parts) == 2 and parts[0] == "subscribe":
            # the long-lived push stream (SSE/arrow/bin); served by ANY
            # replica — matching runs off the local WAL feed
            return self._subscribe_stream(unquote(parts[1]), q)
        if parts == ["stats"]:
            return self._json(200, self._stats_index())
        if len(parts) == 2 and parts[0] == "wal":
            # replication shipping stays OPEN while draining: the fleet
            # restart drains a leader exactly so followers can catch up
            return self._wal_ship(unquote(parts[1]), q)
        if len(parts) == 2 and parts[0] == "snapshot":
            # snapshot bootstrap stays OPEN while draining too: a
            # reprovisioning follower mid-download must be able to
            # finish against a draining leader
            return self._snapshot_ship(unquote(parts[1]), q)
        if len(parts) == 2 and parts[0] in (
            "features", "count", "explain", "density", "stats",
            "refresh", "knn", "tube", "proximity",
        ):
            if self._draining():
                # admission is closed: a draining instance finishes
                # what it has, it does not take on more
                return self._send(
                    503,
                    json.dumps(
                        {"error": "server is draining"}
                    ).encode("utf-8"),
                    "application/json",
                    headers=(("Retry-After", "1"),),
                )
            handler = getattr(self, f"_{parts[0]}")
            return handler(unquote(parts[1]), q)
        self._json(404, {"error": f"no such endpoint {url.path!r}"})

    def _mesh_stats(self) -> dict:
        """``/stats/mesh``: serving-mesh topology + per-type shard
        residency (rows, bytes, Z-key range and build engine per shard)
        for every mesh-resident type staged so far."""
        import jax

        doc: dict = {
            "enabled": bool(self.mesh),
            "devices_visible": len(jax.devices()),
            "types": {},
        }
        for name, di in list(self._resident_cache.items()):
            stats = getattr(di, "mesh_stats", None)
            if stats is not None:
                doc["types"][name] = stats()
        return doc

    def _wal_ship(self, type_name: str, q: dict) -> None:
        """``GET /wal/<type>?from=<seq>&waitMs=&follower=`` — the
        replication ship endpoint: a chunked stream of checksummed WAL
        records (the on-disk framing, ``pack_record``) with
        ``seq >= from``, read through the never-mutating
        :meth:`~geomesa_tpu.store.wal.WriteAheadLog.read_from` cursor —
        safe against the live appender, and servable by ANY replica
        (an election loser tails the winner before it even promotes).
        ``waitMs`` long-polls an empty log so followers ride one
        request per batch instead of hot-polling; ``follower`` is the
        caller's advertised URL, folded into the leader's applied-seq
        accounting (``replica.ack=replica``). 410 Gone when the
        requested position was compacted away below the watermark —
        tailing cannot help; the follower must re-provision from a
        snapshot."""
        import time as _time

        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.store.wal import pack_record

        stream = self.stream
        if stream is None:
            return self._json(
                400,
                {"error": "server is not running with the streaming "
                          "live layer (stream.enabled / serve --stream)"},
            )
        from geomesa_tpu.pubsub import REGISTRY_SHIP_NAME

        if type_name == REGISTRY_SHIP_NAME:
            # the subscription registry ships through the same endpoint
            # as a reserved pseudo-type (no schema, never truncated)
            return self._registry_ship(q)
        self.store.get_schema(type_name)  # KeyError -> 404
        ts = stream._ts(type_name)
        frm = max(int(q.get("from", 0)), 0)
        after = frm - 1
        wait_ms = min(max(float(q.get("waitMs", 0.0)), 0.0), 30_000.0)
        rep = self.replica
        if rep is not None:
            rep.note_follower(q.get("follower", ""), type_name, after)
            try:
                rep.observe_epoch(int(q.get("epoch", 0)))
            except (TypeError, ValueError):
                pass
        watermark = int(self.store._types[type_name].wal_watermark)
        if frm <= watermark:
            first = ts.wal.first_seq()
            if first < 0 or frm < first:
                # compaction GC'd the asked-for records: they live only
                # in the partition files now, which shipping cannot
                # replay — the follower needs a snapshot re-provision
                return self._json(410, {
                    "error": f"WAL records below seq {first} were "
                             "compacted away; re-provision this "
                             "follower from a store snapshot",
                    "first_seq": first,
                    "watermark": watermark,
                })
        # long-poll BEFORE the headers: X-Wal-Next-Seq must reflect the
        # position the stream actually serves through. next_seq is a
        # GIL-safe int read — no segment scan while waiting.
        deadline = _time.monotonic() + wait_ms / 1e3
        while (
            ts.wal.next_seq <= frm
            and _time.monotonic() < deadline
            and not self._draining()
        ):
            _time.sleep(0.01)
        nxt = int(ts.wal.next_seq)
        state = {"bytes": 0, "records": 0}

        def chunks():
            buf = bytearray()
            prev = after
            for seq, payload in ts.wal.read_from(after):
                if seq >= nxt:
                    break  # a fixed upper bound keeps the stream finite
                if seq > prev + 1 and prev >= frm:
                    # a segment vanished mid-walk (compaction racing the
                    # cursor): never ship across the hole — ending the
                    # stream early makes the follower re-ask from its
                    # true position and hit the 410/gap machinery
                    break
                prev = seq
                buf += pack_record(seq, payload)
                state["records"] += 1
                if len(buf) >= (512 << 10):
                    state["bytes"] += len(buf)
                    yield bytes(buf)
                    buf.clear()
            if buf:
                state["bytes"] += len(buf)
                yield bytes(buf)

        role = rep.role if rep is not None else "leader"
        self._send_stream(
            200, "application/x-geomesa-wal", chunks(), "wal",
            headers=(
                ("X-Wal-Next-Seq", str(nxt)),
                ("X-Wal-Watermark", str(watermark)),
                ("X-Replica-Role", role),
                ("X-Replica-Epoch",
                 str(rep.epoch if rep is not None else 0)),
            ),
        )
        if state["records"]:
            metrics.replica_ship_records.inc(state["records"])
            metrics.replica_ship_bytes.inc(state["bytes"])
            if ledger.enabled():
                cost = ledger.RequestCost(
                    tenant="_system", endpoint="wal", lane="batch",
                    shape="wal-ship",
                )
                cost.status = 200
                cost.charge("replica_ship_bytes", state["bytes"])
                ledger.LEDGER.record(cost)

    def _snapshot_ship(self, type_name: str, q: dict) -> None:
        """``GET /snapshot/<type>[?id=&from_file=]`` — the snapshot
        bootstrap endpoint: captures a consistent, GC-pinned snapshot
        of the type's published generation under the publish lock and
        ships it as a chunked stream of length-prefixed, checksummed
        file records (store/snapshot.py framing; the manifest ships
        last, the same order the installer publishes in). ``id`` +
        ``from_file`` resume an earlier stream off its still-pinned
        snapshot, skipping files already landed; 410 Gone when that pin
        was released or aged out (``snapshot.pin.ttl.s``) — the client
        restarts with a fresh capture. The pin is released when the
        stream completes; a truncated stream leaves it for the resume
        or the TTL sweep. Role/epoch ride the response headers so a
        reprovisioning follower can refuse a snapshot seeded by a
        stale leader."""
        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.store import snapshot as snapshot_mod

        stream = self.stream
        if stream is None:
            return self._json(
                400,
                {"error": "server is not running with the streaming "
                          "live layer (stream.enabled / serve --stream)"},
            )
        self.store.get_schema(type_name)  # KeyError -> 404
        store = stream.store
        sid = str(q.get("id", "") or "")
        try:
            from_file = max(int(q.get("from_file", 0) or 0), 0)
        except (TypeError, ValueError):
            from_file = 0
        if sid:
            doc = snapshot_mod.load_pin(store, type_name, sid)
            if doc is None:
                return self._json(410, {
                    "error": f"snapshot {sid!r} was released or its "
                             "pin aged out; restart with a fresh "
                             "GET /snapshot",
                })
            # the resumed stream holds the pin live again
            store._active_pins.add((type_name, sid))
        else:
            doc = snapshot_mod.capture(store, type_name)
            sid = doc["snapshot_id"]
            from_file = 0
        rep = self.replica
        role = rep.role if rep is not None else "leader"
        state = {"bytes": 0, "done": False}

        def chunks():
            try:
                for b in snapshot_mod.iter_stream(
                    store, type_name, doc, from_file=from_file
                ):
                    state["bytes"] += len(b)
                    yield b
                state["done"] = True
            finally:
                if state["done"]:
                    # complete hand-off: unpin, GC may reclaim on the
                    # next sweep
                    snapshot_mod.release(store, type_name, sid)
                else:
                    # truncated (client gone, disk error, failpoint):
                    # the on-disk pin stays for a resume, but this
                    # process stops holding it live — an abandoned
                    # stream's pin ages out under snapshot.pin.ttl.s
                    store._active_pins.discard((type_name, sid))

        self._send_stream(
            200, snapshot_mod.SNAPSHOT_CONTENT_TYPE, chunks(),
            "snapshot",
            headers=(
                ("X-Snapshot-Id", sid),
                ("X-Wal-Watermark", str(int(doc.get("wal_watermark", -1)))),
                ("X-Snapshot-Files", str(len(doc.get("files", ())))),
                ("X-Replica-Role", role),
                ("X-Replica-Epoch",
                 str(rep.epoch if rep is not None else 0)),
            ),
        )
        if state["bytes"]:
            metrics.snapshot_ship_bytes.inc(state["bytes"])
            if state["done"]:
                metrics.snapshot_ship_files.inc(
                    max(len(doc.get("files", ())) - from_file, 0)
                )
            if ledger.enabled():
                cost = ledger.RequestCost(
                    tenant="_system", endpoint="snapshot", lane="batch",
                    shape="snapshot-ship",
                )
                cost.status = 200 if state["done"] else 499
                cost.charge("snapshot_ship_bytes", state["bytes"])
                ledger.LEDGER.record(cost)

    def _stats_index(self) -> dict:
        """``/stats``: one roll-up document — scheduler, store, mesh,
        SLO engine, cost ledger, the persistent compile cache
        (hit/miss) and AOT warmup progress in a single scrape."""
        from geomesa_tpu import slo, warmup
        from geomesa_tpu.jaxconf import compile_cache_stats
        from geomesa_tpu.ledger import LEDGER

        doc: dict = {
            "compile_cache": compile_cache_stats(),
            "warmup": warmup.progress(),
        }
        if self.scheduler is not None:
            doc["sched"] = self.scheduler.snapshot()
        if hasattr(self.store, "store_stats"):
            doc["store"] = self.store.store_stats()
        doc["mesh"] = self._mesh_stats()
        doc["slo"] = slo.ENGINE.snapshot()
        doc["ledger"] = LEDGER.snapshot()
        if self.stream is not None:
            doc["stream"] = self.stream.stream_stats()
        if self.replica is not None:
            doc["replica"] = self.replica.stats()
        if self.pubsub is not None:
            doc["pubsub"] = self.pubsub.stats()
        return doc

    def _debug_traces(self, parts: list, q: dict) -> None:
        """``/debug/traces`` (recent summaries) and
        ``/debug/traces/<id>`` (full span tree; ``?format=perfetto``)."""
        from geomesa_tpu.tracing import TRACER

        if len(parts) == 2:
            limit = int(q.get("limit", 50))
            return self._json(200, {"traces": TRACER.recent(limit)})
        if len(parts) != 3:
            return self._json(404, {"error": "use /debug/traces[/<id>]"})
        t = TRACER.get(unquote(parts[2]))
        if t is None:
            return self._json(
                404,
                {"error": f"no trace {parts[2]!r} (evicted, or neither "
                          "sampled nor slow — see trace.sample / "
                          "trace.slow_ms)"},
            )
        if q.get("format") == "perfetto":
            return self._json(200, t.to_perfetto())
        return self._json(200, t.to_dict())

    # -- endpoints ---------------------------------------------------------

    def _capabilities(self) -> None:
        doc = {"types": {}}
        for name in self.store.type_names:
            sft = self.store.get_schema(name)
            doc["types"][name] = {
                "spec": sft.spec,
                "geometry": sft.geom_field,
                "dtg": sft.dtg_field,
                "attributes": [
                    {"name": a.name, "type": a.type_name}
                    for a in sft.attributes
                ],
            }
        self._json(200, doc)

    def _query(self, type_name: str, q: dict):
        from geomesa_tpu.query.plan import Query

        max_features = q.get("maxFeatures")
        props = q.get("properties")
        return self.store.query(
            type_name,
            Query(
                filter=q.get("cql", "INCLUDE"),
                max_features=int(max_features) if max_features else None,
                properties=props.split(",") if props else None,
                hints={"auths": self._auths(q)},
            ),
        )

    def _features(self, type_name: str, q: dict) -> None:
        from geomesa_tpu import results

        fmt = results.negotiate_format(q, self.headers.get("Accept"))
        di = self._di(type_name)
        if fmt == "bin":
            return self._features_bin(type_name, q, di)
        presorted = None
        if di is not None and not q.get("properties"):
            import time as _time

            import numpy as np

            from geomesa_tpu.sched import FusableQuery

            t0 = _time.perf_counter()
            cql = q.get("cql", "INCLUDE")
            fell: list = []

            def fallback():
                # store rung: exact, audited by the store path itself
                fell.append(True)
                return self._query(type_name, q).batch

            batch = self._degradable(
                q, "device-launch-failed", fallback,
                fuse=FusableQuery(
                    di, cql, "query",
                    loose=self._loose(q), auths=self._auths(q),
                ),
            )
            cap = self._cap(q)
            if cap is not None and len(batch) > cap:
                batch = batch.take(np.arange(cap))
            if not fell:
                self._observe_resident(
                    type_name, cql, t0, _time.perf_counter(), len(batch)
                )
                # the host mirror is Z-sorted and the compacted row ids
                # ascend, so resident hit batches ARE sorted runs of the
                # index key: stamp it, never re-sort on host
                presorted = "z"
            batches = [batch]
            sft = batch.sft
        elif fmt == "arrow" and not q.get("properties"):
            # store rung, streamed: per-partition batches ride the
            # host-I/O prefetch pipeline straight into the encoder —
            # the first record batch hits the wire while later
            # partitions are still being read/decoded
            fetch = [0.0]
            batches = self._timed_batches(
                self._store_batches(type_name, q), fetch
            )
            sft = self.store.get_schema(type_name)
            batch = None
        else:
            batch = self._sched_run(
                q, fn=lambda: self._query(type_name, q).batch
            )
            batches = [batch]
            sft = batch.sft
        if fmt == "arrow":
            # dictionary-delta record batches: clients consume
            # incrementally and dictionaries never retransmit (ref
            # DeltaWriter protocol); per-chunk memory is bounded by
            # results.batch.rows — the whole-response BytesIO is gone
            return self._send_stream(
                200, results.CONTENT_TYPES["arrow"],
                results.arrow_stream_chunks(
                    batches, sft, presorted=presorted
                ),
                "arrow",
                rows=None if batch is None else len(batch),
                upstream=None if batch is not None else fetch,
            )
        self._emit_geojson(batch)

    def _store_batches(self, type_name: str, q: dict):
        """Store-rung result batches as an ITERATOR for the streamed
        encoders. FS stores without the live layer stream one filtered
        batch per surviving partition through the prefetch pipeline
        (bounded read-ahead; visibility applied per partition, the cap
        trimmed across the stream). The streaming live layer and plain
        memory stores materialize the merged view — correctness first:
        a partition iterator would miss memtable rows."""
        from geomesa_tpu import results
        from geomesa_tpu.query.plan import Query

        qp = getattr(self.store, "query_partitions", None)
        if (
            qp is not None
            and self.stream is None
            and not q.get("properties")
        ):
            query = Query(
                filter=q.get("cql", "INCLUDE"),
                hints={"auths": self._auths(q)},
            )
            return results.capped_batches(
                qp(type_name, query), self._cap(q)
            )
        return iter(
            [self._sched_run(q, fn=lambda: self._query(type_name, q).batch)]
        )

    def _features_bin(self, type_name: str, q: dict, di) -> None:
        """``f=bin``: the 16/24-byte track records. Resident indexes
        pack on device (``results.bin.engine``; the fused
        count→cap→compact rider) with the numpy twin as fallback rung;
        the store rung streams per-batch records. ``track=`` names the
        track-id attribute (required), ``label=`` widens to 24-byte
        records, ``sortBin=1`` orders by dtg seconds."""
        import time as _time

        from geomesa_tpu import results

        track = q.get("track")
        if not track:
            raise ValueError("f=bin needs track=<attribute>")
        label = q.get("label") or None
        sort = (q.get("sortBin") or "").lower() in ("1", "true", "yes")
        ctype = results.CONTENT_TYPES["bin"]
        rec = 24 if label else 16
        if di is not None and self._cap(q) is None \
                and not q.get("properties"):
            cql = q.get("cql", "INCLUDE")
            fell: list = []

            def fallback():
                fell.append(True)
                return None

            t0 = _time.perf_counter()

            def device_work():
                return results.resident_bin(
                    di, cql, track, dtg_attr=q.get("dtg"),
                    label_attr=label, sort=sort,
                    loose=self._loose(q), auths=self._auths(q),
                )

            data = self._degradable(
                q, "device-launch-failed", fallback, fn=device_work
            )
            t1 = _time.perf_counter()
            if data is not None:
                if not fell:
                    self._observe_resident(
                        type_name, cql, t0, t1, len(data) // rec
                    )
                return self._send_encoded(
                    200, data, ctype, "bin", t1 - t0,
                    rows=len(data) // rec,
                )
        fetch = [0.0]
        batches = self._timed_batches(
            self._store_batches(type_name, q), fetch
        )
        self._send_stream(
            200, ctype,
            results.bin_stream_chunks(
                batches, track, dtg_attr=q.get("dtg"),
                label_attr=label, sort=sort,
            ),
            "bin",
            upstream=fetch,
        )

    def _emit_geojson(self, batch) -> None:
        """GeoJSON feature collection with the encode/write split."""
        import time as _time

        from geomesa_tpu.export import feature_collection

        t0 = _time.perf_counter()
        body = json.dumps(feature_collection(batch)).encode("utf-8")
        self._send_encoded(
            200, body, "application/json", "geojson",
            _time.perf_counter() - t0, rows=len(batch),
        )

    def _emit_features(self, batch, q: dict, extra=None) -> None:
        """Emit a process result batch in the NEGOTIATED format —
        ``/knn``/``/tube``/``/proximity`` honor ``f=arrow``/``f=bin``
        through the result plane. Extra per-feature outputs (kNN
        distances …) become real typed columns via an extended SFT
        (Arrow columns / GeoJSON properties), not a per-feature zip."""
        from geomesa_tpu import results

        fmt = results.negotiate_format(q, self.headers.get("Accept"))
        if extra:
            batch = results.with_extra_columns(batch, extra)
        if fmt == "arrow":
            return self._send_stream(
                200, results.CONTENT_TYPES["arrow"],
                results.arrow_stream_chunks([batch], batch.sft),
                "arrow", rows=len(batch),
            )
        if fmt == "bin":
            track = q.get("track")
            if not track:
                raise ValueError("f=bin needs track=<attribute>")
            sort = (q.get("sortBin") or "").lower() in (
                "1", "true", "yes"
            )
            return self._send_stream(
                200, results.CONTENT_TYPES["bin"],
                results.bin_stream_chunks(
                    [batch], track, dtg_attr=q.get("dtg"),
                    label_attr=q.get("label") or None, sort=sort,
                ),
                "bin", rows=len(batch),
            )
        self._emit_geojson(batch)

    # -- WPS process endpoints (knn / tube select / proximity search) ------

    def _knn(self, type_name: str, q: dict) -> None:
        """``/knn/<type>?x=&y=&k=&cql=&maxRadius=`` — k nearest features
        (KNearestNeighborSearchProcess analog). In resident mode this is
        ONE fused distance+top_k dispatch on the pinned columns."""
        from geomesa_tpu.process.knn import knn

        px, py = float(q["x"]), float(q["y"])
        k = int(q.get("k", 10))
        kwargs = {}
        if q.get("maxRadius"):
            kwargs["max_radius_deg"] = float(q["maxRadius"])
        batch, dists = self._sched_run(
            q,
            fn=lambda: knn(
                self.store, type_name, px, py, k,
                base_filter=q.get("cql"),
                device_index=self._di(type_name),
                auths=self._auths(q),
                **kwargs,
            ),
        )
        import numpy as np

        self._emit_features(
            batch, q,
            extra={"knn_distance_deg": np.asarray(dists, np.float64)},
        )

    def _tube(self, type_name: str, q: dict) -> None:
        """``/tube/<type>?track=x,y,t;x,y,t;...&buffer=&maxDt=&cql=`` —
        corridor search around a track (TubeSelectProcess analog; one
        union-of-windows dispatch in resident mode)."""
        import numpy as np

        pts = [p for p in q["track"].split(";") if p]
        trk = np.array([[float(v) for v in p.split(",")] for p in pts])
        if trk.ndim != 2 or trk.shape[1] != 3 or len(trk) < 2:
            raise ValueError(
                "track must be 'x,y,t_ms;x,y,t_ms;...' with >= 2 points"
            )
        from geomesa_tpu.process.tube import tube_select

        batch = tube_select(
            self.store, type_name, trk[:, :2], trk[:, 2].astype(np.int64),
            buffer_deg=float(q.get("buffer", 0.1)),
            max_dt_ms=int(q.get("maxDt", 3_600_000)),
            base_filter=q.get("cql"),
            device_index=self._di(type_name),
            auths=self._auths(q),
        )
        self._emit_features(batch, q)

    def _proximity(self, type_name: str, q: dict) -> None:
        """``/proximity/<type>?points=x,y;x,y&distance=&cql=`` — features
        within a distance of any input point (ProximitySearchProcess
        analog; one union-of-windows dispatch in resident mode)."""
        from geomesa_tpu.geom.base import Point
        from geomesa_tpu.process.proximity import proximity_search

        pts = [p for p in q["points"].split(";") if p]
        geoms = [
            Point(*(float(v) for v in p.split(","))) for p in pts
        ]
        batch, dists = proximity_search(
            self.store, type_name, geoms,
            distance_deg=float(q.get("distance", 0.1)),
            base_filter=q.get("cql"),
            device_index=self._di(type_name),
            auths=self._auths(q),
        )
        import numpy as np

        self._emit_features(
            batch, q,
            extra={"proximity_distance_deg": np.asarray(dists, np.float64)},
        )

    def _agg_shaped(self, type_name: str, cql: str) -> bool:
        """Pre-screen for the brownout rung: True when the filter is a
        shape the chunk pre-aggregates can answer (bbox+time
        conjunctions — `is_aggregate_shape`) AND the store actually has
        chunk statistics for the type. Anything else would row-scan
        inside store.count/density, and brownout runs on the HANDLER
        thread outside scheduler admission precisely because it is
        supposed to be near-free: an unmetered full scan there would
        amplify the overload it exists to relieve."""
        from geomesa_tpu.query.plan import Query, is_aggregate_shape

        has_stats = getattr(self.store, "has_chunk_stats", None)
        if has_stats is None or not has_stats(type_name):
            return False  # v1/legacy/memory store: no pre-aggregates
        try:
            return bool(is_aggregate_shape(
                Query(filter=cql).parsed(),
                self.store.get_schema(type_name),
            ))
        except Exception:  # lint: disable=GT011(eligibility probe: an unparseable filter just means no pushdown; the full path classifies it)
            return False

    def _pushdown_eligible(self, q: dict) -> bool:
        """May a count answer from ``store.count`` (chunk pre-aggregates
        + internal row-scan fallback)? Caps and auths force the full
        query path — the ONE eligibility rule for the store-rung
        fallback, the brownout rung, and the non-resident route."""
        return (
            self._cap(q) is None
            and not self._auths(q)
            and hasattr(self.store, "count")
        )

    def _count_fallback(self, type_name: str, q: dict) -> int:
        """Store-rung count: the chunk-pushdown path when eligible
        (audited there), the full query path otherwise — exact either
        way, just not device-resident."""
        if self._pushdown_eligible(q):
            return int(
                self.store.count(type_name, q.get("cql", "INCLUDE"))
            )
        return len(self._query(type_name, q))

    def _count(self, type_name: str, q: dict) -> None:
        di = self._di(type_name)
        if di is not None:
            import time as _time

            from geomesa_tpu import resilience
            from geomesa_tpu.sched import FusableQuery

            t0 = _time.perf_counter()
            cql = q.get("cql", "INCLUDE")
            if resilience.brownout(self.scheduler) and \
                    self._pushdown_eligible(q) and \
                    self._agg_shaped(type_name, cql):
                # brownout rung: the admission queue is near its 429
                # cliff — answer from the store's chunk pre-aggregates
                # (exact; interior chunks never read) WITHOUT queueing
                # another device launch behind the saturated scheduler
                resilience.note_degraded("brownout-pushdown")
                n = int(self.store.count(type_name, cql))
                return self._json(200, {"count": n})
            fell: list = []

            def fallback():
                fell.append(True)
                return self._count_fallback(type_name, q)

            n = self._degradable(
                q, "device-launch-failed", fallback,
                fuse=FusableQuery(
                    di, cql, "count",
                    loose=self._loose(q), auths=self._auths(q),
                ),
            )
            cap = self._cap(q)
            if cap is not None:
                n = min(n, cap)  # the plain path counts the capped result
            if not fell:
                self._observe_resident(
                    type_name, cql, t0, _time.perf_counter(), n
                )
            return self._json(200, {"count": n})
        if self._pushdown_eligible(q):
            # store.count answers bbox+time counts from the v2 chunk
            # pre-aggregates (interior chunks never read) and falls back
            # to the row scan internally for anything else
            n = self._sched_run(
                q,
                fn=lambda: self.store.count(
                    type_name, q.get("cql", "INCLUDE")
                ),
            )
            return self._json(200, {"count": int(n)})
        res = self._sched_run(q, fn=lambda: self._query(type_name, q))
        self._json(200, {"count": len(res)})

    def _refresh(self, type_name: str, q: dict) -> None:
        """Restage a type's resident planes from the backing store (call
        after writes — the resident copy is a snapshot by design)."""
        if not self.resident:
            return self._json(
                400, {"error": "server is not running in resident mode"}
            )
        # freshness is decided under the construction lock (inside
        # _build_locked): a build that STARTED before the caller's writes
        # may finish after them, and skipping refresh on that stale
        # snapshot would lose the writes this endpoint exists to surface
        di, built_now = self._build_locked(type_name)
        if not built_now:  # a fresh build already staged post-write state
            di.refresh()
        self._json(200, {"refreshed": type_name, "rows": len(di)})

    def _stats(self, type_name: str, q: dict) -> None:
        spec = q.get("stats")
        if not spec:
            raise ValueError("stats endpoint needs stats=<Stat-DSL spec>")

        def store_work():
            # store rung: run_stats consults the chunk-stat pushdown
            # internally (PR 6) and row-scans what it cannot pre-answer
            from geomesa_tpu.process import run_stats
            from geomesa_tpu.query.plan import Query

            return run_stats(
                self.store,
                type_name,
                Query(
                    filter=q.get("cql", "INCLUDE"),
                    hints={"auths": self._auths(q)},
                ),
                spec,
            )

        di = self._di(type_name)
        if di is not None:
            import time as _time

            def device_work():
                t0 = _time.perf_counter()
                cql = q.get("cql", "INCLUDE")
                seq = di.stats(
                    cql, spec, loose=self._loose(q), auths=self._auths(q)
                )
                self._observe_resident(
                    type_name, cql, t0, _time.perf_counter(), 0
                )
                return seq

            seq = self._degradable(
                q, "device-launch-failed", store_work, fn=device_work
            )
        else:
            seq = self._sched_run(q, fn=store_work)
        self._json(200, seq.to_json())

    def _explain(self, type_name: str, q: dict) -> None:
        text = self.store.explain(type_name, q.get("cql", "INCLUDE"))
        self._send(200, text.encode("utf-8"), "text/plain")

    def _density(self, type_name: str, q: dict) -> None:
        from geomesa_tpu.process import density

        if "bbox" not in q:
            raise ValueError("density needs bbox=xmin,ymin,xmax,ymax")
        bbox = tuple(float(v) for v in q["bbox"].split(","))
        if len(bbox) != 4:
            raise ValueError("bbox must be xmin,ymin,xmax,ymax")
        width = int(q.get("width", 256))
        height = int(q.get("height", 256))
        from geomesa_tpu.geom import Envelope

        cql = q.get("cql", "INCLUDE")
        env = Envelope(*bbox)

        def store_work():
            # store rung: process.density consults the chunk-histogram
            # pushdown internally (PR 6 — mass-exact, cell placement
            # within coarse-cell tolerance on aligned rasters), records
            # its own metrics (observe_query) and honors the SAME auths
            # the resident path would have
            return density(
                self.store, type_name, cql, env, width, height,
                auths=self._auths(q),
            )

        di = self._di(type_name)
        if di is not None:
            from geomesa_tpu import resilience

            if resilience.brownout(self.scheduler) and \
                    self._agg_shaped(type_name, cql):
                # brownout rung: heatmaps are the classic overload
                # amplifier — answer from the chunk pre-aggregates
                # (within the PR 6 parity bounds) without queueing
                # another device launch behind the saturated scheduler
                resilience.note_degraded("brownout-pushdown")
                grid = store_work()
            else:
                import time as _time

                def device_work():
                    t0 = _time.perf_counter()
                    grid = di.density(
                        cql, env, width, height,
                        loose=self._loose(q), auths=self._auths(q),
                    )
                    if grid is None:
                        # filter/planes not device-expressible: a normal
                        # routing outcome, not a fault — resolved OUTSIDE
                        # _degradable so store-path errors are never
                        # retried/recorded under the DEVICE domain
                        return None
                    # unweighted: the grid mass IS the in-window count
                    self._observe_resident(
                        type_name, cql, t0, _time.perf_counter(),
                        int(round(float(grid.sum()))),
                    )
                    return grid

                grid = self._degradable(
                    q, "device-launch-failed", store_work, fn=device_work
                )
                if grid is None:
                    # the store resolution of a not-device-expressible
                    # filter is NORMAL routing, not an emergency rung:
                    # it goes back through the scheduler's admission
                    # control and deadline like any other unit of work
                    grid = self._sched_run(q, fn=store_work)
        else:
            grid = self._sched_run(q, fn=store_work)
        self._json(
            200,
            {
                "bbox": list(bbox),
                "width": width,
                "height": height,
                "counts": grid.tolist(),
            },
        )


#: the query endpoints the ledger/SLO layer labels by — anything else
#: (typo'd paths that 404, novel routes) collapses into "other" so a
#: URL scanner cannot mint unbounded metric series or ring keys
_KNOWN_ENDPOINTS = frozenset({
    "features", "count", "explain", "density", "stats", "refresh",
    "knn", "tube", "proximity", "capabilities", "append", "wal",
    "subscribe",
})


def _cost_endpoint(parts: list) -> str:
    ep = parts[0] if parts else "-"
    return ep if ep in _KNOWN_ENDPOINTS else "other"


def _query_shape(parts: list, q: dict) -> str:
    """The ledger's query-shape key: endpoint + the filter's leading
    predicate + the loose flag — coarse on purpose (per-tenant detail
    lives in the trace; the shape key exists to group compile/cost
    attribution by KERNEL family, the measurement substrate the
    shape-bucketing work needs). The ledger bounds the key space, so an
    adversarial filter cannot mint unbounded aggregates."""
    endpoint = _cost_endpoint(parts)
    cql = (q.get("cql") or "INCLUDE").strip()
    words = cql.split("(", 1)[0].split()
    head = (words[0].upper()[:16] if words else "INCLUDE") or "INCLUDE"
    if not head.replace("_", "").isalnum():
        head = "EXPR"
    shape = f"{endpoint}:{head}"
    if q.get("loose"):
        shape += ":loose"
    return shape


def _mesh_serving_enabled(mesh) -> bool:
    """Resolve the mesh-serving switch: an explicit ``make_server``
    argument wins, else the ``mesh.enabled`` conf key; either way the
    mesh path needs more than one visible device (a 1-device mesh is
    just single-chip serving with extra steps)."""
    from geomesa_tpu.conf import sys_prop

    if mesh is None:
        mesh = bool(sys_prop("mesh.enabled"))
    if not mesh:
        return False
    import jax

    n = int(sys_prop("mesh.devices")) or len(jax.devices())
    return min(n, len(jax.devices())) > 1


def _make_resident_index(store, type_name: str, mesh: bool,
                         streaming: bool = False):
    """One resident index, mesh-sharded when mesh serving is on. With
    the streaming live layer attached, the mesh flavor reserves
    ``stream.memtable.rows`` of plane headroom so streamed appends land
    as in-place deltas behind the validity plane instead of full mesh
    restages (the single-chip StreamingDeviceIndex delta-appends
    natively)."""
    if mesh:
        from geomesa_tpu.device_cache import ShardedDeviceIndex

        reserve = 0
        if streaming:
            from geomesa_tpu.conf import sys_prop

            reserve = int(sys_prop("stream.memtable.rows"))
        return ShardedDeviceIndex(
            store, type_name, z_planes=True, reserve_rows=reserve
        )
    from geomesa_tpu.device_cache import StreamingDeviceIndex

    capacity = None
    if streaming:
        # pre-size the delta buffers so the first streamed appends land
        # as in-place deltas instead of an immediate growth restage
        from geomesa_tpu.conf import sys_prop

        rows = getattr(store, "manifest_rows", None)
        capacity = int(sys_prop("stream.memtable.rows")) + (
            int(rows(type_name)) if rows else 0
        )
    return StreamingDeviceIndex(
        store, type_name, z_planes=True, capacity=capacity
    )


def make_server(
    store, host: str = "127.0.0.1", port: int = 0, resident: bool = False,
    warm: bool = False, sched=None, io=None, mesh: "bool | None" = None,
    stream: "bool | None" = None, replica=None,
):
    """Build a ThreadingHTTPServer bound to (host, port); port 0 picks an
    ephemeral port (see ``server.server_address``). ``resident=True``
    serves count/features/stats from device-pinned DeviceIndex caches
    (built lazily per type on first access). ``warm=True`` (resident
    only) stages every type and pre-compiles its serving kernels BEFORE
    the server accepts traffic (DeviceIndex.warmup), so no request pays
    a first-touch staging or XLA compile; with the persistent
    compilation cache (on by default, see jaxconf) a restarted server
    warms from disk in seconds.

    ``sched`` enables the device query scheduler (admission control +
    micro-batch scan fusion + per-tenant fairness, see
    :mod:`geomesa_tpu.sched`): pass ``True`` for the default
    :class:`~geomesa_tpu.sched.SchedConfig` or a config instance.
    Queue-full requests get HTTP 429 + ``Retry-After``; expired
    deadlines (``deadlineMs=``) get 504; ``/stats/sched`` reports queue
    depth, wait time and the fusion factor.

    ``io`` overrides the store's host-I/O pipeline for partition scans
    (a :class:`~geomesa_tpu.store.prefetch.PrefetchConfig` or an int
    worker count; None keeps the store's own / the ``io.*`` system
    properties). Prefetch health is visible on ``/metrics`` as the
    ``geomesa_io_*`` series.

    ``mesh`` (or the ``mesh.enabled`` conf key) shards each resident
    type across the serving device mesh by global Z-key range
    (ShardedDeviceIndex): every count/features/stats/density/kNN scan —
    including the scheduler's fused micro-batches — runs as ONE
    mesh-wide SPMD launch, ``/stats/mesh`` reports the topology and
    per-shard residency, and a failed shard launch degrades down the
    PR 7 ladder instead of failing the query. Needs > 1 visible jax
    device; topology comes from ``mesh.devices`` / ``mesh.replicas``.

    ``replica`` joins this server to a replication group: pass a
    :class:`~geomesa_tpu.replica.ReplicaConfig` (or a pre-built
    :class:`~geomesa_tpu.replica.Replicator`). Leaders serve the WAL
    ship endpoint (``GET /wal/<type>``); followers tail the leader,
    apply records at the LEADER's seqs through the replay-idempotent
    live layer, reject POST ``/append`` with 503 + the leader's URL,
    and promote within ``replica.failover.s`` when the leader's lease
    expires. Requires the streaming live layer (the WAL is the thing
    being shipped).

    The persistent XLA compile cache is wired here from the
    ``compile.cache.dir`` conf key (serving is compile-heavy; a
    restarted server warms from disk) — hit/miss counts ride
    ``/stats`` and the ``geomesa_compile_cache_*`` metrics."""
    import os as _os

    from geomesa_tpu import ledger as _ledger
    from geomesa_tpu import slo as _slo
    from geomesa_tpu.jaxconf import enable_compilation_cache
    from geomesa_tpu.pyarrow_compat import preload_pyarrow
    from geomesa_tpu.tracing import TRACER

    enable_compilation_cache()
    _ledger.install()  # compile-time attribution via jax.monitoring
    mesh_on = resident and _mesh_serving_enabled(mesh)
    preload_pyarrow()  # handler threads serve Arrow; see pyarrow_compat
    if io is not None and hasattr(store, "io"):
        store.io = io
    # the slow-query log lives next to the store's audit log
    # (<root>/_slow_queries.jsonl); memory stores keep traces ring-only
    root_dir = getattr(store, "root", None)
    if root_dir:
        TRACER.slow_log_path = _os.path.join(
            str(root_dir), "_slow_queries.jsonl"
        )
    scheduler = None
    if sched:
        from geomesa_tpu.sched import QueryScheduler, SchedConfig

        # sched=True (no explicit config) defers to QueryScheduler's
        # default -- SchedConfig.from_props(), so the sched.* conf keys
        # / GEOMESA_TPU_SCHED_* env overrides actually apply here
        scheduler = QueryScheduler(
            sched if isinstance(sched, SchedConfig) else None
        )
    # streaming live layer: wrap the store so every serving path —
    # endpoints AND resident DeviceIndex staging — reads the merged
    # (memtable ∪ partitions) view; POST /append goes WAL-first and
    # serves immediately. Needs a real filesystem store (the WAL and
    # crash-consistent compaction live under its root).
    stream_layer = None
    from geomesa_tpu.store.stream import StreamingStore, streaming_enabled

    stream_on = streaming_enabled() if stream is None else bool(stream)
    if stream_on:
        if not (root_dir and hasattr(store, "_exclusive")):
            import warnings

            warnings.warn(
                "streaming live layer needs a FileSystemDataStore "
                "(a WAL directory under the store root); stream.enabled "
                "ignored for this store"
            )
        else:
            stream_layer = StreamingStore(store, scheduler=scheduler)
            store = stream_layer
    from geomesa_tpu.locking import checked_lock

    replicator = None
    if replica is not None:
        from geomesa_tpu.replica import ReplicaConfig, Replicator

        if stream_layer is None:
            raise ValueError(
                "replication needs the streaming live layer (the WAL is "
                "what gets shipped); pass stream=True / stream.enabled"
            )
        if isinstance(replica, Replicator):
            replicator = replica
        elif isinstance(replica, ReplicaConfig):
            replicator = Replicator(replica)
        else:
            raise TypeError(
                "replica must be a ReplicaConfig or Replicator, "
                f"got {type(replica).__name__}"
            )
        replicator.attach(stream_layer)
    # continuous-query push tier: rides the live layer (the data WAL
    # seq is the delivery cursor; no WAL, no cursor). The hub wires its
    # own seq listener and retention floor into the stream here.
    pubsub_hub = None
    if stream_layer is not None:
        from geomesa_tpu.pubsub import PubSubHub

        pubsub_hub = PubSubHub(stream_layer, sched=scheduler)
        if replicator is not None:
            # followers tail /wal/_pubsub alongside the data types and
            # a promotion re-arms matching from the replicated registry
            replicator.pubsub = pubsub_hub
            # under replica.ack=replica the leader's hub must not push
            # an alert until the record is replication-durable: a
            # failover could void the unreplicated tail and reassign
            # its seqs, silently breaking the cursor resume
            pubsub_hub.commit_gate = replicator.commit_floor
    from geomesa_tpu.conf import sys_prop as _sys_prop

    handler = type(
        "BoundHandler",
        (_Handler,),
        {
            "store": store,
            "resident": resident,
            "mesh": mesh_on,
            "scheduler": scheduler,
            "stream": stream_layer,
            "replica": replicator,
            "pubsub": pubsub_hub,
            # idle keep-alive bound, declared (GT008) instead of the
            # class-default literal; router→backend pooled connections
            # read the same key
            "timeout": float(_sys_prop("http.keepalive.s")),
            "_resident_cache": {},
            # blocking_ok: first-touch resident builds hold it across
            # store reads + device staging BY DESIGN (a duplicate build
            # would stage the dataset into device memory twice)
            "_resident_lock": checked_lock(
                "server.resident", blocking_ok=True
            ),
        },
    )
    if resident and warm:
        import warnings

        for tn in store.type_names:
            # a type that fails to stage (e.g. device OOM) must not keep
            # the OTHER types from serving — same isolation the lazy
            # first-touch path gives: that type just isn't resident
            try:
                di = _make_resident_index(
                    store, tn, mesh_on,
                    streaming=stream_layer is not None,
                )
            except Exception as e:
                warnings.warn(f"warm staging failed for {tn!r}: {e!r}")
                continue
            handler._resident_cache[tn] = di
        # staging is synchronous (the resident cache is populated when
        # make_server returns); the AOT pre-compile over the bucket x
        # kernel-family set moves to a bounded background pool charged
        # to the _system ledger tenant, with /readyz gating or stamping
        # `warming` per compile.warmup.gate — a fleet rolling restart
        # (wait_ready) therefore never routes traffic at a cold process
        if handler._resident_cache:
            if bool(_sys_prop("compile.warmup.enabled")):
                from geomesa_tpu import warmup as _warmup

                handler._warmup_started = True
                _warmup.start(dict(handler._resident_cache))
            else:
                # warmup.enabled=false keeps the pre-ladder contract:
                # base kernels compile inline before traffic is accepted
                for tn, di in handler._resident_cache.items():
                    try:
                        di.warmup()
                    except Exception as e:  # pragma: no cover - defensive
                        warnings.warn(f"warmup failed for {tn!r}: {e!r}")
    # flight recorder: bundles land next to the store's data (memory
    # stores have no root — the recorder stays disabled unless a test
    # configured a directory of its own); sched/store/mesh snapshots
    # register as bundle providers
    providers: dict = {}
    if scheduler is not None:
        providers["sched"] = scheduler.snapshot
    if hasattr(store, "store_stats"):
        providers["store"] = store.store_stats

    def _mesh_snapshot(h=handler):
        doc = {"enabled": bool(h.mesh), "types": {}}
        for name, di in list(h._resident_cache.items()):
            stats = getattr(di, "mesh_stats", None)
            if stats is not None:
                doc["types"][name] = stats()
        return doc

    providers["mesh"] = _mesh_snapshot
    if pubsub_hub is not None:
        providers["pubsub"] = pubsub_hub.stats
    if stream_layer is not None:
        providers["stream"] = stream_layer.stream_stats

        def _stream_delta(tname, batch, h=handler):
            """Per-append incremental resident refresh: fold the acked
            batch into an already-staged index's planes (delta path —
            no restage on the ack path). The cache probe happens UNDER
            the construction lock: an append acked between a first-
            touch build's staging snapshot and its cache publication
            must wait for the build and then deliver (refresh_delta is
            re-delivery-safe — duplicate fids force a restage through
            the merged view), or the staged index would be missing
            acked rows with no future delta to repair it. A failure
            evicts the index so the next query restages a correct
            copy; the streaming layer stamps ``ingest-degraded`` and
            the rows keep serving from the merged store path either
            way."""
            with h._resident_lock:
                di = h._resident_cache.get(tname)
            if di is None:
                return  # first query stages the merged view lazily
            try:
                di.refresh_delta(batch)
            except Exception:
                h._resident_cache.pop(tname, None)
                raise

        stream_layer.add_delta_listener(_stream_delta)
    _slo.FLIGHTREC.configure(
        _os.path.join(str(root_dir), "_flightrec")
        if root_dir
        else _slo.FLIGHTREC.dir,
        providers=providers,
    )
    server = _GeomesaHTTPServer((host, port), handler)
    server.scheduler = scheduler  # callers may inspect / shut down
    server.store = store  # the draining shutdown flushes its audit log
    server.stream_layer = stream_layer  # closed by the draining shutdown
    server.pubsub = pubsub_hub  # closed (before the stream) at drain
    if replicator is not None:
        # the bound ephemeral port is only known NOW — default the
        # advertised URL from it so tests/CLI may pass port=0
        if not replicator.cfg.self_url:
            addr = server.server_address
            replicator.cfg.self_url = f"http://{addr[0]}:{addr[1]}"
        if replicator.cfg.role == "leader" and not replicator._leader_url:
            replicator._leader_url = replicator.cfg.self_url
        server.replica = replicator
        replicator.start()  # follower tail thread spawns here
    from geomesa_tpu.analysis import compilecheck

    if compilecheck.enabled():
        # serving is live from here: every backend compile must carry an
        # allowed compile_scope (analysis/compilecheck.py)
        server._ccheck_live = True
        compilecheck.CHECKER.serving_up()
    return server


def serve_background(
    store, host: str = "127.0.0.1", port: int = 0, resident: bool = False,
    warm: bool = False, sched=None, io=None, mesh: "bool | None" = None,
    stream: "bool | None" = None, replica=None,
):
    """Start serving on a daemon thread; returns (server, thread). Stop
    with ``server.shutdown()``."""
    server = make_server(
        store, host, port, resident=resident, warm=warm, sched=sched,
        io=io, mesh=mesh, stream=stream, replica=replica,
    )
    thread = spawn_thread(
        server.serve_forever, name="geomesa-serve", context=False
    )
    thread.start()
    return server, thread
