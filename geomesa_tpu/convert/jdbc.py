"""SQL/JDBC converter: ingest from a relational query.

Ref role: geomesa-convert-jdbc JdbcConverter [UNVERIFIED - empty reference
mount]: connect with a JDBC URL, run a statement, and bind result columns
positionally -- ``$0`` is the row id and ``$1..$N`` are SELECT columns
(1-based, like the delimited converter). Here the driver is stdlib
``sqlite3`` (the only RDBMS in the image); the config's ``connection`` is
a sqlite path or URI.

    {
      "type": "jdbc",
      "connection": "file.db",
      "id-field": "$1::string",
      "fields": [
        {"name": "name", "transform": "$2"},
        {"name": "geom", "transform": "point($3::double, $4::double)"},
      ],
    }

``process(sql)`` takes the SELECT statement (the reference streams the
input file as statements; passing the query directly is the Python-native
shape).
"""

from __future__ import annotations

import sqlite3

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult, _rowwise
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch


class JdbcConverter:
    def __init__(self, config: dict, sft):
        self.sft = sft
        self.connection = config["connection"]
        opts = config.get("options", {})
        self.error_mode = opts.get("error-mode", "skip-bad-records")
        self.fields = [
            (f["name"], parse_expression(f["transform"])) for f in config["fields"]
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, sql: str) -> ConvertResult:
        conn = sqlite3.connect(self.connection)
        try:
            rows = conn.execute(sql).fetchall()
        finally:
            conn.close()
        cols: dict = {}
        width = len(rows[0]) if rows else 0
        for i in range(width):
            cols[str(i + 1)] = np.array([r[i] for r in rows], dtype=object)
        cols["0"] = np.array(
            [" ".join(str(v) for v in r) for r in rows], dtype=object
        )
        out = {}
        failed = 0
        ok = np.ones(len(rows), dtype=bool)
        for name, expr in self.fields:
            try:
                out[name] = expr(cols)
            except Exception:
                if self.error_mode == "raise-errors":
                    raise
                out[name], ok = _rowwise(expr, cols, ok)
        if not np.all(ok):
            failed = int((~ok).sum())
            keep = np.nonzero(ok)[0]
            out = {k: (v[keep] if len(v) == len(ok) else v) for k, v in out.items()}
            cols = {k: v[keep] for k, v in cols.items()}
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), failed)
