"""ESRI Shapefile converter.

Ref role: geomesa-convert-shp ShapefileConverter [UNVERIFIED - empty
reference mount]: the reference wraps GeoTools' shapefile datastore; here
the .shp (geometry) and .dbf (attribute) binary formats are parsed
directly -- point / multipoint / polyline / polygon shapes, dBASE III
C/N/F/L/D field types. Attribute columns bind by dbf field name (``$NAME``)
and the shape binds as ``$geom``; with no ``fields`` config the dbf columns
map to same-named SFT attributes.

    {
      "type": "shp",
      "id-field": "$ID",
      "fields": [
        {"name": "name", "transform": "$NAME"},
        {"name": "geom", "transform": "$geom"},
      ],
    }

``process(path_or_bytes, dbf=None)`` takes the .shp path (the sibling .dbf
is discovered automatically) or raw bytes for both.
"""

from __future__ import annotations

import datetime
import os
import struct

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.geom import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)


def _ring_is_cw(ring: np.ndarray) -> bool:
    # shoelace: shapefile outer rings are clockwise
    x, y = ring[:, 0], ring[:, 1]
    return float(np.sum((x[1:] - x[:-1]) * (y[1:] + y[:-1]))) > 0


def _parse_poly_parts(buf: bytes, off: int):
    n_parts, n_points = struct.unpack_from("<ii", buf, off + 36)
    parts = struct.unpack_from(f"<{n_parts}i", buf, off + 44)
    pts = np.frombuffer(
        buf, dtype="<f8", count=n_points * 2, offset=off + 44 + 4 * n_parts
    ).reshape(n_points, 2)
    bounds = list(parts) + [n_points]
    return [pts[bounds[i] : bounds[i + 1]] for i in range(n_parts)]


def read_shp(data: bytes) -> list:
    """Parse .shp bytes into a list of Geometry | None (null shapes)."""
    if struct.unpack_from(">i", data, 0)[0] != 9994:
        raise ValueError("not a shapefile (bad magic)")
    flen = struct.unpack_from(">i", data, 24)[0] * 2  # 16-bit words
    geoms = []
    off = 100
    while off < flen:
        _, content_len = struct.unpack_from(">ii", data, off)
        rec = off + 8
        shape_type = struct.unpack_from("<i", data, rec)[0]
        if shape_type == 0:
            geoms.append(None)
        elif shape_type == 1:  # Point
            x, y = struct.unpack_from("<dd", data, rec + 4)
            geoms.append(Point(x, y))
        elif shape_type == 8:  # MultiPoint
            (n,) = struct.unpack_from("<i", data, rec + 36)
            pts = np.frombuffer(data, "<f8", n * 2, rec + 40).reshape(n, 2)
            geoms.append(MultiPoint(tuple(Point(*p) for p in pts)))
        elif shape_type == 3:  # PolyLine
            lines = [LineString(p) for p in _parse_poly_parts(data, rec)]
            geoms.append(lines[0] if len(lines) == 1 else MultiLineString(tuple(lines)))
        elif shape_type == 5:  # Polygon: CW rings = shells, CCW = holes
            rings = _parse_poly_parts(data, rec)
            polys: list = []
            for r in rings:
                if _ring_is_cw(r) or not polys:
                    polys.append(Polygon(r))
                else:
                    last = polys[-1]
                    polys[-1] = Polygon(last.shell, last.holes + (r,))
            geoms.append(polys[0] if len(polys) == 1 else MultiPolygon(tuple(polys)))
        else:
            raise ValueError(f"unsupported shape type {shape_type}")
        off = rec + content_len * 2
    return geoms


def read_dbf(data: bytes) -> "tuple[list, list[list]]":
    """Parse .dbf bytes -> (field names, row values)."""
    n_records, header_size, record_size = struct.unpack_from("<iHH", data, 4)
    fields = []  # (name, type, length, decimals)
    off = 32
    while off < header_size - 1 and data[off] != 0x0D:
        name = data[off : off + 11].split(b"\x00")[0].decode("ascii")
        ftype = chr(data[off + 11])
        length = data[off + 16]
        decimals = data[off + 17]
        fields.append((name, ftype, length, decimals))
        off += 32
    rows = []
    off = header_size
    for _ in range(n_records):
        if off + record_size > len(data):
            break
        rec = data[off : off + record_size]
        off += record_size
        if rec[:1] == b"*":  # deleted
            continue
        vals = []
        pos = 1
        for name, ftype, length, decimals in fields:
            raw = rec[pos : pos + length].decode("latin-1").strip()
            pos += length
            if ftype in ("N", "F"):
                if not raw:
                    vals.append(None)
                elif decimals or ftype == "F" or "." in raw:
                    vals.append(float(raw))
                else:
                    vals.append(int(raw))
            elif ftype == "L":
                vals.append(raw.upper() in ("T", "Y"))
            elif ftype == "D" and raw:
                # YYYYMMDD -> epoch ms
                iso = f"{raw[:4]}-{raw[4:6]}-{raw[6:8]}"
                vals.append(int(np.datetime64(iso, "ms").astype(np.int64)))
            else:
                vals.append(raw or None)
        rows.append(vals)
    return [f[0] for f in fields], rows


# -- writer (export side; ref geomesa-tools ExportCommand's shp format) ------


def _close_ring(r: np.ndarray) -> np.ndarray:
    r = np.asarray(r, np.float64)
    if not np.array_equal(r[0], r[-1]):
        r = np.concatenate([r, r[:1]])
    return r


def _oriented(r: np.ndarray, cw: bool) -> np.ndarray:
    return r if _ring_is_cw(r) == cw else r[::-1]


def _poly_record(shape_type: int, rings: list) -> bytes:
    pts = np.concatenate(rings)
    parts = np.cumsum([0] + [len(r) for r in rings[:-1]]).astype("<i4")
    head = struct.pack(
        "<i4dii",
        shape_type,
        float(pts[:, 0].min()), float(pts[:, 1].min()),
        float(pts[:, 0].max()), float(pts[:, 1].max()),
        len(rings), len(pts),
    )
    return head + parts.tobytes() + pts.astype("<f8").tobytes()


def _geom_record(g) -> bytes:
    if g is None:
        return struct.pack("<i", 0)
    if isinstance(g, Point):
        return struct.pack("<idd", 1, g.x, g.y)
    if isinstance(g, MultiPoint):
        pts = np.array([[p.x, p.y] for p in g.points], np.float64)
        return struct.pack(
            "<i4di",
            8,
            float(pts[:, 0].min()), float(pts[:, 1].min()),
            float(pts[:, 0].max()), float(pts[:, 1].max()),
            len(pts),
        ) + pts.astype("<f8").tobytes()
    if isinstance(g, LineString):
        return _poly_record(3, [np.asarray(g.coords, np.float64)])
    if isinstance(g, MultiLineString):
        return _poly_record(
            3, [np.asarray(l.coords, np.float64) for l in g.lines]
        )
    if isinstance(g, (Polygon, MultiPolygon)):
        polys = g.polygons if isinstance(g, MultiPolygon) else (g,)
        rings = []
        for p in polys:
            # shapefile convention: shells CLOCKWISE, holes CCW
            rings.append(_oriented(_close_ring(p.shell), cw=True))
            for h in p.holes:
                rings.append(_oriented(_close_ring(h), cw=False))
        return _poly_record(5, rings)
    raise ValueError(f"cannot write {type(g).__name__} to a shapefile")


def _dbf_fields(sft):
    """[(name10, type, length, decimals, attr)] for the non-geometry
    attributes (dbf field names cap at 10 chars; collisions raise)."""
    out = []
    seen = set()
    for a in sft.attributes:
        if a.is_geometry:
            continue
        name = a.name[:10]
        if name in seen:
            raise ValueError(
                f"dbf field name collision after 10-char truncation: {name!r}"
            )
        seen.add(name)
        if a.type_name == "Date":
            out.append((name, "D", 8, 0, a.name))
        elif a.type_name in ("Integer", "Int", "Long"):
            out.append((name, "N", 18, 0, a.name))
        elif a.type_name in ("Float", "Double"):
            out.append((name, "N", 18, 6, a.name))
        elif a.type_name == "Boolean":
            out.append((name, "L", 1, 0, a.name))
        else:
            out.append((name, "C", 254, 0, a.name))
    return out


def write_shp(batch) -> "tuple[bytes, bytes, bytes]":
    """FeatureBatch -> (.shp, .shx, .dbf) bytes — the write side of this
    converter (the reference exports shapefiles through GeoTools; here
    the three sibling files are emitted directly and round-trip through
    :func:`read_shp` / :func:`read_dbf`)."""
    geom = batch.sft.geom_field
    col = batch.columns[geom] if geom else None
    records = []
    shape_type = None  # resolved from the first non-null geometry
    for i in range(len(batch)):
        if col is None:
            g = None
        elif col.dtype != object:
            g = Point(float(col[i, 0]), float(col[i, 1]))
        else:
            g = col[i]
        rec = _geom_record(g)
        st = struct.unpack_from("<i", rec, 0)[0]
        if st:
            if shape_type is not None and shape_type != st:
                raise ValueError(
                    "a shapefile holds ONE shape type; batch mixes "
                    f"types {shape_type} and {st}"
                )
            shape_type = st
        records.append(rec)
    if shape_type is None:
        shape_type = 1  # all-null batch: header still needs a type

    # .shp + .shx (chunk lists + join: bytes += is quadratic in records)
    body_parts: list = []
    shx_parts: list = []
    offset_words = 50  # header = 100 bytes
    for idx, rec in enumerate(records, start=1):
        clen = len(rec) // 2
        body_parts.append(struct.pack(">ii", idx, clen))
        body_parts.append(rec)
        shx_parts.append(struct.pack(">ii", offset_words, clen))
        offset_words += 4 + clen
    body = b"".join(body_parts)
    shx_body = b"".join(shx_parts)

    bbox = (0.0, 0.0, 0.0, 0.0)
    if col is not None and len(batch):
        if col.dtype != object:
            xs, ys = col[:, 0], col[:, 1]
            bbox = (
                float(xs.min()), float(ys.min()),
                float(xs.max()), float(ys.max()),
            )
        else:
            # per-geometry envelopes, skipping null shapes (which the
            # record loop above writes as type-0 records)
            envs = [g.envelope for g in col if g is not None]
            if envs:
                bbox = (
                    min(e.xmin for e in envs), min(e.ymin for e in envs),
                    max(e.xmax for e in envs), max(e.ymax for e in envs),
                )

    def header(total_bytes: int) -> bytes:
        return (
            struct.pack(">i5i", 9994, 0, 0, 0, 0, 0)
            + struct.pack(">i", total_bytes // 2)
            + struct.pack("<ii", 1000, shape_type)
            + struct.pack("<4d", *bbox)
            + struct.pack("<4d", 0.0, 0.0, 0.0, 0.0)  # z/m ranges
        )

    shp = header(100 + len(body)) + body
    shx = header(100 + len(shx_body)) + shx_body

    # .dbf
    fields = _dbf_fields(batch.sft)
    record_size = 1 + sum(f[2] for f in fields)
    header_size = 32 + 32 * len(fields) + 1
    dbf = bytearray()
    # last-update date: dBASE packs the year as years-since-1900 (so a
    # raw 26 would decode as 1926); derive YY/MM/DD from today, clamped
    # to the byte range for dates past 2155
    today = datetime.date.today()
    dbf += struct.pack(
        "<4BiHH20x", 0x03, min(today.year - 1900, 255), today.month,
        today.day, len(batch), header_size, record_size,
    )
    for name, ftype, length, decimals, _ in fields:
        dbf += struct.pack(
            "<11sc4xBB14x", name.encode("ascii"), ftype.encode("ascii"),
            length, decimals,
        )
    dbf += b"\x0d"
    for i in range(len(batch)):
        dbf += b" "
        for name, ftype, length, decimals, attr in fields:
            v = batch.columns[attr][i]
            v = v.item() if hasattr(v, "item") else v
            if ftype == "D":
                s = (
                    str(np.datetime64(int(v), "ms").astype("datetime64[D]"))
                    .replace("-", "")
                    if v is not None
                    else ""
                )
            elif ftype == "N":
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    s = ""
                elif decimals:
                    s = f"{float(v):.{decimals}f}"
                else:
                    s = str(int(v))
                if len(s) > length:
                    # right-truncation would silently drop trailing
                    # DIGITS (1e18 -> 1e17): refuse instead
                    raise ValueError(
                        f"value {v!r} of field {name!r} does not fit the "
                        f"dbf numeric width ({length} chars)"
                    )
                s = s.rjust(length)
            elif ftype == "L":
                s = "T" if v else "F"
            else:
                s = "" if v is None else str(v)
            raw = s.encode("latin-1", "replace")[:length].ljust(length)
            dbf += raw
    dbf += b"\x1a"
    return shp, shx, bytes(dbf)


def write_shapefile(batch, path: str) -> None:
    """Write ``batch`` as the shapefile triplet next to ``path`` (given
    ``x.shp``, also writes ``x.shx`` and ``x.dbf``)."""
    base = os.path.splitext(os.fspath(path))[0]
    shp, shx, dbf = write_shp(batch)
    for ext, data in ((".shp", shp), (".shx", shx), (".dbf", dbf)):
        with open(base + ext, "wb") as fh:
            fh.write(data)


class ShapefileConverter:
    binary = True  # CLI opens input files in 'rb' mode

    def __init__(self, config: dict, sft):
        self.sft = sft
        self.fields = [
            (f["name"], parse_expression(f["transform"]))
            for f in config.get("fields", [])
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, shp, dbf=None) -> ConvertResult:
        if isinstance(shp, (str, os.PathLike)):
            path = os.fspath(shp)
            with open(path, "rb") as fh:
                shp_bytes = fh.read()
            if dbf is None:
                dbf_path = os.path.splitext(path)[0] + ".dbf"
                if os.path.exists(dbf_path):
                    with open(dbf_path, "rb") as fh:
                        dbf = fh.read()
        else:
            shp_bytes = shp
        geoms = read_shp(shp_bytes)
        cols: dict = {"geom": np.array(geoms, dtype=object)}
        if dbf is not None:
            names, rows = read_dbf(dbf)
            if len(rows) != len(geoms):
                raise ValueError(
                    f"dbf has {len(rows)} rows but shp has {len(geoms)} shapes"
                )
            for i, name in enumerate(names):
                cols[name] = np.array([r[i] for r in rows], dtype=object)
        if self.fields:
            out = {name: expr(cols) for name, expr in self.fields}
        else:  # default: same-named dbf columns + the shape column
            out = {
                a.name: cols[a.name]
                for a in self.sft.attributes
                if a.name in cols
            }
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), 0)
