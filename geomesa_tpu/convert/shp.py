"""ESRI Shapefile converter.

Ref role: geomesa-convert-shp ShapefileConverter [UNVERIFIED - empty
reference mount]: the reference wraps GeoTools' shapefile datastore; here
the .shp (geometry) and .dbf (attribute) binary formats are parsed
directly -- point / multipoint / polyline / polygon shapes, dBASE III
C/N/F/L/D field types. Attribute columns bind by dbf field name (``$NAME``)
and the shape binds as ``$geom``; with no ``fields`` config the dbf columns
map to same-named SFT attributes.

    {
      "type": "shp",
      "id-field": "$ID",
      "fields": [
        {"name": "name", "transform": "$NAME"},
        {"name": "geom", "transform": "$geom"},
      ],
    }

``process(path_or_bytes, dbf=None)`` takes the .shp path (the sibling .dbf
is discovered automatically) or raw bytes for both.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.geom import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)


def _ring_is_cw(ring: np.ndarray) -> bool:
    # shoelace: shapefile outer rings are clockwise
    x, y = ring[:, 0], ring[:, 1]
    return float(np.sum((x[1:] - x[:-1]) * (y[1:] + y[:-1]))) > 0


def _parse_poly_parts(buf: bytes, off: int):
    n_parts, n_points = struct.unpack_from("<ii", buf, off + 36)
    parts = struct.unpack_from(f"<{n_parts}i", buf, off + 44)
    pts = np.frombuffer(
        buf, dtype="<f8", count=n_points * 2, offset=off + 44 + 4 * n_parts
    ).reshape(n_points, 2)
    bounds = list(parts) + [n_points]
    return [pts[bounds[i] : bounds[i + 1]] for i in range(n_parts)]


def read_shp(data: bytes) -> list:
    """Parse .shp bytes into a list of Geometry | None (null shapes)."""
    if struct.unpack_from(">i", data, 0)[0] != 9994:
        raise ValueError("not a shapefile (bad magic)")
    flen = struct.unpack_from(">i", data, 24)[0] * 2  # 16-bit words
    geoms = []
    off = 100
    while off < flen:
        _, content_len = struct.unpack_from(">ii", data, off)
        rec = off + 8
        shape_type = struct.unpack_from("<i", data, rec)[0]
        if shape_type == 0:
            geoms.append(None)
        elif shape_type == 1:  # Point
            x, y = struct.unpack_from("<dd", data, rec + 4)
            geoms.append(Point(x, y))
        elif shape_type == 8:  # MultiPoint
            (n,) = struct.unpack_from("<i", data, rec + 36)
            pts = np.frombuffer(data, "<f8", n * 2, rec + 40).reshape(n, 2)
            geoms.append(MultiPoint(tuple(Point(*p) for p in pts)))
        elif shape_type == 3:  # PolyLine
            lines = [LineString(p) for p in _parse_poly_parts(data, rec)]
            geoms.append(lines[0] if len(lines) == 1 else MultiLineString(tuple(lines)))
        elif shape_type == 5:  # Polygon: CW rings = shells, CCW = holes
            rings = _parse_poly_parts(data, rec)
            polys: list = []
            for r in rings:
                if _ring_is_cw(r) or not polys:
                    polys.append(Polygon(r))
                else:
                    last = polys[-1]
                    polys[-1] = Polygon(last.shell, last.holes + (r,))
            geoms.append(polys[0] if len(polys) == 1 else MultiPolygon(tuple(polys)))
        else:
            raise ValueError(f"unsupported shape type {shape_type}")
        off = rec + content_len * 2
    return geoms


def read_dbf(data: bytes) -> "tuple[list, list[list]]":
    """Parse .dbf bytes -> (field names, row values)."""
    n_records, header_size, record_size = struct.unpack_from("<iHH", data, 4)
    fields = []  # (name, type, length, decimals)
    off = 32
    while off < header_size - 1 and data[off] != 0x0D:
        name = data[off : off + 11].split(b"\x00")[0].decode("ascii")
        ftype = chr(data[off + 11])
        length = data[off + 16]
        decimals = data[off + 17]
        fields.append((name, ftype, length, decimals))
        off += 32
    rows = []
    off = header_size
    for _ in range(n_records):
        if off + record_size > len(data):
            break
        rec = data[off : off + record_size]
        off += record_size
        if rec[:1] == b"*":  # deleted
            continue
        vals = []
        pos = 1
        for name, ftype, length, decimals in fields:
            raw = rec[pos : pos + length].decode("latin-1").strip()
            pos += length
            if ftype in ("N", "F"):
                if not raw:
                    vals.append(None)
                elif decimals or ftype == "F" or "." in raw:
                    vals.append(float(raw))
                else:
                    vals.append(int(raw))
            elif ftype == "L":
                vals.append(raw.upper() in ("T", "Y"))
            elif ftype == "D" and raw:
                # YYYYMMDD -> epoch ms
                iso = f"{raw[:4]}-{raw[4:6]}-{raw[6:8]}"
                vals.append(int(np.datetime64(iso, "ms").astype(np.int64)))
            else:
                vals.append(raw or None)
        rows.append(vals)
    return [f[0] for f in fields], rows


class ShapefileConverter:
    binary = True  # CLI opens input files in 'rb' mode

    def __init__(self, config: dict, sft):
        self.sft = sft
        self.fields = [
            (f["name"], parse_expression(f["transform"]))
            for f in config.get("fields", [])
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, shp, dbf=None) -> ConvertResult:
        if isinstance(shp, (str, os.PathLike)):
            path = os.fspath(shp)
            with open(path, "rb") as fh:
                shp_bytes = fh.read()
            if dbf is None:
                dbf_path = os.path.splitext(path)[0] + ".dbf"
                if os.path.exists(dbf_path):
                    with open(dbf_path, "rb") as fh:
                        dbf = fh.read()
        else:
            shp_bytes = shp
        geoms = read_shp(shp_bytes)
        cols: dict = {"geom": np.array(geoms, dtype=object)}
        if dbf is not None:
            names, rows = read_dbf(dbf)
            if len(rows) != len(geoms):
                raise ValueError(
                    f"dbf has {len(rows)} rows but shp has {len(geoms)} shapes"
                )
            for i, name in enumerate(names):
                cols[name] = np.array([r[i] for r in rows], dtype=object)
        if self.fields:
            out = {name: expr(cols) for name, expr in self.fields}
        else:  # default: same-named dbf columns + the shape column
            out = {
                a.name: cols[a.name]
                for a in self.sft.attributes
                if a.name in cols
            }
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), 0)
