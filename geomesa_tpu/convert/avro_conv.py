"""Avro converter: ingest from Avro object container files.

Ref role: geomesa-convert-avro AvroConverter [UNVERIFIED - empty reference
mount]. Unlike ``features/avro.py`` (our own export format, which embeds
the SFT spec), this reads *arbitrary* Avro container files: a generic
decoder walks the embedded writer schema (records of scalars, nullable
unions, arrays of scalars) and binds each top-level field as ``$name`` for
the field transforms. The reference uses avro-java GenericRecord + an
``avroPath`` language; top-level-field binding covers the same configs
without a second path DSL.

    {
      "type": "avro",
      "id-field": "$id",
      "fields": [
        {"name": "geom", "transform": "point($lon, $lat)"},
        {"name": "dtg",  "transform": "millisToDate($ts)"},
      ],
    }
"""

from __future__ import annotations

import io
import json
import struct

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.avro import MAGIC, read_bytes, read_long
from geomesa_tpu.features.batch import FeatureBatch


def _decoder(schema):
    """Build value-decoder(buf) for an Avro schema node (generic subset)."""
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return lambda buf: None
        if t == "boolean":
            return lambda buf: buf.read(1) == b"\x01"
        if t in ("int", "long"):
            return read_long
        if t == "float":
            return lambda buf: struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return lambda buf: struct.unpack("<d", buf.read(8))[0]
        if t == "string":
            return lambda buf: read_bytes(buf).decode()
        if t == "bytes":
            return read_bytes
        raise ValueError(f"unsupported avro type {t!r}")
    if isinstance(schema, list):  # union: tag = branch index
        branches = [_decoder(s) for s in schema]

        def dec_union(buf, branches=branches):
            return branches[read_long(buf)](buf)

        return dec_union
    t = schema.get("type")
    if t in ("record",):
        fields = [(f["name"], _decoder(f["type"])) for f in schema["fields"]]

        def dec_record(buf, fields=fields):
            return {name: d(buf) for name, d in fields}

        return dec_record
    if t == "array":
        item = _decoder(schema["items"])

        def dec_array(buf, item=item):
            out = []
            while True:
                n = read_long(buf)
                if n == 0:
                    return out
                if n < 0:
                    n = -n
                    read_long(buf)  # skip byte-size hint
                out.extend(item(buf) for _ in range(n))

        return dec_array
    if t == "enum":
        symbols = schema["symbols"]
        return lambda buf, symbols=symbols: symbols[read_long(buf)]
    if t == "fixed":
        size = int(schema["size"])
        return lambda buf, size=size: buf.read(size)
    if t in ("map",):
        val = _decoder(schema["values"])

        def dec_map(buf, val=val):
            out = {}
            while True:
                n = read_long(buf)
                if n == 0:
                    return out
                if n < 0:
                    n = -n
                    read_long(buf)
                for _ in range(n):
                    out[read_bytes(buf).decode()] = val(buf)

        return dec_map
    return _decoder(t)  # {"type": "string", ...} wrapper


def read_generic_avro(data: bytes) -> list:
    """All records of a container file as a list of dicts."""
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError("not an Avro object container file")
    meta: dict = {}
    while True:
        n = read_long(buf)
        if n == 0:
            break
        if n < 0:
            n = -n
            read_long(buf)
        for _ in range(n):
            k = read_bytes(buf).decode()
            meta[k] = read_bytes(buf)
    if meta.get("avro.codec", b"null") not in (b"null", b""):
        raise ValueError(f"unsupported avro codec {meta['avro.codec']!r}")
    schema = json.loads(meta["avro.schema"].decode())
    dec = _decoder(schema)
    sync = buf.read(16)
    records = []
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, 1)
        count = read_long(buf)
        block = io.BytesIO(read_bytes(buf))
        for _ in range(count):
            records.append(dec(block))
        if buf.read(16) != sync:
            raise ValueError("avro sync marker mismatch")
    return records


class AvroConverter:
    binary = True  # CLI opens input files in 'rb' mode

    def __init__(self, config: dict, sft):
        self.sft = sft
        self.fields = [
            (
                f["name"],
                f.get("path"),  # optional top-level field name
                parse_expression(f["transform"]) if f.get("transform") else None,
            )
            for f in config["fields"]
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, data: bytes) -> ConvertResult:
        if hasattr(data, "read"):
            data = data.read()
        records = read_generic_avro(data)
        cols: dict = {}
        if records:
            for key in records[0]:
                cols[key] = np.array([r.get(key) for r in records], dtype=object)
        out = {}
        for name, path, transform in self.fields:
            if transform is not None:
                out[name] = transform(cols)
            elif path is not None:
                out[name] = cols[path]
            elif name in cols:
                out[name] = cols[name]
            else:
                raise ValueError(f"field {name!r} needs path or transform")
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), 0)
