"""Declarative ingest converters (maps reference geomesa-convert).

(ref: geomesa-convert SimpleFeatureConverter/AbstractConverter + the
Transformers expression language [UNVERIFIED - empty reference mount]).
A converter config (dict; the TypeSafe-Config analog) declares how raw
records become features:

    {
      "type": "delimited-text",       # or "json"
      "format": "csv",                 # csv | tsv
      "id-field": "$1",                # expression for the feature id
      "options": {"skip-lines": 1, "error-mode": "skip-bad-records"},
      "fields": [
        {"name": "name", "transform": "$1"},
        {"name": "age",  "transform": "$2::int"},
        {"name": "dtg",  "transform": "datetime($3)"},
        {"name": "geom", "transform": "point($4::double, $5::double)"},
      ],
    }

Transforms use the expression language in ``expression.py``; evaluation is
vectorized over record batches (columns in, columns out).
"""

from geomesa_tpu.convert.expression import Expression, parse_expression
from geomesa_tpu.convert.delimited import DelimitedTextConverter
from geomesa_tpu.convert.json_conv import JsonConverter
from geomesa_tpu.convert.xml_conv import XmlConverter
from geomesa_tpu.convert.fixedwidth import FixedWidthConverter
from geomesa_tpu.convert.avro_conv import AvroConverter
from geomesa_tpu.convert.jdbc import JdbcConverter
from geomesa_tpu.convert.shp import ShapefileConverter
from geomesa_tpu.convert.parquet_conv import ParquetConverter

_CONVERTERS = {
    "delimited-text": DelimitedTextConverter,
    "json": JsonConverter,
    "xml": XmlConverter,
    "fixed-width": FixedWidthConverter,
    "avro": AvroConverter,
    "jdbc": JdbcConverter,
    "shp": ShapefileConverter,
    "parquet": ParquetConverter,
}


def converter_for(config: dict, sft):
    kind = config.get("type")
    if kind not in _CONVERTERS:
        raise ValueError(f"unknown converter type {kind!r}")
    return _CONVERTERS[kind](config, sft)


__all__ = [
    "Expression",
    "parse_expression",
    "DelimitedTextConverter",
    "JsonConverter",
    "XmlConverter",
    "FixedWidthConverter",
    "AvroConverter",
    "JdbcConverter",
    "ShapefileConverter",
    "ParquetConverter",
    "converter_for",
]
