"""Fixed-width text converter.

Ref role: geomesa-convert-fixedwidth FixedWidthConverter [UNVERIFIED -
empty reference mount]: each field declares a character ``start`` and
``width`` slice of the line; the sliced string binds as ``$name`` (and the
whole line as ``$0``) for the optional transform.

    {
      "type": "fixed-width",
      "id-field": "$name",
      "options": {"skip-lines": 0},
      "fields": [
        {"name": "lat", "start": 0, "width": 6, "transform": "$lat::double"},
        {"name": "lon", "start": 6, "width": 7, "transform": "$lon::double"},
        {"name": "geom", "transform": "point($lon::double, $lat::double)"},
      ],
    }
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult, _rowwise
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch


class FixedWidthConverter:
    def __init__(self, config: dict, sft):
        self.sft = sft
        opts = config.get("options", {})
        self.skip_lines = int(opts.get("skip-lines", 0))
        self.error_mode = opts.get("error-mode", "skip-bad-records")
        self.fields = []
        for f in config["fields"]:
            slc = None
            if "start" in f:
                start = int(f["start"])
                slc = (start, start + int(f["width"]))
            self.fields.append(
                (
                    f["name"],
                    slc,
                    parse_expression(f["transform"]) if f.get("transform") else None,
                )
            )
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, text_or_lines) -> ConvertResult:
        if isinstance(text_or_lines, str):
            lines = text_or_lines.splitlines()
        else:
            lines = [ln.rstrip("\n") for ln in text_or_lines]
        lines = [ln for ln in lines[self.skip_lines :] if ln.strip()]
        failed = 0
        cols: dict = {"0": np.array(lines, dtype=object)}
        for name, slc, _ in self.fields:
            if slc is not None:
                i0, i1 = slc
                cols[name] = np.array(
                    [ln[i0:i1].strip() for ln in lines], dtype=object
                )
        out = {}
        ok = np.ones(len(lines), dtype=bool)
        for name, slc, transform in self.fields:
            if transform is not None:
                try:
                    out[name] = transform(cols)
                except Exception:
                    if self.error_mode == "raise-errors":
                        raise
                    out[name], ok = _rowwise(transform, cols, ok)
            elif slc is not None:
                out[name] = cols[name]
            else:
                raise ValueError(f"field {name!r} needs start/width or transform")
        if not np.all(ok):
            failed = int((~ok).sum())
            keep = np.nonzero(ok)[0]
            out = {k: (v[keep] if len(v) == len(ok) else v) for k, v in out.items()}
            cols = {k: v[keep] for k, v in cols.items()}
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), failed)
