"""Delimited-text converter (ref: geomesa-convert-text
DelimitedTextConverter)."""

from __future__ import annotations

import csv
import io
from dataclasses import dataclass

import numpy as np

from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch


@dataclass
class ConvertResult:
    batch: FeatureBatch
    success: int
    failed: int


class DelimitedTextConverter:
    def __init__(self, config: dict, sft):
        self.sft = sft
        self.delimiter = {"csv": ",", "tsv": "\t"}.get(
            config.get("format", "csv"), config.get("format", ",")
        )
        opts = config.get("options", {})
        self.skip_lines = int(opts.get("skip-lines", 0))
        self.error_mode = opts.get("error-mode", "skip-bad-records")
        self.fields = [
            (f["name"], parse_expression(f["transform"])) for f in config["fields"]
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )
        missing = {a.name for a in sft.attributes} - {n for n, _ in self.fields}
        if missing:
            raise ValueError(f"converter missing fields for {sorted(missing)}")

    def process(self, text_or_lines) -> ConvertResult:
        """Convert raw csv/tsv content to a FeatureBatch."""
        if isinstance(text_or_lines, str):
            rows = list(
                csv.reader(io.StringIO(text_or_lines), delimiter=self.delimiter)
            )
        else:
            rows = list(csv.reader(text_or_lines, delimiter=self.delimiter))
        rows = [r for r in rows[self.skip_lines :] if r]
        if not rows:
            empty = FeatureBatch.from_columns(
                self.sft, {a.name: [] for a in self.sft.attributes}
            )
            return ConvertResult(empty, 0, 0)
        width = max(len(r) for r in rows)
        # drop short rows (bad records) up front
        good = [r for r in rows if len(r) == width]
        failed = len(rows) - len(good)
        if failed and self.error_mode == "raise-errors":
            raise ValueError(f"{failed} malformed records")
        cols = {
            str(i + 1): np.array([r[i] for r in good], dtype=object)
            for i in range(width)
        }
        cols["0"] = np.array([self.delimiter.join(r) for r in good], dtype=object)
        out = {}
        ok = np.ones(len(good), dtype=bool)
        for name, expr in self.fields:
            try:
                out[name] = expr(cols)
            except Exception:
                if self.error_mode == "raise-errors":
                    raise
                # row-wise salvage: evaluate one row at a time
                vals, ok = _rowwise(expr, cols, ok)
                out[name] = vals
        if not np.all(ok):
            failed += int((~ok).sum())
            keep = np.nonzero(ok)[0]
            out = {
                k: (v[keep] if len(v) == len(ok) else v) for k, v in out.items()
            }
            cols = {k: v[keep] for k, v in cols.items()}
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), failed)


def _rowwise(expr, cols: dict, ok: np.ndarray):
    n = len(next(iter(cols.values())))
    vals = [None] * n
    ok = ok.copy()
    for i in range(n):
        row = {k: v[i : i + 1] for k, v in cols.items()}
        try:
            vals[i] = expr(row)[0]
        except Exception:
            ok[i] = False
    arr = np.array([v for v in vals], dtype=object)
    return arr, ok
