"""XML converter.

Ref role: geomesa-convert-xml XmlConverter [UNVERIFIED - empty reference
mount] -- declarative ingest from XML documents. The reference evaluates
javax XPath expressions per feature element; here the path language is the
ElementTree subset (``tag``, ``a/b``, ``.//tag``, ``tag[@k='v']``) plus a
trailing ``/@attr`` or ``/text()`` selector, which covers the converter
configs the reference ships in tests.

Config shape (mirrors the JSON converter):

    {
      "type": "xml",
      "feature-path": ".//Feature",      # element iteration path
      "id-field": "$id",
      "fields": [
        {"name": "id",   "path": "@id"},
        {"name": "name", "path": "Name/text()"},
        {"name": "geom", "path": "Pos", "transform": "..."},
      ],
    }

Each field's ``path`` is evaluated against the feature element and bound as
``$name`` for transforms; with no transform the extracted string is the
value.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch


def xml_select(elem: ET.Element, path: str):
    """Evaluate a path with optional trailing /@attr or /text()."""
    attr = None
    want_text = False
    if path.startswith("@"):
        return elem.get(path[1:])
    if "/@" in path:
        path, attr = path.rsplit("/@", 1)
    elif path.endswith("/text()"):
        path = path[: -len("/text()")]
        want_text = True
    target = elem if path in (".", "") else elem.find(path)
    if target is None:
        return None
    if attr is not None:
        return target.get(attr)
    if want_text:
        return target.text
    # bare element path: its text content (the common converter case)
    return target.text


class XmlConverter:
    def __init__(self, config: dict, sft):
        self.sft = sft
        self.feature_path = config.get("feature-path", ".")
        opts = config.get("options", {})
        self.error_mode = opts.get("error-mode", "skip-bad-records")
        self.fields = [
            (
                f["name"],
                f.get("path"),
                parse_expression(f["transform"]) if f.get("transform") else None,
            )
            for f in config["fields"]
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, text: str) -> ConvertResult:
        root = ET.fromstring(text)
        if self.feature_path in (".", ""):
            records = [root]
        else:
            records = list(root.iterfind(self.feature_path))
        raw: dict = {}
        for name, path, _ in self.fields:
            if path:
                raw[name] = np.array(
                    [xml_select(r, path) for r in records], dtype=object
                )
        cols = dict(raw)
        out = {}
        failed = 0
        ok = np.ones(len(records), dtype=bool)
        for name, path, transform in self.fields:
            if transform is not None:
                try:
                    out[name] = transform(cols)
                except Exception:
                    if self.error_mode == "raise-errors":
                        raise
                    from geomesa_tpu.convert.delimited import _rowwise

                    out[name], ok = _rowwise(transform, cols, ok)
            elif path is not None:
                out[name] = raw[name]
            else:
                raise ValueError(f"field {name!r} needs path or transform")
        if not np.all(ok):
            failed = int((~ok).sum())
            keep = np.nonzero(ok)[0]
            out = {k: (v[keep] if len(v) == len(ok) else v) for k, v in out.items()}
            cols = {k: v[keep] for k, v in cols.items()}
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), failed)
