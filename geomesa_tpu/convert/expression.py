"""Converter expression language.

(ref: geomesa-convert .../Transformers.scala / Expression.scala parboiled
parser [UNVERIFIED - empty reference mount]). Supported grammar:

    expr     := term ('::' cast)?
    term     := func '(' expr (',' expr)* ')' | ref | literal
    ref      := $N (1-based column) | $0 (whole record) | $name (field ref)
    literal  := 'string' | number
    cast     := int | long | float | double | string | boolean
    func     := point | datetime | millisToDate | secsToDate | concat |
                trim | lowercase | uppercase | replace | substring |
                stringToInt/Long/Float/Double | md5 | lit | try

Evaluation is columnar: refs resolve in a dict {ref: np.ndarray}; functions
are vectorized where numpy allows, else row-wise object ops.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Callable

import numpy as np

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<ref>\$[A-Za-z0-9_]+)
      | (?P<cast>::[a-z]+)
      | (?P<lparen>\()
      | (?P<rparen>\))
      | (?P<comma>,)
      | (?P<string>'(?:[^']|'')*')
      | (?P<number>-?\d+\.?\d*(?:[eE][-+]?\d+)?)
      | (?P<word>[A-Za-z_][A-Za-z0-9_]*)
    )""",
    re.VERBOSE,
)


@dataclass
class Expression:
    fn: Callable  # (cols: dict) -> np.ndarray
    refs: set
    text: str

    def __call__(self, cols: dict) -> np.ndarray:
        return self.fn(cols)


def parse_expression(text: str) -> Expression:
    toks = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise ValueError(f"cannot tokenize {text[pos:pos+15]!r}")
            break
        pos = m.end()
        for k, v in m.groupdict().items():
            if v is not None:
                toks.append((k, v))
                break
    state = {"i": 0}
    refs: set = set()

    def peek():
        return toks[state["i"]] if state["i"] < len(toks) else (None, None)

    def nxt():
        t = peek()
        if t[0] is None:
            raise ValueError(f"unexpected end of expression {text!r}")
        state["i"] += 1
        return t

    def parse_expr():
        fn = parse_term()
        kind, val = peek()
        if kind == "cast":
            nxt()
            fn = _cast(fn, val[2:])
        return fn

    def parse_term():
        kind, val = nxt()
        if kind == "ref":
            name = val[1:]
            refs.add(name)
            return lambda cols, name=name: cols[name]
        if kind == "string":
            s = val[1:-1].replace("''", "'")
            return lambda cols, s=s: _broadcast(cols, np.array([s], dtype=object))
        if kind == "number":
            v = float(val) if ("." in val or "e" in val.lower()) else int(val)
            return lambda cols, v=v: _broadcast(cols, np.array([v]))
        if kind == "word":
            fname = val.lower()
            k2, _ = peek()
            if k2 != "lparen":
                raise ValueError(f"expected '(' after {val!r}")
            nxt()
            args = []
            if peek()[0] != "rparen":
                args.append(parse_expr())
                while peek()[0] == "comma":
                    nxt()
                    args.append(parse_expr())
            if peek()[0] != "rparen":
                raise ValueError(f"missing ')' in {text!r}")
            nxt()
            return _function(fname, args)
        raise ValueError(f"unexpected token {val!r} in {text!r}")

    fn = parse_expr()
    if peek()[0] is not None:
        raise ValueError(f"trailing input in expression {text!r}")
    return Expression(fn, refs, text)


def _broadcast(cols: dict, v: np.ndarray) -> np.ndarray:
    n = len(next(iter(cols.values()))) if cols else 1
    return np.repeat(v, n)


_CASTS = {
    "int": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "boolean": None,
    "string": None,
}


def _cast(fn, kind: str):
    if kind not in _CASTS:
        raise ValueError(f"unknown cast ::{kind}")
    if kind == "string":
        return lambda cols: np.array(
            [str(v) for v in fn(cols)], dtype=object
        )
    if kind == "boolean":
        return lambda cols: np.array(
            [str(v).strip().lower() in ("true", "1", "t", "yes") for v in fn(cols)]
        )
    dtype = _CASTS[kind]
    if kind in ("int", "long"):
        # parse via float first so "3.0" and "3" both work
        return lambda cols: np.asarray(
            np.asarray(fn(cols), dtype=np.float64), dtype=dtype
        )
    return lambda cols: np.asarray(fn(cols), dtype=dtype)


def _function(name: str, args: list):
    if name == "point":
        if len(args) != 2:
            raise ValueError("point(x, y) takes 2 args")
        fx, fy = args
        return lambda cols: np.stack(
            [
                np.asarray(fx(cols), dtype=np.float64),
                np.asarray(fy(cols), dtype=np.float64),
            ],
            axis=1,
        )
    if name in ("datetime", "isodate"):
        (f,) = args
        def dt(cols, f=f):
            vals = f(cols)
            out = np.empty(len(vals), dtype=np.int64)
            for i, v in enumerate(vals):
                s = str(v).strip()
                if s.endswith("Z"):
                    s = s[:-1]
                out[i] = np.datetime64(s, "ms").astype(np.int64)
            return out
        return dt
    if name == "millistodate":
        (f,) = args
        return lambda cols: np.asarray(
            np.asarray(f(cols), dtype=np.float64), dtype=np.int64
        )
    if name == "secstodate":
        (f,) = args
        return lambda cols: np.asarray(
            np.asarray(f(cols), dtype=np.float64) * 1000, dtype=np.int64
        )
    if name == "concat":
        return lambda cols: np.array(
            ["".join(str(f(cols)[i]) for f in args) for i in range(len(args[0](cols)))],
            dtype=object,
        )
    if name == "trim":
        (f,) = args
        return lambda cols: np.array([str(v).strip() for v in f(cols)], dtype=object)
    if name == "lowercase":
        (f,) = args
        return lambda cols: np.array([str(v).lower() for v in f(cols)], dtype=object)
    if name == "uppercase":
        (f,) = args
        return lambda cols: np.array([str(v).upper() for v in f(cols)], dtype=object)
    if name == "replace":
        f, fa, fb = args
        def rep(cols, f=f, fa=fa, fb=fb):
            a = str(fa(cols)[0])
            b = str(fb(cols)[0])
            return np.array([str(v).replace(a, b) for v in f(cols)], dtype=object)
        return rep
    if name == "substring":
        f, f0, f1 = args
        def sub(cols, f=f, f0=f0, f1=f1):
            i0 = int(f0(cols)[0])
            i1 = int(f1(cols)[0])
            return np.array([str(v)[i0:i1] for v in f(cols)], dtype=object)
        return sub
    if name in ("stringtoint", "stringtolong", "stringtofloat", "stringtodouble"):
        f, default = args if len(args) == 2 else (args[0], None)
        dtype = {
            "stringtoint": np.int32,
            "stringtolong": np.int64,
            "stringtofloat": np.float32,
            "stringtodouble": np.float64,
        }[name]
        def conv(cols, f=f, default=default, dtype=dtype):
            vals = f(cols)
            dflt = default(cols)[0] if default is not None else 0
            out = []
            for v in vals:
                try:
                    out.append(dtype(float(v)))
                except (TypeError, ValueError):
                    out.append(dtype(dflt))
            return np.array(out, dtype=dtype)
        return conv
    if name == "md5":
        (f,) = args
        return lambda cols: np.array(
            [hashlib.md5(str(v).encode()).hexdigest() for v in f(cols)],
            dtype=object,
        )
    if name == "lit":
        (f,) = args
        return f
    if name == "try":
        f, fallback = args
        def try_(cols, f=f, fallback=fallback):
            try:
                return f(cols)
            except Exception:
                return fallback(cols)
        return try_
    raise ValueError(f"unknown function {name!r}")
