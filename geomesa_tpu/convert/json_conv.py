"""JSON converter (ref: geomesa-convert-json JsonConverter; JsonPath
subset)."""

from __future__ import annotations

import json
import re

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch

_PATH = re.compile(r"\.([A-Za-z0-9_]+)|\[(\d+|\*)\]")


def json_path(obj, path: str):
    """Minimal JsonPath: $.a.b[0].c and $.items[*] (one wildcard)."""
    if not path.startswith("$"):
        raise ValueError(f"json path must start with $: {path!r}")
    cur = [obj]
    for m in _PATH.finditer(path, 1):
        key, idx = m.group(1), m.group(2)
        nxt = []
        for c in cur:
            if c is None:
                nxt.append(None)
            elif key is not None:
                nxt.append(c.get(key) if isinstance(c, dict) else None)
            elif idx == "*":
                nxt.extend(c if isinstance(c, list) else [])
            else:
                i = int(idx)
                nxt.append(c[i] if isinstance(c, list) and i < len(c) else None)
        cur = nxt
    return cur


class JsonConverter:
    """fields entries use "json-path" (per-record extraction) and/or
    "transform" (expression over extracted refs; extracted values bind as
    ``$name``)."""

    def __init__(self, config: dict, sft):
        self.sft = sft
        self.feature_path = config.get("feature-path")  # e.g. $.features[*]
        self.fields = []
        for f in config["fields"]:
            self.fields.append(
                (
                    f["name"],
                    f.get("json-path"),
                    parse_expression(f["transform"]) if f.get("transform") else None,
                )
            )
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, text: str) -> ConvertResult:
        docs = []
        text = text.strip()
        if not text:
            docs = []
        elif text.startswith("["):
            docs = json.loads(text)
        else:
            # newline-delimited json or a single object
            try:
                one = json.loads(text)
                docs = [one]
            except json.JSONDecodeError:
                docs = [json.loads(line) for line in text.splitlines() if line.strip()]
        if self.feature_path:
            records = []
            for d in docs:
                records.extend(json_path(d, self.feature_path))
        else:
            records = docs
        failed = 0
        # extract raw values per field
        raw: dict = {}
        for name, path, _ in self.fields:
            if path:
                vals = []
                for r in records:
                    v = json_path(r, path)
                    vals.append(v[0] if len(v) == 1 else v)
                raw[name] = np.array(vals, dtype=object)
        n = len(records)
        cols = dict(raw)
        out = {}
        for name, path, transform in self.fields:
            if transform is not None:
                out[name] = transform(cols)
            elif path is not None:
                out[name] = raw[name]
            else:
                raise ValueError(f"field {name!r} needs json-path or transform")
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), failed)
