"""Parquet converter: ingest from Parquet files.

Ref role: geomesa-convert-parquet ParquetConverter [UNVERIFIED - empty
reference mount]. Reads a Parquet file via pyarrow, binds each top-level
column as ``$name`` for the field transforms (the reference binds Parquet
group fields the same way through its avro-path-style language). Columns
already in columnar form skip the per-record loop entirely — transforms
run vectorized over the column arrays.

    {
      "type": "parquet",
      "id-field": "$id",
      "fields": [
        {"name": "geom", "transform": "point($lon, $lat)"},
        {"name": "dtg",  "transform": "millisToDate($ts)"},
        {"name": "name", "path": "name"},
      ],
    }
"""

from __future__ import annotations

import io

import numpy as np

from geomesa_tpu.convert.delimited import ConvertResult
from geomesa_tpu.convert.expression import parse_expression
from geomesa_tpu.features.batch import FeatureBatch


def _column_to_numpy(col) -> np.ndarray:
    """Arrow column -> numpy, preserving numeric dtypes, object for the rest."""
    import pyarrow as pa

    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if pa.types.is_floating(arr.type) or pa.types.is_integer(arr.type):
        if arr.null_count == 0:
            return arr.to_numpy(zero_copy_only=False)
    if pa.types.is_timestamp(arr.type):
        # epoch millis (matches the converter expression language's date units)
        return arr.cast(pa.timestamp("ms")).cast(pa.int64()).to_numpy(
            zero_copy_only=False
        )
    return np.array(arr.to_pylist(), dtype=object)


class ParquetConverter:
    binary = True  # CLI opens input files in 'rb' mode

    def __init__(self, config: dict, sft):
        self.sft = sft
        self.fields = [
            (
                f["name"],
                f.get("path"),
                parse_expression(f["transform"]) if f.get("transform") else None,
            )
            for f in config["fields"]
        ]
        self.id_expr = (
            parse_expression(config["id-field"]) if config.get("id-field") else None
        )

    def process(self, data) -> ConvertResult:
        import pyarrow.parquet as pq

        if hasattr(data, "read"):
            data = data.read()
        if isinstance(data, (bytes, bytearray)):
            source = io.BytesIO(data)
        else:
            source = data  # path
        table = pq.read_table(source)
        cols = {name: _column_to_numpy(table[name]) for name in table.column_names}
        out = {}
        for name, path, transform in self.fields:
            if transform is not None:
                out[name] = transform(cols)
            elif path is not None:
                out[name] = cols[path]
            elif name in cols:
                out[name] = cols[name]
            else:
                raise ValueError(f"field {name!r} needs path or transform")
        fids = self.id_expr(cols) if self.id_expr else None
        batch = FeatureBatch.from_columns(self.sft, out, fids)
        return ConvertResult(batch, len(batch), 0)
