"""Per-request cost ledger: who is spending the device's time, on what.

Ref role: GeoMesa's audited query logs answer "which query cost what"
after the fact (PAPER.md's stats/audit layer [UNVERIFIED - empty
reference mount]); this module is that idea rebuilt for an accelerator
serving stack, where the scarce resources are device launches, device
seconds, host I/O bytes and — above all — XLA compile time (ROADMAP
item 4: kNN cold compile 14.3s vs 194ms warm).

Three pieces:

- **Request cost collection.** The server installs a :class:`RequestCost`
  per request (:func:`collect_cost`, a contextvar exactly like the
  tracing / degradation collectors); instrumented sites call
  :func:`charge` with a field from the :data:`FIELDS` registry (lint
  rule GT009 validates the literals). The collector crosses thread
  pools EXPLICITLY (:func:`capture_cost` / :func:`attach_cost` — the
  scheduler and the prefetch pipeline both carry it), so device seconds
  burned on a scheduler worker and bytes read on a prefetch thread land
  on the request that caused them. Shared fused launches charge each
  rider its FAIR SHARE (duration / riders), so summing the ledger over
  tenants reproduces actual device time instead of multiplying it.

- **Compile-time attribution.** :class:`CompileLedger` hooks the jit
  path process-wide through ``jax.monitoring``: every backend compile
  records its duration under the active shape signature
  (:func:`compile_scope`, stamped by the device-cache kernel builders;
  the request's query shape otherwise), persistent-compile-cache hits
  count per signature, and the request that BLOCKED on the compile is
  charged ``compile_seconds`` — plus a retroactive ``xla.compile`` span
  in its trace, so a 14s cold-compile request shows the compile that
  ate its deadline.

- **Aggregation.** Finished requests fold into the process-wide
  :class:`CostLedger`: per-tenant and per-shape aggregates (bounded
  key spaces — overflow collapses into ``"other"``), latency histograms
  per aggregate (p50/p99 for the load-driver exit summary), and a
  top-K ring of the most expensive individual requests with their trace
  ids (``/stats/ledger`` links a cost outlier straight to its captured
  trace in ``/debug/traces``).

The process-ledger fold is gated by ``ledger.enabled`` (the SLO engine
has its own independent ``slo.enabled`` switch — both read the same
per-request collector), and the layer is sized to stay out of the
serving hot path: a charge is a dict add under a per-request lock, and
the fault-free overhead guard (bench.py ``--trace-overhead``) holds the
whole accounting path under 1% of p50 on the serve leg.
"""

from __future__ import annotations

import contextvars
import time
from bisect import bisect_left
from collections import OrderedDict
from contextlib import contextmanager

from geomesa_tpu.locking import checked_lock

__all__ = [
    "FIELDS",
    "SCOPE_FAMILIES",
    "RequestCost",
    "CostLedger",
    "CompileLedger",
    "LEDGER",
    "COMPILES",
    "add_compile_observer",
    "attach_cost",
    "attach_scope",
    "capture_cost",
    "capture_scope",
    "charge",
    "collect_cost",
    "compile_scope",
    "cost_from_trace",
    "current_cost",
    "enabled",
    "finish_request",
    "install",
]

#: the ledger field registry (lint rule GT009: every ``charge`` literal
#: must come from here — an undeclared field would silently mint a new
#: column nobody aggregates or documents)
FIELDS = (
    "device_launches",   # device scan launches this request rode
    "device_seconds",    # fair-share device execution time (dur/riders)
    "fusion_width",      # widest fused launch this request rode (max)
    "compiles",          # XLA backend compiles this request blocked on
    "compile_seconds",   # time spent blocked on those compiles
    "compile_cache_hits",  # persistent-cache loads instead of compiles
    "read_bytes",        # partition-file bytes read for this request
    "read_seconds",      # host read time (prefetch workers included)
    "decode_seconds",    # Arrow-to-FeatureBatch decode time
    "stage_bytes",       # host column bytes staged for device scans
    "stage_seconds",     # host column staging time
    "chunks_read",       # v2 chunks actually read
    "chunks_pruned",     # v2 chunks skipped before read/decode
    "retries",           # serving-path retries spent (resilience.py)
    "degraded",          # degradation rungs taken (note_degraded count)
    "wal_bytes",         # write-ahead-log bytes this append durably wrote
    "wal_fsyncs",        # WAL fsync calls this append waited on
    "memtable_rows",     # rows this append landed in the live memtable
    "compact_seconds",   # background compaction seconds (system requests)
    "join_candidates",   # candidate pairs expanded by join refinement
    "join_pairs",        # pairs this request's spatial joins emitted
    "encode_seconds",    # wire-format serialization time (http.encode)
    "response_bytes",    # response body bytes written to the socket
    "replica_ship_bytes",  # WAL record bytes shipped to followers
    "replica_apply_rows",  # rows applied from a leader's shipped WAL
    "snapshot_ship_bytes",  # snapshot stream bytes shipped to a fetcher
    "sub_matches",       # matched alert rows charged to the subscriber
    "sub_deliver_bytes",  # push-stream bytes delivered to a subscriber
)

#: fields folded with max() instead of sum() (a request's fusion width
#: is the widest launch it rode, not the total of all of them)
_MAX_FIELDS = frozenset({"fusion_width"})

_FIELD_SET = frozenset(FIELDS)

#: per-aggregate latency buckets (seconds) for the ledger's p50/p99
#: summaries — coarser than the metrics histograms on purpose (one
#: array per tenant/shape, bounded key spaces)
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

#: bounded aggregate key spaces: pressure past these collapses new keys
#: into "other" (a tenant id is client-controlled input — an unbounded
#: dict would be an allocation amplifier, same discipline as GT006)
_MAX_TENANTS = 256
_MAX_SHAPES = 64
_TOPK_RING = 16


def enabled() -> bool:
    from geomesa_tpu.conf import sys_prop

    return bool(sys_prop("ledger.enabled"))


class RequestCost:
    """One request's cost accumulator. Charged from the handler thread,
    scheduler workers and prefetch workers concurrently — every
    mutation happens under the instance lock."""

    __slots__ = (
        "fields", "tenant", "endpoint", "lane", "shape", "trace_id",
        "status", "dur_s", "_lock",
    )

    def __init__(
        self, tenant: str = "", endpoint: str = "", lane: str = "",
        shape: str = "", trace_id: str = "",
    ):
        self.fields: dict = {}
        self.tenant = tenant
        self.endpoint = endpoint
        self.lane = lane
        self.shape = shape
        self.trace_id = trace_id
        self.status = 0
        self.dur_s = 0.0
        self._lock = checked_lock("ledger.cost")

    def charge(self, field: str, amount: float) -> None:
        if field not in _FIELD_SET:
            raise KeyError(f"unknown ledger field {field!r} (see FIELDS)")
        with self._lock:
            if field in _MAX_FIELDS:
                self.fields[field] = max(
                    self.fields.get(field, 0.0), float(amount)
                )
            else:
                self.fields[field] = (
                    self.fields.get(field, 0.0) + float(amount)
                )

    def snapshot_fields(self) -> dict:
        with self._lock:
            return dict(self.fields)

    def weight_s(self) -> float:
        """The cost rank used by the top-K ring: seconds of machine time
        this request consumed (device + compile + host I/O stages)."""
        f = self.snapshot_fields()
        return (
            f.get("device_seconds", 0.0)
            + f.get("compile_seconds", 0.0)
            + f.get("read_seconds", 0.0)
            + f.get("decode_seconds", 0.0)
            + f.get("stage_seconds", 0.0)
        )

    def to_dict(self) -> dict:
        f = self.snapshot_fields()
        return {
            "tenant": self.tenant,
            "endpoint": self.endpoint,
            "lane": self.lane,
            "shape": self.shape,
            "trace_id": self.trace_id,
            "status": self.status,
            "duration_ms": round(self.dur_s * 1e3, 3),
            "cost_s": round(self.weight_s(), 6),
            "fields": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in sorted(f.items())
            },
        }


#: the per-request collector; None outside a serving request
_cost: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_ledger_cost", default=None
)

# -- runtime-checker observer seams ------------------------------------------
#
# The analysis-layer runtime checkers (ctxcheck / compilecheck) arm
# these at install time; unarmed they are None / empty and every hook
# below is a single falsy check — the production path stays unchanged.
#: called as fn(active_cost_or_None, field) on every context-routed charge
_charge_observer = None
#: called as fn(cost_or_collector, entering: bool) when a collector is
#: explicitly attached/installed on (entering) or detached from (exiting)
#: a thread — how ctxcheck learns which collectors a worker task may
#: legitimately charge
_attach_observer = None
#: called as fn(raw_scope_or_None, active_cost_or_None, dur_s) on every
#: backend compile event, BEFORE the fallback-signature resolution
_compile_observers: list = []


def add_compile_observer(fn) -> None:
    """Register a backend-compile event observer (runtime checkers)."""
    if fn not in _compile_observers:
        _compile_observers.append(fn)


def set_charge_observer(fn) -> None:
    global _charge_observer
    _charge_observer = fn


def set_attach_observer(fn) -> None:
    global _attach_observer
    _attach_observer = fn


@contextmanager
def collect_cost(**meta):
    """Install a fresh :class:`RequestCost` for the request (server
    request loop); yields it. The collector is installed even with
    ``ledger.enabled=False``: the SLO engine reads the request's
    endpoint/lane/status from it (the two switches are independent —
    :func:`finish_request` skips only the LEDGER fold when disabled),
    and a dropped-on-the-floor charge costs a dict add."""
    cost = RequestCost(**meta)
    token = _cost.set(cost)
    if _attach_observer is not None:
        _attach_observer(cost, True)
    try:
        yield cost
    finally:
        if _attach_observer is not None:
            _attach_observer(cost, False)
        _cost.reset(token)


def current_cost() -> "RequestCost | None":
    return _cost.get()


def charge(field: str, amount: float) -> None:
    """Charge the current request's ledger (no-op outside a request or
    with the ledger disabled). ``field`` must be a :data:`FIELDS` name
    — GT009 validates call-site literals statically."""
    cost = _cost.get()
    if _charge_observer is not None:
        _charge_observer(cost, field)
    if cost is not None:
        cost.charge(field, amount)


def capture_cost() -> "RequestCost | None":
    """The current cost collector, for EXPLICIT propagation onto worker
    threads (same discipline as tracing.capture / capture_degraded)."""
    return _cost.get()


@contextmanager
def attach_cost(cost):
    """Attach a captured collector around work executing on another
    thread (scheduler / prefetch workers); None attaches nothing."""
    if cost is None:
        yield
        return
    token = _cost.set(cost)
    if _attach_observer is not None:
        _attach_observer(cost, True)
    try:
        yield
    finally:
        if _attach_observer is not None:
            _attach_observer(cost, False)
        _cost.reset(token)


# -- compile-time attribution -----------------------------------------------

#: the statically-registered compile-scope families: every
#: :func:`compile_scope` call site stamps a signature of the form
#: ``family`` or ``family:<bucketed dims>``, and every family is
#: declared here — this is the closed set the AOT warmup plan
#: (:mod:`geomesa_tpu.warmup`) enumerates bucket x family signatures
#: from, and what keeps ``/stats/ledger``'s ``by_signature`` keys a
#: bounded, documented namespace. Adding a compile_scope site means
#: adding its family here (and, if it should be pre-compiled, a warmup
#: leg that exercises it).
SCOPE_FAMILIES = (
    ("cache.stage", "resident column staging pipeline"),
    ("cache.scan", "resident per-filter scan kernels"),
    ("store.scan", "streamed store-scan kernels"),
    ("fused.dim", "fused micro-batch count/query (r x q capacities)"),
    ("fused.cmp", "fused single-query compare kernels"),
    ("fused.agg", "fused aggregation kernels (stats/density)"),
    ("knn", "k-nearest-neighbor top-k (k on the bucket ladder)"),
    ("join.refine", "spatial-join refinement count/compact buckets"),
    ("join.mesh", "sharded spatial-join mesh kernels"),
)

_scope: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_compile_scope", default=None
)


@contextmanager
def compile_scope(signature: str):
    """Tag any XLA compile triggered in the body with ``signature`` (a
    BOUNDED kernel-family string, e.g. ``resident.fused:w=8`` with the
    width bucketed to a power of two). The device-cache kernel builders
    wrap their jit sites with this so the compile ledger attributes
    compile time to query shapes, not just to whole requests."""
    token = _scope.set(str(signature))
    try:
        yield
    finally:
        _scope.reset(token)


def capture_scope() -> "str | None":
    """The active compile-scope signature, for EXPLICIT propagation
    onto worker threads (the blessed spawn helper carries it with the
    trace/cost/degraded set — a builder that hands device work to a
    pool keeps its compiles attributed)."""
    return _scope.get()


@contextmanager
def attach_scope(signature):
    """Attach a captured compile scope around work on another thread
    (:mod:`geomesa_tpu.spawn`); None attaches nothing."""
    if signature is None:
        yield
        return
    token = _scope.set(str(signature))
    try:
        yield
    finally:
        _scope.reset(token)


class CompileLedger:
    """Process-wide compilation ledger, fed by ``jax.monitoring``:
    every backend compile (the event fires synchronously on the thread
    that blocked on it) records under the active :func:`compile_scope`
    signature, charges the in-flight request that waited, and attaches
    a retroactive ``xla.compile`` span to its trace."""

    def __init__(self, max_signatures: int = 128):
        self.max_signatures = max_signatures
        self._lock = checked_lock("ledger.compile")
        self._by_sig: OrderedDict = OrderedDict()
        self.compiles = 0
        self.total_s = 0.0
        self.cache_hits = 0

    def _signature(self) -> str:
        sig = _scope.get()
        if sig:
            return sig
        cost = _cost.get()
        if cost is not None and cost.shape:
            return f"request:{cost.shape}"
        return "untagged"

    def on_backend_compile(self, dur_s: float) -> None:
        sig = self._signature()
        cost = _cost.get()
        if _compile_observers:
            # the runtime checkers see the RAW scope (None when no
            # compile_scope is active — the fallback signature would
            # mask exactly the unattributed compiles they exist to flag)
            raw = _scope.get()
            for obs in _compile_observers:
                try:
                    obs(raw, cost, dur_s)
                except Exception:  # pragma: no cover - checkers must not break jit
                    pass
        trace_id = cost.trace_id if cost is not None else ""
        with self._lock:
            ent = self._by_sig.get(sig)
            if ent is None:
                if len(self._by_sig) >= self.max_signatures:
                    sig = "other"
                    ent = self._by_sig.get(sig)
                if ent is None:
                    ent = self._by_sig[sig] = {
                        "compiles": 0, "total_s": 0.0, "max_s": 0.0,
                        "cache_hits": 0, "last_trace_id": "",
                    }
            ent["compiles"] += 1
            ent["total_s"] += dur_s
            ent["max_s"] = max(ent["max_s"], dur_s)
            if trace_id:
                ent["last_trace_id"] = trace_id
            self.compiles += 1
            self.total_s += dur_s
        from geomesa_tpu import metrics

        metrics.compile_events.inc()
        metrics.compile_event_seconds.inc(dur_s)
        if cost is not None:
            cost.charge("compiles", 1)
            cost.charge("compile_seconds", dur_s)
        # the compile happened INSIDE the request's wall time: stamp it
        # into the trace retroactively so the span tree shows exactly
        # which compile ate the budget
        try:
            from geomesa_tpu import tracing

            sp = tracing.current_span()
            if sp is not None:
                tracing.record_span(
                    sp, "xla.compile",
                    time.perf_counter() - dur_s, dur_s, signature=sig,
                )
        except Exception:  # pragma: no cover - tracing must not break jit
            pass

    def on_cache_hit(self) -> None:
        sig = self._signature()
        with self._lock:
            self.cache_hits += 1
            ent = self._by_sig.get(sig)
            if ent is not None:
                ent["cache_hits"] += 1
        cost = _cost.get()
        if cost is not None:
            cost.charge("compile_cache_hits", 1)

    def snapshot(self, top: int = 16) -> dict:
        with self._lock:
            sigs = {k: dict(v) for k, v in self._by_sig.items()}
            compiles, total_s = self.compiles, self.total_s
            hits = self.cache_hits
        ranked = sorted(
            sigs.items(), key=lambda kv: kv[1]["total_s"], reverse=True
        )[: max(top, 0)]
        return {
            "compiles": compiles,
            "total_s": round(total_s, 4),
            "cache_hits": hits,
            "by_signature": {
                k: {
                    "compiles": v["compiles"],
                    "total_s": round(v["total_s"], 4),
                    "max_s": round(v["max_s"], 4),
                    "cache_hits": v["cache_hits"],
                    "last_trace_id": v["last_trace_id"],
                }
                for k, v in ranked
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._by_sig.clear()
            self.compiles = 0
            self.total_s = 0.0
            self.cache_hits = 0


_installed = False


def install() -> None:
    """Register the jax.monitoring listeners feeding the compile ledger
    (idempotent; called by make_server and the bench/CLI entry points).
    Safe without jax monitoring support — the ledger then only sees
    what :meth:`CompileLedger.on_backend_compile` is fed directly."""
    global _installed
    if _installed:
        return
    _installed = True
    try:
        from jax import monitoring

        def _on_dur(event, dur_s, **kw):
            if event == "/jax/core/compile/backend_compile_duration":
                COMPILES.on_backend_compile(float(dur_s))

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                COMPILES.on_cache_hit()

        monitoring.register_event_duration_secs_listener(_on_dur)
        monitoring.register_event_listener(_on_event)
    except Exception:  # pragma: no cover - jax without monitoring
        pass


# -- process-wide aggregation -----------------------------------------------


class _Agg:
    """One aggregate bucket (a tenant or a query shape)."""

    __slots__ = ("requests", "errors", "fields", "lat_counts", "lat_sum")

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.fields: dict = {}
        self.lat_counts = [0] * (len(LATENCY_BUCKETS) + 1)
        self.lat_sum = 0.0

    def fold(self, cost: RequestCost, fields: dict) -> None:
        self.requests += 1
        if cost.status >= 500:
            self.errors += 1
        for k, v in fields.items():
            if k in _MAX_FIELDS:
                self.fields[k] = max(self.fields.get(k, 0.0), v)
            else:
                self.fields[k] = self.fields.get(k, 0.0) + v
        self.lat_counts[bisect_left(LATENCY_BUCKETS, cost.dur_s)] += 1
        self.lat_sum += cost.dur_s

    def quantile_ms(self, q: float) -> "float | None":
        """Bucket-upper-bound quantile (prometheus-style estimate)."""
        n = self.requests
        if n <= 0:
            return None
        rank = q * n
        cum = 0
        for i, c in enumerate(self.lat_counts):
            cum += c
            if cum >= rank and c:
                if i < len(LATENCY_BUCKETS):
                    return round(LATENCY_BUCKETS[i] * 1e3, 3)
                return round(
                    max(LATENCY_BUCKETS[-1], self.lat_sum / n) * 1e3, 3
                )
        return round(LATENCY_BUCKETS[-1] * 1e3, 3)

    def to_dict(self) -> dict:
        return {
            "requests": self.requests,
            "errors": self.errors,
            "p50_ms": self.quantile_ms(0.5),
            "p99_ms": self.quantile_ms(0.99),
            "mean_ms": (
                round(self.lat_sum / self.requests * 1e3, 3)
                if self.requests
                else None
            ),
            "cost": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in sorted(self.fields.items())
            },
        }


class CostLedger:
    """Per-tenant / per-shape aggregates + the top-K expensive-request
    ring. The module global :data:`LEDGER` is the serving one; tests
    may build their own."""

    def __init__(self):
        self._lock = checked_lock("ledger.registry")
        self._tenants: dict = {}
        self._shapes: dict = {}
        self._top: list = []  # RequestCost.to_dict()s, by cost_s desc
        self.requests = 0

    @staticmethod
    def _key(table: dict, key: str, cap: int) -> str:
        if key in table or len(table) < cap:
            return key
        return "other"

    def record(self, cost: RequestCost) -> None:
        fields = cost.snapshot_fields()
        with self._lock:
            self.requests += 1
            tk = self._key(self._tenants, cost.tenant or "-", _MAX_TENANTS)
            self._tenants.setdefault(tk, _Agg()).fold(cost, fields)
            sk = self._key(self._shapes, cost.shape or "-", _MAX_SHAPES)
            self._shapes.setdefault(sk, _Agg()).fold(cost, fields)
            doc = cost.to_dict()
            self._top.append(doc)
            self._top.sort(key=lambda d: d["cost_s"], reverse=True)
            del self._top[_TOPK_RING:]
        from geomesa_tpu import metrics

        metrics.ledger_requests.inc()
        metrics.ledger_device_seconds.inc(
            fields.get("device_seconds", 0.0)
        )
        metrics.ledger_compile_seconds.inc(
            fields.get("compile_seconds", 0.0)
        )

    @staticmethod
    def _ranked(table: dict, top: int) -> dict:
        """Rank already-serialized aggregate docs by machine-time cost."""
        def cost_of(doc: dict) -> float:
            c = doc["cost"]
            return (
                c.get("device_seconds", 0.0)
                + c.get("compile_seconds", 0.0)
                + c.get("read_seconds", 0.0)
            )

        ranked = sorted(
            table.items(), key=lambda kv: cost_of(kv[1]), reverse=True
        )
        return dict(ranked[: max(top, 0)])

    def snapshot(self, top: "int | None" = None) -> dict:
        """The ``/stats/ledger`` document. Aggregates serialize UNDER
        the ledger lock: record() mutates the same ``_Agg.fields``
        dicts concurrently, and iterating them live would let a
        first-seen field key raise mid-scrape (the concurrent-writer
        discipline metrics.prometheus_text follows)."""
        if top is None:
            from geomesa_tpu.conf import sys_prop

            top = int(sys_prop("ledger.topk"))
        with self._lock:
            tenants = {k: v.to_dict() for k, v in self._tenants.items()}
            shapes = {k: v.to_dict() for k, v in self._shapes.items()}
            top_reqs = list(self._top[: max(top, 0)])
            requests = self.requests
        return {
            "enabled": enabled(),
            "requests": requests,
            "tenants": self._ranked(tenants, top),
            "shapes": self._ranked(shapes, top),
            "top_requests": top_reqs,
            "compile": COMPILES.snapshot(top),
        }

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._shapes.clear()
            del self._top[:]
            self.requests = 0


LEDGER = CostLedger()
COMPILES = CompileLedger()


def finish_request(cost: "RequestCost | None", trace=None) -> None:
    """Finalize one request: stamp its latency from the finished trace,
    fold degradation stamps, feed the SLO engine, and aggregate into
    the process ledger. Called by the server AFTER the trace context
    exits (the span tree is complete at that point — this is the
    'assembled at trace completion' step). Best-effort by design.
    The two master switches are INDEPENDENT: ``ledger.enabled`` gates
    only the cost fold, the SLO observation is gated by ``slo.enabled``
    inside the engine."""
    if cost is None:
        return
    try:
        if trace is not None and trace.dur_s is not None:
            cost.dur_s = float(trace.dur_s)
            cost.trace_id = trace.trace_id
        if enabled():
            LEDGER.record(cost)
        from geomesa_tpu import slo

        slo.ENGINE.observe(
            endpoint=cost.endpoint,
            lane=cost.lane,
            dur_s=cost.dur_s,
            error=cost.status >= 500,
            trace_id=cost.trace_id,
        )
        # a request that breached its lane's SLO threshold should be
        # inspectable: force-retain its trace so the /metrics exemplar
        # resolves in /debug/traces even when head-sampling declined
        d = slo.slo_for_lane(cost.lane)
        if (
            trace is not None
            and trace.recording
            and (cost.status >= 500 or cost.dur_s * 1e3 > d.threshold_ms)
        ):
            from geomesa_tpu.tracing import TRACER

            TRACER.retain(trace)
    except Exception:  # pragma: no cover - accounting must not break
        pass


# -- span-tree assembly (the trace CLI's per-trace cost view) ---------------

#: span name -> (seconds field, bytes attr -> bytes field)
_SPAN_COSTS = {
    "store.read": ("read_seconds", ("bytes", "read_bytes")),
    "store.decode": ("decode_seconds", None),
    "store.stage": ("stage_seconds", None),
    "xla.compile": ("compile_seconds", None),
}


def cost_from_trace(doc: dict) -> dict:
    """Derive the cost fields recoverable from one trace document's
    span tree (``Trace.to_dict()`` form): device launch count/seconds
    from the ``sched.execute`` spans (fair-share split by the ``fused``
    width), host read/decode/stage time and bytes from the store spans,
    compile time from the retroactive ``xla.compile`` spans, and chunk
    read/prune counts from the ``store.read`` chunk attrs. The live
    collector is authoritative (it sees work even when span recording
    is off); this is the offline view over a captured trace."""
    out: dict = {}

    def add(field: str, amount: float) -> None:
        if field in _MAX_FIELDS:
            out[field] = max(out.get(field, 0.0), amount)
        else:
            out[field] = out.get(field, 0.0) + amount

    def walk(sp: dict) -> None:
        name = sp.get("name", "")
        dur_s = (sp.get("dur_ms") or 0.0) / 1e3
        attrs = sp.get("attrs") or {}
        if name == "sched.execute":
            width = max(int(attrs.get("fused", 1) or 1), 1)
            add("device_launches", 1)
            add("device_seconds", dur_s / width)
            add("fusion_width", width)
        elif name in _SPAN_COSTS:
            sec_field, byte_map = _SPAN_COSTS[name]
            add(sec_field, dur_s)
            if byte_map is not None and byte_map[0] in attrs:
                add(byte_map[1], float(attrs[byte_map[0]]))
            if name == "store.read" and "chunks" in attrs:
                read = float(attrs.get("chunks") or 0)
                total = float(attrs.get("chunk_total") or read)
                add("chunks_read", read)
                add("chunks_pruned", max(total - read, 0.0))
        for c in sp.get("children") or []:
            walk(c)

    root = doc.get("spans")
    if root:
        walk(root)
    return {k: round(v, 6) for k, v in sorted(out.items())}
