"""geomesa_tpu: a TPU-native spatio-temporal indexing and query framework.

Re-implements the capabilities of GeoMesa (reference: jorgeramirez/geomesa, a
fork of locationtech/geomesa) with a JAX/XLA/Pallas execution model:

- space-filling-curve index math (Z2/Z3/XZ2/XZ3) as vectorized bit kernels
  (``geomesa_tpu.curves``)
- columnar SimpleFeature batches (struct-of-arrays, Arrow-fed)
  (``geomesa_tpu.features``)
- CQL-style filters compiled to fused device mask scans
  (``geomesa_tpu.filter``, ``geomesa_tpu.ops``)
- index build = z-key sort + partition manifests (``geomesa_tpu.index``)
- a query planner with strategy costing and partition pruning
  (``geomesa_tpu.query``)
- DataStore-style APIs over in-memory and Parquet filesystem backends
  (``geomesa_tpu.store``)
- pushdown analytics: density, stats sketches, BIN export, kNN
  (``geomesa_tpu.process``, ``geomesa_tpu.stats``)
- multi-chip scaling via jax.sharding meshes + XLA collectives
  (``geomesa_tpu.parallel``)

Subpackages are added as layers land (see the build plan in SURVEY.md
section 7); importing ``geomesa_tpu`` itself is side-effect free -- jax is
loaded lazily and 64-bit mode is enabled only by the code paths that need it
(``geomesa_tpu.jaxconf.require_x64``).

Design notes live in SURVEY.md (structural analysis of the reference) at the
repo root. Citations in docstrings use upstream-canonical GeoMesa paths; the
reference mount was empty at survey time so they are unverified (SURVEY.md
provenance note).
"""

__version__ = "0.1.0"
