"""End-to-end query tracing: per-request span trees across the serving
path.

Ref role: geomesa-utils MethodProfiling + the ``explain`` output are the
reference's de-facto query profiler [UNVERIFIED - empty reference
mount]; PAPER.md section 5 maps them to ``jax.profiler`` traces plus
host timers. :mod:`geomesa_tpu.profiling` keeps the AGGREGATE face of
that mapping (wall time per label, process-wide); this module is the
PER-REQUEST face: when one query is slow, its trace says where the time
went — which fused launch it rode, how long it waited in the scheduler
queue, which partition reads it sat behind.

Model:

- A :class:`Trace` is one request: a trace id, a root :class:`Span`, and
  a tree of timed child spans (name, attrs, start offset, duration,
  thread). Spans nest via a contextvar — ``with span("query.plan"):``
  attaches to whatever span is current on this thread.
- The process-wide :class:`Tracer` (module global ``TRACER``) keeps a
  bounded ring of recent finished traces and decides retention:
  head-sampling (``trace.sample``, the probability a trace is kept) OR
  always-on slow capture (wall time >= ``trace.slow_ms``). Slow traces
  additionally append to the slow-query log (``_slow_queries.jsonl``
  next to the audit log, full trace embedded). ``trace.sample=0`` with
  ``trace.slow_ms=0`` turns recording off entirely — spans become
  no-ops and the only residue is the trace id (requests still get their
  ``X-Request-Id`` echo).
- Context crosses thread pools EXPLICITLY: contextvars are per-thread,
  so a prefetch worker sees no current span unless the consumer's
  context is carried over — :func:`capture` on the submitting thread,
  ``with attach(ctx):`` on the worker (store/prefetch.py does exactly
  this around its work items; the scheduler does it around execution).
  Retroactive spans (queue wait, a shared fused launch fanned out to
  every rider's trace) use :func:`record_span` with an explicit start.

Export: ``Trace.to_dict()`` is the ``/debug/traces/<id>`` JSON;
``Trace.to_perfetto()`` emits Chrome-trace/Perfetto JSON (load in
https://ui.perfetto.dev or chrome://tracing); :func:`format_trace`
pretty-prints the tree (the ``trace`` CLI subcommand).
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import threading
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager

from geomesa_tpu.locking import checked_lock

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "TRACER",
    "span",
    "record_span",
    "capture",
    "attach",
    "current_span",
    "current_trace",
    "current_trace_id",
    "format_trace",
]

_current: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_tpu_span", default=None
)


def _new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _clean_id(trace_id) -> "str | None":
    """Sanitize an inbound (client-supplied) trace id: printable, short,
    no characters that could corrupt a JSONL log line or a URL path."""
    if not trace_id:
        return None
    s = "".join(
        c for c in str(trace_id)[:64] if c.isalnum() or c in "-_.:"
    )
    return s or None


class Span:
    """One timed operation in a trace. ``set(**attrs)`` adds attributes
    after creation (e.g. a row count known only at the end)."""

    __slots__ = (
        "name", "attrs", "start_s", "dur_s", "children", "thread", "trace"
    )

    def __init__(self, name: str, trace: "Trace", start_s: float, attrs):
        self.name = name
        self.trace = trace
        self.start_s = start_s  # relative to the trace's t0
        self.dur_s: "float | None" = None
        self.attrs = dict(attrs) if attrs else {}
        self.children: list = []
        self.thread = threading.current_thread().name

    def set(self, **attrs) -> None:
        # copy-on-write reference swap, never in-place mutation: a
        # serializer (slow-log write, /debug/traces read) may be
        # iterating the attrs dict from another thread while a late
        # prefetch worker is still stamping attributes on this span
        new = dict(self.attrs)
        new.update(attrs)
        self.attrs = new

    def to_dict(self) -> dict:
        # snapshot under the trace lock: begin_span appends children
        # concurrently (workers can outlive the root by a beat)
        with self.trace.lock:
            children = list(self.children)
        return {
            "name": self.name,
            "start_ms": round(self.start_s * 1e3, 3),
            "dur_ms": (
                round(self.dur_s * 1e3, 3) if self.dur_s is not None else None
            ),
            "thread": self.thread,
            "attrs": self.attrs,
            "children": [c.to_dict() for c in children],
        }


class _NoopSpan:
    """Inert span: recording off / no active trace. ``set`` swallows."""

    __slots__ = ()
    trace = None

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class Trace:
    """One request's span tree. Created by :meth:`Tracer.trace`; child
    spans attach via :func:`span` / :func:`record_span`. ``recording``
    False means head-sampling declined AND slow capture is off — the
    trace exists only to carry its id."""

    def __init__(
        self, tracer: "Tracer", name: str, trace_id: str,
        sampled: bool, slow_ms: float, recording: bool,
    ):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.sampled = sampled
        self.slow_ms = slow_ms
        self.recording = recording
        # epoch anchor for summaries + Perfetto timestamps (wall-clock by
        # design; every duration below uses perf_counter)
        self.t0_epoch = time.time()  # lint: disable=GT003(epoch anchor for trace export; durations use perf_counter)
        self.t0 = time.perf_counter()
        self.dur_s: "float | None" = None
        self.slow = False
        self.lock = checked_lock("tracing.trace")
        self.root = (
            Span(name, self, 0.0, None) if recording else _NOOP
        )

    # -- span plumbing (called by the module-level helpers) ----------------

    def begin_span(self, name: str, parent: Span, attrs) -> Span:
        sp = Span(name, self, time.perf_counter() - self.t0, attrs)
        with self.lock:
            parent.children.append(sp)
        return sp

    def add_finished(
        self, name: str, parent: Span, start_perf: float, dur_s: float, attrs
    ) -> Span:
        """A retroactive span: timed elsewhere (queue wait, a shared
        fused launch), attached once its duration is known."""
        sp = Span(name, self, start_perf - self.t0, attrs)
        sp.dur_s = dur_s
        with self.lock:
            parent.children.append(sp)
        return sp

    def finish(self) -> None:
        self.dur_s = time.perf_counter() - self.t0
        if self.recording:
            self.root.dur_s = self.dur_s
        self.slow = self.slow_ms > 0 and self.dur_s * 1e3 >= self.slow_ms
        self.tracer._finish(self)

    # -- export -------------------------------------------------------------

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "ts": round(self.t0_epoch, 3),
            "duration_ms": (
                round(self.dur_s * 1e3, 3) if self.dur_s is not None else None
            ),
            "sampled": self.sampled,
            "slow": self.slow,
        }

    def to_dict(self) -> dict:
        doc = self.summary()
        doc["spans"] = (
            self.root.to_dict() if isinstance(self.root, Span) else None
        )
        return doc

    def to_perfetto(self) -> dict:
        """Chrome-trace (Perfetto-loadable) JSON: one complete ("X")
        event per span, microsecond timestamps anchored at the trace's
        epoch start, tids mapped from python thread names."""
        events: list = []
        tids: dict = {}

        def tid_of(thread: str) -> int:
            if thread not in tids:
                tids[thread] = len(tids) + 1
                events.append({
                    "name": "thread_name", "ph": "M", "pid": 1,
                    "tid": tids[thread], "args": {"name": thread},
                })
            return tids[thread]

        def walk(sp: Span) -> None:
            events.append({
                "name": sp.name,
                "ph": "X",
                "ts": round((self.t0_epoch + sp.start_s) * 1e6, 1),
                "dur": round((sp.dur_s or 0.0) * 1e6, 1),
                "pid": 1,
                "tid": tid_of(sp.thread),
                "cat": "geomesa",
                "args": dict(sp.attrs),
            })
            with self.lock:  # same late-append race as Span.to_dict
                kids = list(sp.children)
            for c in kids:
                walk(c)

        if isinstance(self.root, Span):
            walk(self.root)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id, "name": self.name},
        }


class Tracer:
    """Process-wide trace registry: starts traces (sampling decision),
    keeps a bounded ring of recent finished ones, writes the slow-query
    log. The module global :data:`TRACER` is the one the serving path
    uses; tests may build their own."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = checked_lock("tracing.ring")
        self._ring: OrderedDict = OrderedDict()  # trace_id -> Trace
        #: slow-query JSONL path; None = no slow log (set by make_server
        #: next to the store's audit log)
        self.slow_log_path: "str | None" = None
        # serializes slow-log appends; holding across the write is the
        # lock's whole purpose (one JSONL line per trace, never torn)
        self._log_lock = checked_lock("tracing.slowlog", blocking_ok=True)

    @contextmanager
    def trace(self, name: str, trace_id=None, attrs=None):
        """Open a root span for one request. Yields the :class:`Trace`
        (never None — even unrecorded traces carry an id for the
        ``X-Request-Id`` echo); on exit the trace finishes and retention
        is decided (ring buffer if sampled or slow; slow log if slow)."""
        from geomesa_tpu.conf import sys_prop

        try:
            sample = float(sys_prop("trace.sample"))
            slow_ms = float(sys_prop("trace.slow_ms"))
        except Exception:
            # a malformed GEOMESA_TPU_TRACE_* env value must degrade
            # tracing, never drop the request it wraps — fall back to
            # slow-capture-only (the always-on safety net)
            sample, slow_ms = 0.0, 500.0
        sampled = sample > 0 and random.random() < sample
        recording = sampled or slow_ms > 0
        t = Trace(
            self, name, _clean_id(trace_id) or _new_trace_id(),
            sampled, slow_ms, recording,
        )
        if attrs and recording:
            t.root.set(**attrs)
        token = _current.set(t.root if recording else _NOOP)
        try:
            yield t
        finally:
            _current.reset(token)
            t.finish()

    def _finish(self, t: Trace) -> None:
        if not t.recording or not (t.sampled or t.slow):
            return
        try:
            from geomesa_tpu import metrics

            metrics.traces_captured.inc()
            if t.slow:
                metrics.slow_queries.inc()
        except Exception:  # pragma: no cover - observability must not break
            pass
        with self._lock:
            self._ring[t.trace_id] = t
            self._ring.move_to_end(t.trace_id)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
        if t.slow and self.slow_log_path:
            self._write_slow(t)

    def _write_slow(self, t: Trace) -> None:
        try:
            doc = t.to_dict()
            line = json.dumps(doc, default=str)
            with self._log_lock:
                d = os.path.dirname(self.slow_log_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                # lint: disable=GT002(appending under the lock is its purpose: one un-torn JSONL line per slow trace)
                with open(self.slow_log_path, "a") as fh:
                    fh.write(line + "\n")  # lint: disable=GT002(same un-torn append under the slow-log lock)
        except Exception:  # pragma: no cover - the log must not break serving
            pass

    def retain(self, t: Trace) -> None:
        """Force-retain a finished trace in the recent-trace ring even
        when head-sampling declined and it beat the slow threshold —
        the SLO engine calls this for requests that breached their
        lane's objective, so the ``/metrics`` exemplar pointing at the
        trace id actually resolves in ``/debug/traces``. No-op for
        unrecorded traces (there is no span tree to show)."""
        if not t.recording:
            return
        with self._lock:
            self._ring[t.trace_id] = t
            self._ring.move_to_end(t.trace_id)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)

    # -- read side (the /debug/traces endpoints + the trace CLI) -----------

    def get(self, trace_id: str) -> "Trace | None":
        with self._lock:
            return self._ring.get(trace_id)

    def recent(self, limit: int = 50) -> "list[dict]":
        """Newest-first summaries of the retained traces."""
        if limit <= 0:
            return []
        with self._lock:
            traces = list(self._ring.values())
        return [t.summary() for t in reversed(traces[-limit:])]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


TRACER = Tracer()


# -- context helpers --------------------------------------------------------


def current_span():
    """The active span on THIS thread (None when untraced)."""
    sp = _current.get()
    return None if sp is None or sp is _NOOP else sp


def current_trace() -> "Trace | None":
    sp = current_span()
    return sp.trace if sp is not None else None


def current_trace_id() -> str:
    """The active trace id, or "" — the audit-event stamp."""
    t = current_trace()
    return t.trace_id if t is not None else ""


def capture():
    """The current span, to carry across a thread pool: pass the return
    value to :func:`attach` (or ``span(..., parent=ctx)``) on the worker.
    Contextvars are per-thread — a worker that skips this records
    nothing (by design: no implicit thread-locals across pools)."""
    return current_span()


@contextmanager
def attach(ctx):
    """Make ``ctx`` (a captured span, or None) current on this thread
    for the block — the worker-side half of :func:`capture`."""
    token = _current.set(ctx if ctx is not None else None)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextmanager
def span(name: str, parent=None, **attrs):
    """``with span("store.read", pid=3) as sp:`` — a timed child of the
    current span (or of ``parent``, for explicit cross-thread
    parenting). No active trace -> a shared no-op span; ``sp.set(...)``
    always works."""
    p = parent if parent is not None else _current.get()
    if p is None or p is _NOOP:
        yield _NOOP
        return
    sp = p.trace.begin_span(name, p, attrs)
    token = _current.set(sp)
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        sp.dur_s = time.perf_counter() - t0
        _current.reset(token)


def record_span(parent, name: str, start_perf: float, dur_s: float, **attrs):
    """Attach an already-timed span under ``parent`` (a captured span):
    queue waits and shared fused launches are timed by the scheduler and
    fanned out to every rider's trace after the fact."""
    if parent is None or parent is _NOOP:
        return None
    return parent.trace.add_finished(name, parent, start_perf, dur_s, attrs)


# -- pretty printer (the `trace` CLI subcommand) ----------------------------


def format_trace(doc: dict) -> str:
    """Human-readable tree for a ``Trace.to_dict()`` document (also
    accepts the slow-query log's embedded form)."""
    head = (
        f"trace {doc.get('trace_id')}  {doc.get('name')}  "
        f"{doc.get('duration_ms')}ms"
    )
    flags = [k for k in ("sampled", "slow") if doc.get(k)]
    if flags:
        head += f"  [{', '.join(flags)}]"
    lines = [head]
    total = doc.get("duration_ms") or 0.0
    root = doc.get("spans")

    def walk(sp: dict, prefix: str, last: bool) -> None:
        branch = "`- " if last else "|- "
        dur = sp.get("dur_ms")
        pct = (
            f" ({dur / total * 100:.0f}%)"
            if dur is not None and total
            else ""
        )
        attrs = sp.get("attrs") or {}
        a = (
            "  " + " ".join(f"{k}={v}" for k, v in attrs.items())
            if attrs
            else ""
        )
        lines.append(
            f"{prefix}{branch}{sp['name']:<28} "
            f"{dur if dur is not None else '?':>9}ms{pct}"
            f"  @{sp.get('thread', '')}{a}"
        )
        kids = sp.get("children") or []
        ext = "   " if last else "|  "
        for i, c in enumerate(kids):
            walk(c, prefix + ext, i == len(kids) - 1)

    if root:
        lines.append(
            f"`- {root['name']:<28} {root.get('dur_ms')}ms  "
            f"@{root.get('thread', '')}"
        )
        kids = root.get("children") or []
        for i, c in enumerate(kids):
            walk(c, "   ", i == len(kids) - 1)
    else:
        lines.append("(no spans recorded)")
    return "\n".join(lines)


def coverage(doc: dict) -> float:
    """Fraction of the root span's wall time covered by the union of its
    descendant spans' intervals (the acceptance-criteria number: a trace
    whose children explain >= 95% of the request)."""
    root = doc.get("spans")
    if not root or not root.get("dur_ms"):
        return 0.0
    intervals: list = []

    def walk(sp: dict) -> None:
        for c in sp.get("children") or []:
            if c.get("dur_ms") is not None:
                intervals.append(
                    (c["start_ms"], c["start_ms"] + c["dur_ms"])
                )
            walk(c)

    walk(root)
    if not intervals:
        return 0.0
    intervals.sort()
    covered = 0.0
    cur_lo, cur_hi = intervals[0]
    for lo, hi in intervals[1:]:
        if lo > cur_hi:
            covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    covered += cur_hi - cur_lo
    return min(1.0, covered / root["dur_ms"])
