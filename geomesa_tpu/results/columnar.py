"""Columnar assembly helpers for the result plane.

The hot-path rule of the whole package: a result column is born as a
numpy buffer (vectorized take over the staged host mirror) and stays a
buffer until pyarrow wraps it — no per-feature Python between the
device's compacted row ids and the wire.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType

#: numpy dtype kind -> SFT attribute type for extra result columns
_EXTRA_TYPES = (
    ("f", "Double"),
    ("i", "Long"),
    ("u", "Long"),
    ("b", "Boolean"),
)


def _extra_type_name(arr: np.ndarray) -> str:
    for kind, tname in _EXTRA_TYPES:
        if arr.dtype.kind == kind:
            return tname
    return "String"


def with_extra_columns(batch: FeatureBatch, extra: dict) -> FeatureBatch:
    """A new batch whose SFT grows one REAL attribute per ``extra``
    entry (name -> per-row values) — process outputs like kNN
    distances become typed Arrow/BIN-exportable columns instead of a
    GeoJSON-only ``zip`` loop over rendered features. Values are
    coerced as whole arrays (vectorized); names must not collide with
    existing attributes."""
    if not extra:
        return batch
    clash = [n for n in extra if n in batch.sft.attribute_names]
    if clash:
        raise ValueError(f"extra columns {clash} collide with the schema")
    spec = batch.sft.spec
    cols = dict(batch.columns)
    for name, vals in extra.items():
        arr = np.asarray(vals)  # lint: disable=GT004(host-list coercion of extra columns: no device array is in play)
        if len(arr) != len(batch):
            raise ValueError(
                f"extra column {name!r} has {len(arr)} rows, "
                f"expected {len(batch)}"
            )
        tname = _extra_type_name(arr)
        if tname == "String":
            arr = arr.astype(object)
        spec += f",{name}:{tname}"
        cols[name] = arr
    sft = SimpleFeatureType.create(batch.sft.type_name, spec)
    return FeatureBatch.from_columns(sft, cols, batch.fids)


def capped_batches(batches, cap: "int | None"):
    """Stream ``batches`` up to ``cap`` total rows (MaxFeatures across
    a multi-batch stream has cross-batch semantics: trim the batch that
    crosses the cap, stop pulling after it — upstream partition reads
    past the cap are never decoded)."""
    if cap is None:
        yield from batches
        return
    left = int(cap)
    for b in batches:
        if left <= 0:
            break
        if len(b) > left:
            b = b.take(np.arange(left))
        left -= len(b)
        if len(b):
            yield b
