"""Streamed wire encoders: chunked Arrow IPC and BIN record streams.

The server's chunked responses and the bulk export jobs consume the
SAME generators, so serving and export share one encoder stack (ref:
the reference's DeltaWriter serves both its WFS output format and its
bulk exports). Memory is bounded by construction: each yielded chunk
covers at most ``results.batch.rows`` rows and is handed to the
consumer (socket / file) before the next is encoded — the
whole-response ``BytesIO`` buffering this module replaces is gone.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.conf import sys_prop


class _ChunkSink:
    """Minimal binary sink handing written bytes to the consumer in
    write order (pyarrow's IPC writer flushes one encapsulated message
    per write_batch, so drains align with IPC message boundaries)."""

    closed = False  # file protocol (pyarrow wraps python sinks)

    def __init__(self):
        self._parts: list = []

    def write(self, data) -> int:
        b = bytes(data)
        self._parts.append(b)
        return len(b)

    def writable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return False

    def flush(self) -> None:  # nothing buffered here
        pass

    def close(self) -> None:
        # the IPC writer closes its sink; keep draining the EOS marker
        pass

    def drain(self) -> bytes:
        if not self._parts:
            return b""
        out = b"".join(self._parts)
        self._parts.clear()
        return out


def _rows_per_chunk(chunk_rows: "int | None") -> int:
    if chunk_rows is None:
        chunk_rows = int(sys_prop("results.batch.rows"))
    return max(int(chunk_rows), 1)


def arrow_stream_chunks(
    batches,
    sft=None,
    *,
    chunk_rows: "int | None" = None,
    sort_key: "str | None" = None,
    presorted: "str | None" = None,
    dict_encode: "tuple[str, ...] | None" = None,
    with_visibility: "bool | None" = None,
):
    """Yield one delta-dictionary Arrow IPC stream as incremental byte
    chunks: the first record batch is yielded while later input batches
    are still being produced (out-of-core partition scans keep
    prefetching behind the socket), string dictionaries grow
    monotonically across chunks and only deltas retransmit.

    ``sort_key`` sorts each INPUT batch before chunking (one vectorized
    argsort per batch); streams sorted that way can be k-way merged by
    that column (``merge_delta_streams``). ``presorted`` instead STAMPS
    an order into the stream's schema metadata without re-sorting — the
    Z-sorted resident path uses it to emit sorted record batches with
    no host re-sort (the stamp is a column name when the stream carries
    one, else an order tag like ``"z"``; see SORT_KEY_META).
    ``with_visibility``
    None auto-detects from the first batch and fails loudly if a LATER
    batch introduces labels an unlabeled schema cannot carry."""
    from geomesa_tpu.arrow_io.io import (
        DeltaWriter,
        ensure_labels_representable,
    )
    from geomesa_tpu.security import VIS_COLUMN

    rows = _rows_per_chunk(chunk_rows)
    it = iter(batches)
    first = next(it, None)
    sink = _ChunkSink()
    if first is None:
        if sft is None:
            raise ValueError("empty stream needs an explicit sft")
        with DeltaWriter(
            sink, sft, dict_encode=dict_encode,
            with_visibility=bool(with_visibility), presorted=presorted,
        ):
            pass
        yield sink.drain()
        return
    auto = with_visibility is None
    want_vis = (
        VIS_COLUMN in first.columns if auto else bool(with_visibility)
    )
    writer = DeltaWriter(
        sink, sft or first.sft, dict_encode=dict_encode,
        with_visibility=want_vis, presorted=presorted,
    )
    try:
        b = first
        while b is not None:
            ensure_labels_representable(auto, want_vis, b)
            if sort_key is not None:
                b = b.take(np.argsort(b.column(sort_key), kind="stable"))
            if len(b) <= rows:
                writer.write(b)
                yield sink.drain()
            else:
                for i in range(0, len(b), rows):
                    writer.write(
                        b.take(np.arange(i, min(i + rows, len(b))))
                    )
                    yield sink.drain()
            b = next(it, None)
    finally:
        writer.close()
        close = getattr(it, "close", None)
        if close is not None:
            # abandonment propagates upstream NOW (a partition stream
            # joins its prefetch workers), not at GC time
            close()
    tail = sink.drain()  # the IPC end-of-stream marker
    if tail:
        yield tail


def bin_stream_chunks(
    batches,
    track_attr: str,
    *,
    dtg_attr: "str | None" = None,
    geom_attr: "str | None" = None,
    label_attr: "str | None" = None,
    sort: bool = False,
):
    """Yield BIN track-record bytes per input batch (16B or 24B
    records; vectorized numpy encode). ``sort`` orders WITHIN each
    batch — globally dtg-sorted output is the resident rider's job
    (one result set = one batch there); multi-batch store streams
    document per-batch order, exactly the reference's per-iterator BIN
    aggregation semantics."""
    from geomesa_tpu.process.binexport import encode_bin

    it = iter(batches)
    try:
        for b in it:
            if not len(b):
                continue
            yield encode_bin(
                b, track_attr, dtg_attr=dtg_attr, geom_attr=geom_attr,
                label_attr=label_attr, sort=sort,
            )
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()


def write_arrow_stream_file(path: str, batches, sft=None, **kw) -> int:
    """Stream FeatureBatches to ``path`` through the same chunked delta
    encoder the server streams responses from; returns bytes written.
    Bounded memory: each chunk hits the file before the next encodes."""
    total = 0
    with open(path, "wb") as fh:
        for chunk in arrow_stream_chunks(batches, sft, **kw):
            fh.write(chunk)
            total += len(chunk)
    return total
