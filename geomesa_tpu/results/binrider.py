"""BIN engine selection: the fused device pack vs its numpy host twin.

``DeviceIndex.bin_rider`` packs the 16/24-byte track records on device
(count→cap→compact, the ``_mesh_hits`` discipline) so only packed
record bytes cross back to host; ``DeviceIndex.bin_export`` is the
bit-identical numpy twin. ``results.bin.engine`` picks, with ``auto``
following the ``mesh.sort.engine`` precedent: the host twin on all-CPU
platforms (numpy beats a jitted emulation there), the device pack
whenever a real accelerator is visible.
"""

from __future__ import annotations


def bin_engine() -> str:
    """Resolve ``results.bin.engine`` (auto -> host on all-CPU)."""
    from geomesa_tpu.conf import sys_prop

    eng = sys_prop("results.bin.engine")
    if eng != "auto":
        return eng
    import jax

    return (
        "host"
        if all(d.platform == "cpu" for d in jax.devices())
        else "device"
    )


def resident_bin(
    di,
    query,
    track_attr: str,
    *,
    dtg_attr: "str | None" = None,
    geom_attr: "str | None" = None,
    label_attr: "str | None" = None,
    sort: bool = False,
    loose: "bool | None" = None,
    auths=None,
) -> bytes:
    """BIN bytes for a resident index's hits under the configured
    engine. The device rider declines shapes it cannot express
    (labeled staging, host-residual filters, non-point geometry) —
    ``auto``/``host`` fall to the twin; a pinned ``device`` raises so
    an operator's explicit pin never silently changes engines."""
    kw = dict(
        dtg_attr=dtg_attr, geom_attr=geom_attr, label_attr=label_attr,
        sort=sort, loose=loose, auths=auths,
    )
    eng = bin_engine()
    if eng != "host":
        data = di.bin_rider(query, track_attr, **kw)
        if data is not None:
            return data
        if eng == "device":
            raise ValueError(
                "results.bin.engine=device but the query shape is not "
                "device-expressible (labeled staging, host-residual "
                "filter or non-point geometry); use auto or host"
            )
    return di.bin_export(query, track_attr, **kw)
