"""Arrow-native result plane: device-compacted hits to the wire with
no per-feature Python (ISSUE 12; ROADMAP item 3).

Ref role: geomesa-arrow's DeltaWriter tier + BinaryOutputEncoder's BIN
track format [UNVERIFIED - empty reference mount] — the reference keeps
response encoding columnar all the way to the socket; this package does
the same for the TPU serving stack, where the scan core emits hits at
device rates and the interpreter must never own the response again.

Pieces:

- :mod:`~geomesa_tpu.results.negotiate` — one content-negotiation table
  (``f=`` query param > ``Accept`` header > GeoJSON) shared by every
  feature-emitting endpoint.
- :mod:`~geomesa_tpu.results.stream` — streamed encoders: chunked
  delta-dictionary Arrow IPC (first batch flushes while later batches
  are still assembling; per-chunk memory bounded by
  ``results.batch.rows``) and BIN record streams, consumed by the
  server's chunked responses AND the bulk export jobs — one encoder
  stack for both.
- :mod:`~geomesa_tpu.results.columnar` — columnar assembly helpers:
  extra per-feature outputs (kNN distances …) become REAL Arrow
  columns via an extended SFT, never a per-feature ``zip`` loop.
- :mod:`~geomesa_tpu.results.binrider` — the BIN engine selector:
  fused device pack (``DeviceIndex.bin_rider``, count→cap→compact)
  with the numpy host twin, switched by ``results.bin.engine``.
"""

from geomesa_tpu.results.columnar import capped_batches, with_extra_columns
from geomesa_tpu.results.negotiate import (
    CONTENT_TYPES,
    FORMATS,
    PUSH_CONTENT_TYPES,
    negotiate_format,
)
from geomesa_tpu.results.binrider import bin_engine, resident_bin
from geomesa_tpu.results.stream import (
    arrow_stream_chunks,
    bin_stream_chunks,
    write_arrow_stream_file,
)

__all__ = [
    "CONTENT_TYPES",
    "FORMATS",
    "PUSH_CONTENT_TYPES",
    "arrow_stream_chunks",
    "bin_engine",
    "capped_batches",
    "bin_stream_chunks",
    "negotiate_format",
    "resident_bin",
    "with_extra_columns",
    "write_arrow_stream_file",
]
