"""Wire-format content negotiation for feature-emitting endpoints.

One table: the explicit ``f=`` query parameter wins, else the request's
``Accept`` header is scanned in client order for a media type we serve,
else GeoJSON. Every endpoint that emits features routes through
:func:`negotiate_format` so ``/features``, ``/knn``, ``/tube`` and
``/proximity`` agree on the same spellings and content types.
"""

from __future__ import annotations

#: formats the result plane serves, in documentation order
FORMATS = ("geojson", "arrow", "bin")

#: response Content-Type per format
CONTENT_TYPES = {
    "geojson": "application/json",
    "arrow": "application/vnd.apache.arrow.stream",
    "bin": "application/vnd.geomesa.bin",
}

#: Content-Type per format on the PUSH plane (``GET /subscribe/<type>``,
#: long-lived continuous-query streams): geojson rides Server-Sent
#: Events (one ``match`` event per batch, ``id:`` = WAL-seq cursor,
#: ``:keepalive`` heartbeats); arrow and bin keep their pull-plane
#: framing — the negotiation table is shared, only the envelope differs
PUSH_CONTENT_TYPES = {
    "geojson": "text/event-stream",
    "arrow": CONTENT_TYPES["arrow"],
    "bin": CONTENT_TYPES["bin"],
}

#: ``f=`` spellings accepted per format (case-insensitive)
_PARAM_ALIASES = {
    "geojson": "geojson",
    "json": "geojson",
    "arrow": "arrow",
    "bin": "bin",
}

#: Accept-header media types we recognize (exact match per entry)
_ACCEPT_TYPES = {
    "application/vnd.apache.arrow.stream": "arrow",
    "application/vnd.geomesa.bin": "bin",
    "application/geo+json": "geojson",
    "application/json": "geojson",
}


def negotiate_format(q: dict, accept: "str | None" = None) -> str:
    """Resolve the response format for a request.

    ``q`` is the parsed query dict (``f=`` wins; an unknown value
    raises ValueError -> 400, never a silent GeoJSON fallback), then
    the ``Accept`` header's media types in client order (first
    recognized type wins; a ``;q=0`` entry is an explicit rejection
    and is skipped, other q-weights are not ranked; ``*/*`` and
    unknown types fall through), then GeoJSON."""
    f = q.get("f")
    if f is not None:
        fmt = _PARAM_ALIASES.get(f.strip().lower())
        if fmt is None:
            raise ValueError(f"unknown format {f!r}")
        return fmt
    for part in (accept or "").split(","):
        media, _, params = part.partition(";")
        fmt = _ACCEPT_TYPES.get(media.strip().lower())
        if fmt is None:
            continue
        rejected = False
        for p in params.split(";"):
            k, _, v = p.partition("=")
            if k.strip().lower() == "q":
                try:
                    rejected = float(v.strip()) == 0.0
                except ValueError:
                    pass
                break
        if not rejected:
            return fmt
    return "geojson"
