"""Fusable-query descriptors for the device query scheduler.

A FusableQuery names ONE resident-index query (count or query/features)
that the micro-batcher may execute as part of a shared device launch.
Compatibility is decided in two stages: the cheap queue-level key (same
index object, same operation, same loose/auths signature) groups
candidates, and the DeviceIndex fused launch itself (``fused_loose_*``)
makes the final call — it returns None for groups whose z-range sets
cannot share a kernel (mixed engines, a filter the key planes cannot
answer), and the scheduler falls back to per-query serial execution,
which is always available and always exact.
"""

from __future__ import annotations


class FusableQuery:
    """One scheduler-visible resident query.

    ``op`` is "count" (fused result: int) or "query" (fused result:
    FeatureBatch). ``fusable`` is False when the loose key-plane engine
    cannot possibly answer (loose off for the request, or the index has
    no key planes) — the scheduler then skips the fusion window and runs
    the serial callable directly under admission control only.
    """

    __slots__ = ("di", "query", "op", "loose", "auths", "fusable")

    def __init__(self, di, query, op: str, loose=None, auths=None):
        if op not in ("count", "query"):
            raise ValueError(f"unknown fusable op {op!r}")
        self.di = di
        self.query = query
        self.op = op
        self.loose = loose
        self.auths = tuple(sorted(str(a) for a in (auths or ())))
        self.fusable = bool(di is not None and di._resolve_loose(loose))

    @property
    def key(self):
        """Queue-level compatibility: requests sharing a key MAY ride one
        device launch (the index makes the final call)."""
        return (id(self.di), self.op, bool(self.loose), self.auths)

    @property
    def mesh_shards(self) -> int:
        """Shards the index's launches span (0 = single-device index) —
        rides the scheduler's launch spans so a trace shows whether a
        fused group ran mesh-wide."""
        return int(getattr(self.di, "mesh_shards", 0) or 0)

    def run_serial(self):
        """The unfused (exact-parity) execution of this one query."""
        if self.op == "count":
            return self.di.count(self.query, loose=self.loose,
                                 auths=self.auths)
        return self.di.query(self.query, loose=self.loose, auths=self.auths)


def execute_group(specs: "list[FusableQuery]"):
    """Run a compatible group as ONE batched device launch — on a
    mesh-sharded index that launch is SPMD across every shard (each
    shard scans its resident Z-range for the whole stacked query set;
    partial counts all-reduce, hit planes gather once), so the fused
    micro-batch costs one mesh-wide kernel pass, not queries x shards.
    Returns the per-query results aligned with ``specs``, or None when
    the index declines to fuse (caller falls back to serial)."""
    from geomesa_tpu.tracing import span

    di = specs[0].di
    queries = [s.query for s in specs]
    with span(
        "fusion.launch", op=specs[0].op, queries=len(queries),
        shards=specs[0].mesh_shards,
    ):
        if specs[0].op == "count":
            return di.fused_loose_counts(queries, loose=specs[0].loose)
        return di.fused_loose_query(queries, loose=specs[0].loose)
