"""Admission control + micro-batch fusion for the device serving path.

Architecture (the tablet-server scan-executor pool, re-shaped for an
accelerator):

- **Admission controller.** Requests enter a bounded queue; when it is
  full they are rejected immediately (the server maps this to HTTP 429 +
  ``Retry-After``) instead of piling up one thread per request. A fixed
  pool of ``max_inflight`` workers is the device concurrency cap — the
  accelerator serializes launches anyway, so more concurrent launchers
  only add queueing in the runtime where nothing can observe it.

- **Micro-batcher.** When a worker dequeues a fusable request (a
  resident loose count/features query) it drains every queued compatible
  request and holds a short fusion window for late arrivals, then
  executes the whole group as ONE stacked device launch
  (``DeviceIndex.fused_loose_*``: per-query z-range sets stack along a
  leading query axis and a single vmapped zscan dispatch answers all of
  them). Batch hardware rewards exactly this shape: K compatible queries
  cost one kernel's bandwidth pass, not K.

- **Priority lanes + tenant fairness.** Two lanes (interactive before
  batch); within a lane, tenants are drained round-robin so one noisy
  client cannot starve the rest. Fusion groups may span tenants — a
  shared launch makes everyone in it faster.

- **Deadlines.** Every request carries an absolute deadline; requests
  that expire while queued are completed with :class:`DeadlineExpired`
  (never executed), and submitters stop waiting at their deadline. A
  request already executing runs to completion — device launches are
  not cancellable mid-flight.

- **Failure domains (resilience.py).** Every claimed group is tracked
  in-flight; a watchdog thread fails groups stuck past
  ``resilience.launch.timeout.s`` with :class:`LaunchStuckError`
  (records a device-breaker failure) and REPLACES the wedged worker —
  a hung device launch costs one abandoned thread, not a scheduler
  lane. A worker-level crash (``fail.sched.worker``) fails its group's
  unfinished requests typed and the worker keeps serving. Completion
  is idempotent: between the watchdog, the crash handler and normal
  execution every request gets EXACTLY one response.

- **Adaptive Retry-After.** 429 rejections carry a Retry-After derived
  from live queue depth and an EWMA of per-request service time
  (depth x service / workers), jittered 0.75-1.25x so a synchronized
  client fleet de-correlates instead of re-spiking admission; the
  static ``sched.retry.after.s`` is only the no-data fallback.

Observability: queue depth, wait time, launches, fusion factor
(queries / launches), rejections and expirations — exported through
:mod:`geomesa_tpu.metrics` and the server's ``/stats/sched`` endpoint.
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass

from geomesa_tpu.spawn import spawn_thread

_retry_rng = random.Random()  # Retry-After jitter (de-correlates clients)

LANE_INTERACTIVE = "interactive"
LANE_BATCH = "batch"
#: streaming appends: highest priority BY DESIGN — an append is a
#: sub-millisecond host-side unit (WAL write + memtable insert; its
#: own 429 bound is the wal.max.generations backpressure), and queueing
#: acks behind multi-second device scans would put a flush back on the
#: ack path. Admission/deadline/fairness apply like any lane.
LANE_INGEST = "ingest"
_LANES = (LANE_INGEST, LANE_INTERACTIVE, LANE_BATCH)


class RejectedError(RuntimeError):
    """Admission queue full: shed the request now (HTTP 429)."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"scheduler queue full; retry after {retry_after_s:g}s"
        )
        self.retry_after_s = retry_after_s


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before it could execute."""


@dataclass
class SchedConfig:
    """Tuning knobs for :class:`QueryScheduler`.

    ``max_queue`` bounds admitted-but-waiting requests (the backpressure
    point); ``max_inflight`` is the worker count (device concurrency
    cap); ``fusion_window_ms`` is how long a worker holds a fusable
    request for compatible late arrivals (0 fuses only already-queued
    requests); ``max_fusion`` caps queries per device launch;
    ``default_deadline_ms`` applies when a request carries none (None =
    unbounded); ``retry_after_s`` rides the 429 Retry-After header."""

    max_queue: int = 128
    max_inflight: int = 2
    fusion_window_ms: float = 2.0
    max_fusion: int = 64
    default_deadline_ms: "float | None" = 30_000.0
    retry_after_s: float = 1.0

    @staticmethod
    def from_props() -> "SchedConfig":
        """Defaults from the ``sched.*`` system properties (conf.py key
        registry) -- what ``QueryScheduler()`` with no explicit config
        uses, so a deployment can tune admission/fusion via environment
        (``GEOMESA_TPU_SCHED_MAX_QUEUE=...``) without code changes. A
        non-positive ``sched.default.deadline.ms`` means no deadline.

        ``max_fusion`` snaps UP onto the compile-shape ladder
        (:mod:`geomesa_tpu.bucketing`): the fusion width becomes a jit
        batch capacity downstream, so an off-ladder cap (say 48) would
        mint compile shapes the warmup plan does not enumerate."""
        from geomesa_tpu.bucketing import bucket_cap
        from geomesa_tpu.conf import sys_prop

        deadline = float(sys_prop("sched.default.deadline.ms"))
        return SchedConfig(
            max_queue=int(sys_prop("sched.max.queue")),
            max_inflight=int(sys_prop("sched.max.inflight")),
            fusion_window_ms=float(sys_prop("sched.fusion.window.ms")),
            max_fusion=bucket_cap(int(sys_prop("sched.max.fusion"))),
            default_deadline_ms=deadline if deadline > 0 else None,
            retry_after_s=float(sys_prop("sched.retry.after.s")),
        )


_USE_DEFAULT = object()  # submit(): "no deadline_ms given, apply config"


class _Request:
    __slots__ = (
        "fn", "fuse", "lane", "tenant", "deadline", "enqueued",
        "event", "result", "error", "state", "ctx", "t0_perf",
        "degraded", "device", "cost",
    )

    def __init__(self, fn, fuse, lane, tenant, deadline, device=False):
        from geomesa_tpu import ledger, resilience, tracing

        self.fn = fn
        self.fuse = fuse
        self.device = device
        self.lane = lane
        self.tenant = tenant
        self.deadline = deadline
        self.enqueued = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.state = "queued"  # -> running -> done
        # the submitter's span, captured EXPLICITLY: the worker that
        # executes this request attaches it so plan/launch/store spans
        # land in the submitting request's trace, and the queue-wait +
        # execute spans fan out to every rider of a fused launch
        self.ctx = tracing.capture()
        # the submitter's degradation collector rides the same way, so
        # a degraded note from work on a scheduler thread lands in the
        # submitting request's X-Degraded header / audit event
        self.degraded = resilience.capture_degraded()
        # ...and so does the cost ledger: device seconds burned on a
        # worker thread are charged to the request that asked for them
        self.cost = ledger.capture_cost()
        self.t0_perf = time.perf_counter()


class QueryScheduler:
    """Bounded-queue device query scheduler (see module docstring).

    >>> sched = QueryScheduler(SchedConfig(max_inflight=1))
    >>> sched.run(fn=lambda: 42)
    42
    >>> sched.run(fuse=FusableQuery(di, cql, "count", loose=True))
    """

    def __init__(self, config: "SchedConfig | None" = None):
        self.config = config or SchedConfig.from_props()
        self._cv = threading.Condition()
        # lane -> tenant -> deque of queued requests (RR over tenants)
        self._queues: dict = {lane: OrderedDict() for lane in _LANES}
        self._queued = 0
        self._running = 0  # claimed but not yet finished (close() drains)
        self._stop = False
        # counters for snapshot(); the process-global metrics mirror them
        self.queries = 0
        self.launches = 0
        self.fused_queries = 0
        self.rejected = 0
        self.expired = 0
        self.worker_failures = 0  # crashes survived (group failed typed)
        self.watchdog_timeouts = 0  # stuck launches failed + replaced
        self._wait_sum = 0.0
        self._svc_ewma = None  # EWMA per-request service seconds
        self._launch_seq = 0  # device-launch ids for trace tagging
        # in-flight groups for the launch watchdog: token ->
        # [group, started_monotonic, abandoned]; abandoned entries were
        # failed by the watchdog — their (wedged) worker must neither
        # finish the requests again nor retire the running count twice
        self._inflight: dict = {}
        self._inflight_seq = 0
        # service threads: the worker loop attaches each rider's captured
        # context per launch itself (see _execute) — inheriting the
        # CONSTRUCTING thread's context would pin it forever
        self._workers = [
            spawn_thread(
                self._worker, name=f"sched-worker-{i}", context=False
            )
            for i in range(max(1, self.config.max_inflight))
        ]
        for w in self._workers:
            w.start()
        self._watchdog = spawn_thread(
            self._watchdog_loop, name="sched-watchdog", context=False
        )
        self._watchdog.start()

    # -- submission --------------------------------------------------------

    def submit(
        self,
        fn=None,
        fuse=None,
        lane: str = LANE_INTERACTIVE,
        tenant: str = "",
        deadline_ms=_USE_DEFAULT,
        device=None,
    ) -> _Request:
        """Admit one request (non-blocking). ``fn`` is the zero-arg
        serial execution; ``fuse`` an optional FusableQuery the
        micro-batcher may fold into a shared launch (``fn`` defaults to
        its serial form). ``deadline_ms`` unset applies the config
        default; an explicit None means no deadline (bulk producers).
        ``device`` marks the work a device launch — the stuck-launch
        watchdog only arms for device groups (a long host/store scan is
        slow, not stuck, and must not charge the device breaker); unset,
        it is inferred from ``fuse`` (fused queries are launches by
        construction). Raises :class:`RejectedError` when the queue is
        full. Wait for the result with :meth:`wait`."""
        if device is None:
            device = fuse is not None
        if fuse is not None and not fuse.fusable:
            if fn is None:
                fn = fuse.run_serial
            fuse = None
        if fn is None:
            if fuse is None:
                raise ValueError("submit needs fn or fuse")
            fn = fuse.run_serial
        if lane not in _LANES:
            raise ValueError(f"unknown lane {lane!r}")
        if deadline_ms is _USE_DEFAULT:
            deadline_ms = self.config.default_deadline_ms
        deadline = (
            time.monotonic() + deadline_ms / 1e3
            if deadline_ms is not None
            else None
        )
        req = _Request(
            fn, fuse, lane, str(tenant or ""), deadline, device=bool(device)
        )
        from geomesa_tpu import metrics

        with self._cv:
            if self._stop:
                raise RuntimeError("scheduler is shut down")
            if self._queued >= self.config.max_queue:
                self.rejected += 1
                metrics.sched_rejected.inc()
                raise RejectedError(self._retry_after_locked())
            self._queues[req.lane].setdefault(
                req.tenant, deque()
            ).append(req)
            self._queued += 1
            metrics.sched_queue_depth.set(self._queued)
            # notify_all: a single notify can land on a worker holding a
            # fusion window (which re-waits on this cv) while an idle
            # worker sleeps its poll out — a needless latency spike
            self._cv.notify_all()
        return req

    def wait(self, req: _Request):
        """Block until ``req`` completes; raises its error (including
        :class:`DeadlineExpired` when it expired waiting). A request
        already executing at its deadline runs to completion — device
        launches are not cancellable mid-flight."""
        if req.deadline is not None and not req.event.wait(
            timeout=max(req.deadline - time.monotonic(), 0.0)
        ):
            with self._cv:
                if req.state == "queued":  # expired without being claimed
                    from geomesa_tpu import metrics

                    req.state = "done"
                    req.error = DeadlineExpired(
                        "request expired in the scheduler queue"
                    )
                    self._queued -= 1
                    metrics.sched_queue_depth.set(self._queued)
                    self.expired += 1
                    self._observe_expired()
                    req.event.set()
                    self._cv.notify_all()  # close() waits on drain
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def run(
        self,
        fn=None,
        fuse=None,
        lane: str = LANE_INTERACTIVE,
        tenant: str = "",
        deadline_ms=_USE_DEFAULT,
        device=None,
    ):
        """submit() + wait() in one call — the serving entry point."""
        return self.wait(
            self.submit(
                fn=fn, fuse=fuse, lane=lane, tenant=tenant,
                deadline_ms=deadline_ms, device=device,
            )
        )

    def _retry_after_locked(self) -> float:
        """Retry-After for a 429, from ACTUAL queue pressure: estimated
        drain time of the current queue (depth x EWMA service time /
        workers), jittered 0.75-1.25x so synchronized clients that all
        got shed together do not all come back together. Falls back to
        the static ``sched.retry.after.s`` before any request has been
        measured; clamped to [0.05s, 30s]."""
        base = self.config.retry_after_s
        svc = self._svc_ewma
        if svc is not None and svc > 0:
            est = self._queued * svc / max(self.config.max_inflight, 1)
            est = max(est, base * 0.25)  # never promise a near-0 comeback
        else:
            est = base
        est *= 0.75 + 0.5 * _retry_rng.random()
        return min(max(est, 0.05), 30.0)

    def queue_pressure(self) -> "tuple[int, int]":
        """(queued, max_queue) — what the brownout ladder consults."""
        with self._cv:
            return (self._queued, self.config.max_queue)

    # -- queue internals (call under self._cv) -----------------------------

    def _pop_locked(self) -> "_Request | None":
        """Next request: interactive lane first, round-robin across
        tenants within a lane. Claims the request (state -> running)."""
        from geomesa_tpu import metrics

        for lane in _LANES:
            tenants = self._queues[lane]
            for tenant in list(tenants):
                dq = tenants[tenant]
                req = None
                while dq:
                    r = dq.popleft()
                    if r.state == "queued":
                        req = r
                        break
                    # cancelled while queued: already accounted for
                if dq:
                    tenants.move_to_end(tenant)  # fairness rotation
                else:
                    del tenants[tenant]
                if req is not None:
                    req.state = "running"
                    self._queued -= 1
                    self._running += 1
                    metrics.sched_queue_depth.set(self._queued)
                    return req
        return None

    def _drain_locked(self, key, limit: int) -> "list[_Request]":
        """Claim up to ``limit`` queued requests whose fuse key matches
        (any lane, any tenant — a shared launch helps everyone in it)."""
        from geomesa_tpu import metrics

        got: list = []
        if limit <= 0:
            return got
        for lane in _LANES:
            tenants = self._queues[lane]
            for tenant in list(tenants):
                dq = tenants[tenant]
                keep: deque = deque()
                while dq:
                    r = dq.popleft()
                    if (
                        len(got) < limit
                        and r.state == "queued"
                        and r.fuse is not None
                        and r.fuse.key == key
                    ):
                        r.state = "running"
                        got.append(r)
                    elif r.state == "queued":
                        keep.append(r)
                if keep:
                    tenants[tenant] = keep
                else:
                    del tenants[tenant]
        if got:
            self._queued -= len(got)
            self._running += len(got)
            metrics.sched_queue_depth.set(self._queued)
        return got

    # -- execution ---------------------------------------------------------

    def _worker(self) -> None:
        cfg = self.config
        while True:
            with self._cv:
                req = self._pop_locked()
                while req is None and not self._stop:
                    self._cv.wait(timeout=0.25)
                    req = self._pop_locked()
                if req is None:
                    return  # shut down
                group = [req]
                if req.fuse is not None:
                    group += self._drain_locked(
                        req.fuse.key, cfg.max_fusion - len(group)
                    )
            if (
                req.fuse is not None
                and cfg.fusion_window_ms > 0
                and len(group) < cfg.max_fusion
            ):
                # hold the fusion window for compatible late arrivals
                stop_at = time.monotonic() + cfg.fusion_window_ms / 1e3
                while len(group) < cfg.max_fusion:
                    rem = stop_at - time.monotonic()
                    if rem <= 0:
                        break
                    with self._cv:
                        more = self._drain_locked(
                            req.fuse.key, cfg.max_fusion - len(group)
                        )
                        if not more:
                            self._cv.wait(timeout=rem)
                            more = self._drain_locked(
                                req.fuse.key, cfg.max_fusion - len(group)
                            )
                        group += more
            token = self._track_start(group)
            try:
                from geomesa_tpu.failpoints import fail_point

                fail_point("fail.sched.worker")
                self._execute(group)
            except Exception as e:
                # worker-level crash (a bug outside the per-request
                # try, or the fail.sched.worker injection): the group
                # must neither hang nor vanish — fail every unfinished
                # request typed, count it, and KEEP this worker serving
                from geomesa_tpu import metrics

                with self._cv:
                    self.worker_failures += 1
                metrics.sched_worker_failures.inc()
                for r in group:
                    self._finish(r, error=e)
            finally:
                # the whole group was claimed (queued -> running) above;
                # retire it and wake close(), which drains on this count
                # — unless the watchdog already abandoned this worker
                # (it retired the count and failed the requests); then
                # a replacement is serving and this thread exits
                if self._track_end(token, group):
                    return

    def _track_start(self, group) -> int:
        with self._cv:
            self._inflight_seq += 1
            token = self._inflight_seq
            # [group, last-progress time, done-rider count]: the
            # watchdog restarts the stall clock whenever another rider
            # completes, so it measures the CURRENT launch's stall, not
            # the group's cumulative wall-clock (a serially executed
            # fusion-declined group is slow, not stuck)
            self._inflight[token] = [group, time.monotonic(), 0]
        return token

    def _track_end(self, token: int, group) -> bool:
        """Retire a tracked group; True when the watchdog abandoned it
        — it popped the entry when it failed the group, so a missing
        entry tells the wedged thread to exit instead of
        double-retiring."""
        with self._cv:
            entry = self._inflight.pop(token, None)
            abandoned = entry is None
            if not abandoned:
                self._running -= len(group)
            self._cv.notify_all()
        return abandoned

    def _launch_timeout_s(self) -> float:
        from geomesa_tpu import resilience
        from geomesa_tpu.conf import sys_prop

        if not resilience.enabled():
            return 0.0
        return float(sys_prop("resilience.launch.timeout.s"))

    def _watchdog_loop(self) -> None:
        """Fail DEVICE groups whose CURRENT launch is stuck past the
        launch-timeout budget and replace their (wedged, uncancellable)
        workers, so a hung device launch costs one abandoned thread
        instead of a scheduler lane. The stall clock restarts whenever
        a rider of the group completes — a fusion-declined group run
        serially makes progress launch by launch and is slow, not
        stuck. Host/store groups are exempt: a legitimately long scan
        (a large export) would be falsely failed by any launch-scale
        timeout and would charge the DEVICE breaker for work that never
        touched the device — a genuinely wedged host scan instead costs
        its worker, the pre-watchdog status quo. Runs until shutdown."""
        from geomesa_tpu import metrics, resilience

        while True:
            stuck: list = []
            with self._cv:
                if self._stop:
                    return
                timeout = self._launch_timeout_s()
                if timeout > 0:
                    now = time.monotonic()
                    for token, entry in list(self._inflight.items()):
                        group, started, done0 = entry
                        done = sum(
                            1 for r in group if r.state == "done"
                        )
                        if done != done0:  # progress: restart the clock
                            entry[2] = done
                            entry[1] = started = now
                        if (
                            now - started > timeout
                            and any(r.device for r in group)
                        ):
                            # pop NOW: the wedged worker may never
                            # return to retire the entry via _track_end,
                            # and a leaked entry would pin the group's
                            # closures/results for the process lifetime
                            del self._inflight[token]
                            self._running -= len(group)
                            self.watchdog_timeouts += 1
                            stuck.append(group)
                    if stuck:
                        self._cv.notify_all()  # close() drains on running
                self._cv.wait(timeout=0.25)
            for group in stuck:
                metrics.resilience_watchdog_timeouts.inc()
                resilience.device_breaker().record_failure()
                for r in group:
                    self._finish(r, error=resilience.LaunchStuckError(
                        "device launch exceeded "
                        f"resilience.launch.timeout.s ({timeout:g}s); "
                        "worker abandoned and replaced"
                    ))
            if stuck:
                replacements = [
                    spawn_thread(
                        self._worker, name="sched-worker-replacement",
                        context=False,
                    )
                    for _ in stuck
                ]
                with self._cv:
                    # prune dead threads while adding replacements: the
                    # list must not grow without bound over a long-lived
                    # server's lifetime of watchdog interventions
                    self._workers = [
                        w for w in self._workers if w.is_alive()
                    ] + replacements
                for w in replacements:
                    w.start()

    def _observe_service_locked(self, dur_s: float, n: int) -> None:
        """Fold one execution's per-request service time into the EWMA
        the adaptive Retry-After estimate drains the queue with."""
        if n <= 0 or dur_s < 0:
            return
        per = dur_s / n
        self._svc_ewma = (
            per
            if self._svc_ewma is None
            else 0.8 * self._svc_ewma + 0.2 * per
        )

    def _execute(self, group: "list[_Request]") -> None:
        from geomesa_tpu import ledger, metrics, resilience, tracing
        from geomesa_tpu.sched.fusion import execute_group

        now = time.monotonic()
        now_perf = time.perf_counter()
        live: list = []
        dead: list = []
        with self._cv:  # counters race sibling workers otherwise
            for r in group:
                if r.deadline is not None and now > r.deadline:
                    self.expired += 1
                    dead.append(r)
                else:
                    self._wait_sum += now - r.enqueued
                    live.append(r)
        for r in dead:
            self._observe_expired()
            self._finish(r, error=DeadlineExpired(
                "request expired before execution"
            ))
        for r in live:
            metrics.sched_wait_seconds.observe(now - r.enqueued)
            # queue wait (admission -> claimed, incl. the fusion window),
            # timed here and attached retroactively to the rider's trace
            tracing.record_span(
                r.ctx, "sched.wait", r.t0_perf, now_perf - r.t0_perf,
                lane=r.lane, tenant=r.tenant,
            )
        if not live:
            return
        fused = None
        if len(live) > 1 and live[0].fuse is not None:
            try:
                # detail spans from inside the shared launch can only
                # belong to one trace: the head rider's. Every rider
                # still gets the flat sched.execute span below, tagged
                # with the shared launch id.
                with tracing.attach(live[0].ctx), \
                        resilience.attach_degraded(live[0].degraded), \
                        ledger.attach_cost(live[0].cost):
                    fused = execute_group([r.fuse for r in live])
            except Exception:  # lint: disable=GT011(fusion is an optimization: any failure falls back to the serial path, which classifies per-request)
                fused = None  # any fusion failure: serial is always exact
        with self._cv:
            if fused is not None:
                self._launch_seq += 1
                launch_id = self._launch_seq
                self.launches += 1
                self.queries += len(live)
                self.fused_queries += len(live)
            else:
                self.launches += len(live)
                self.queries += len(live)
        if fused is not None:
            metrics.sched_launches.inc()
            metrics.sched_queries.inc(len(live))
            metrics.sched_fused.inc(len(live))
            dur = time.perf_counter() - now_perf
            with self._cv:
                self._observe_service_locked(dur, len(live))
            shards = live[0].fuse.mesh_shards
            for r, v in zip(live, fused):
                tracing.record_span(
                    r.ctx, "sched.execute", now_perf, dur,
                    launch=launch_id, fused=len(live), lane=r.lane,
                    shards=shards,
                )
                if r.cost is not None:
                    # fair-share cost split: summing the ledger over
                    # the riders reproduces the launch's actual device
                    # time instead of multiplying it by the width
                    r.cost.charge("device_launches", 1)
                    r.cost.charge("device_seconds", dur / len(live))
                    r.cost.charge("fusion_width", len(live))
                self._finish(r, result=v)
            return
        metrics.sched_launches.inc(len(live))
        metrics.sched_queries.inc(len(live))
        for r in live:
            with self._cv:
                self._launch_seq += 1
                launch_id = self._launch_seq
            t_run = time.perf_counter()
            try:
                # attach the rider's context so the work's own spans
                # (plan / device.launch / store reads) nest in its
                # trace, its degradation collector so degraded notes
                # reach its response/audit stamping, and its cost
                # collector so device/compile time is charged to it
                with tracing.attach(r.ctx), \
                        resilience.attach_degraded(r.degraded), \
                        ledger.attach_cost(r.cost), \
                        tracing.span(
                            "sched.execute", launch=launch_id, fused=1,
                            lane=r.lane,
                        ):
                    res = r.fn()
            except Exception as e:  # the submitter re-raises it
                dur_run = time.perf_counter() - t_run
                self._charge_serial(r, dur_run)
                with self._cv:
                    self._observe_service_locked(dur_run, 1)
                self._finish(r, error=e)
                continue
            dur_run = time.perf_counter() - t_run
            self._charge_serial(r, dur_run)
            with self._cv:
                self._observe_service_locked(dur_run, 1)
            self._finish(r, result=res)

    @staticmethod
    def _charge_serial(r: _Request, dur_s: float) -> None:
        """Ledger one serially-executed request: device work charges a
        launch; host/store work (device=False) charges nothing here —
        its read/decode/stage time is charged at the store layer."""
        if r.cost is None or not r.device:
            return
        r.cost.charge("device_launches", 1)
        r.cost.charge("device_seconds", dur_s)
        r.cost.charge("fusion_width", 1)

    def _finish(self, req: _Request, result=None, error=None) -> None:
        """Complete a request EXACTLY ONCE: between normal execution,
        the worker crash handler, the watchdog and queue-expiry, the
        first completion wins and every later one is a no-op — a
        submitter can never observe two results (or a result mutating
        under it after the event fired)."""
        with self._cv:
            if req.state == "done":
                return
            req.result = result
            req.error = error
            req.state = "done"
        req.event.set()

    def _observe_expired(self) -> None:
        from geomesa_tpu import metrics

        metrics.sched_expired.inc()

    # -- observability / lifecycle -----------------------------------------

    def snapshot(self) -> dict:
        """The ``/stats/sched`` document: queue pressure, execution
        counters and the fusion factor (queries per device launch)."""
        with self._cv:
            queries, launches = self.queries, self.launches
            return {
                "queue_depth": self._queued,
                "running": self._running,
                "max_queue": self.config.max_queue,
                "inflight_cap": self.config.max_inflight,
                "fusion_window_ms": self.config.fusion_window_ms,
                "max_fusion": self.config.max_fusion,
                "queries": queries,
                "launches": launches,
                "fused_queries": self.fused_queries,
                "fusion_factor": (
                    round(queries / launches, 3) if launches else None
                ),
                "rejected": self.rejected,
                "expired": self.expired,
                "worker_failures": self.worker_failures,
                "watchdog_timeouts": self.watchdog_timeouts,
                "retry_after_estimate_s": round(
                    self._retry_after_locked(), 4
                ),
                "avg_wait_ms": (
                    round(self._wait_sum / queries * 1e3, 3)
                    if queries
                    else None
                ),
            }

    def close(self, timeout: float = 5.0) -> None:
        """Drain-then-stop: wait (bounded, monotonic) for every queued
        AND in-flight request to finish, then stop and JOIN the workers.
        The graceful sibling of :meth:`shutdown` -- a CLI or test
        process must not exit mid-device-launch with work half-executed;
        ``make_server``'s shutdown calls this. Idempotent; requests
        still unfinished at the timeout are failed by the shutdown."""
        deadline = time.monotonic() + timeout
        drained = False
        with self._cv:
            while (self._queued or self._running) and not self._stop:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                self._cv.wait(timeout=min(rem, 0.25))
            drained = not (self._queued or self._running)
        if drained:
            from geomesa_tpu import metrics

            metrics.sched_drains.inc()
        self.shutdown(timeout=max(deadline - time.monotonic(), 0.1))

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop the workers; queued requests complete with errors."""
        with self._cv:
            self._stop = True
            pending: list = []
            for lane in _LANES:
                for dq in self._queues[lane].values():
                    pending += [r for r in dq if r.state == "queued"]
                self._queues[lane].clear()
            self._queued = 0
            self._cv.notify_all()
        for r in pending:
            self._finish(
                r, error=RuntimeError("scheduler shut down")
            )
        # one SHARED deadline for all joins: a watchdog-abandoned
        # (wedged) worker never exits, and paying the full timeout per
        # wedged thread would stretch shutdown by N x timeout
        join_deadline = time.monotonic() + timeout
        with self._cv:
            workers = list(self._workers)
        for w in workers:
            w.join(timeout=max(join_deadline - time.monotonic(), 0.0))
        self._watchdog.join(
            timeout=max(join_deadline - time.monotonic(), 0.1)
        )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
