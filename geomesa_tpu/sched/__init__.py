"""Device query scheduler: admission control, micro-batch scan fusion,
and backpressure for the serving path.

Ref role: the tablet server's scan-executor pool (the reference bounds
concurrent scans per server and queues the rest) — re-designed for
batch-oriented hardware, where N compatible small queries are cheaper as
ONE stacked device launch than as N independent ones. See
:mod:`geomesa_tpu.sched.scheduler` for the architecture.
"""

from geomesa_tpu.sched.fusion import FusableQuery, execute_group
from geomesa_tpu.sched.scheduler import (
    LANE_BATCH,
    LANE_INGEST,
    LANE_INTERACTIVE,
    DeadlineExpired,
    QueryScheduler,
    RejectedError,
    SchedConfig,
)

__all__ = [
    "DeadlineExpired",
    "FusableQuery",
    "LANE_BATCH",
    "LANE_INGEST",
    "LANE_INTERACTIVE",
    "QueryScheduler",
    "RejectedError",
    "SchedConfig",
    "execute_group",
]
