"""Named fault-injection points for crash-consistency testing.

Ref role: the failpoint harnesses durable stores grow once crash
consistency becomes a contract (Accumulo's fate-sharing kill tests;
LevelDB/RocksDB ``SyncPoint``/fault-injection env [UNVERIFIED - empty
reference mount]). A failpoint is a named hook compiled into the hot
path as a cheap dictionary probe; armed, it kills the process, raises,
or raises-N-times-then-passes, letting the chaos suite SIGKILL a
flushing subprocess at every interesting instant and letting unit tests
inject transient read errors without touching the filesystem.

Points honored by the store layer (fs.py / prefetch.py):

- ``fail.flush.after_write``    -- new-generation partition files written
                                   (+checksummed), nothing published
- ``fail.flush.before_publish`` -- manifest about to atomically publish
- ``fail.flush.after_publish``  -- manifest published, old generation
                                   not yet garbage-collected
- ``fail.read.io``              -- partition file about to be read
                                   (transient: the prefetch retry path)
- ``fail.read.corrupt``         -- partition read reports a checksum
                                   mismatch (exercises quarantine)

Serving-path points (sched / query runner / device cache — the chaos
suite's fault-tolerant-serving legs, ISSUE 7):

- ``fail.device.launch``        -- a device scan launch is about to
                                   dispatch (resident + store paths);
                                   ``raise`` simulates a launch failure
                                   the degradation ladder must absorb
- ``fail.stage.oom``            -- column staging for a device scan run;
                                   a raise here is treated as HBM OOM by
                                   the batch-halving recovery
- ``fail.sched.worker``         -- a scheduler worker about to execute a
                                   claimed group; ``raise`` simulates a
                                   worker crash (requests must fail
                                   typed, never hang or vanish)
- ``fail.read.slow``            -- evaluated next to ``fail.read.io``;
                                   arm with ``sleep:<ms>`` to inject
                                   slow-disk latency without errors

Streaming-ingest points (store/wal.py + store/stream.py — the live
layer's crash kill matrix, ISSUE 10):

- ``fail.wal.append``           -- a WAL record is about to be written
                                   (before any byte lands); ``kill``
                                   here loses exactly the un-acked
                                   record, never an acked one
- ``fail.wal.rotate``           -- a full WAL segment is about to seal
                                   and a new one open
- ``fail.wal.replay``           -- WAL replay at store open is about to
                                   scan a segment (recovery must be
                                   idempotent under a crash mid-replay)
- ``fail.compact.publish``      -- the background compactor published a
                                   new generation but has not yet
                                   truncated the consumed WAL segments
                                   (replay must skip them via the
                                   manifest watermark, not re-apply)

Replication points (replica.py — the failover kill matrix, ISSUE 14):

- ``fail.replica.apply``        -- a follower is about to apply one
                                   shipped WAL record (after checksum
                                   verification, before the local
                                   append_at); ``kill`` here must lose
                                   nothing — the leader still holds the
                                   record and the next tail re-ships it
- ``fail.replica.promote``      -- a follower won its election and is
                                   about to adopt the leader role;
                                   promotion must survive (or another
                                   replica must take over from) a fault
                                   injected here

Snapshot-plane points (store/snapshot.py — the self-healing replica
matrix, ISSUE 15):

- ``fail.snapshot.stream``      -- a pinned snapshot stream is about to
                                   ship its next file record; ``raise``
                                   truncates the stream mid-transfer
                                   (the client must resume or restart,
                                   and the orphaned pin must age out
                                   under ``snapshot.pin.ttl.s``)
- ``fail.snapshot.install``     -- a downloaded snapshot is about to
                                   swap into the live tree; a fault
                                   here must leave the previous
                                   generation published and intact
- ``fail.sub.match``            -- the fused batch×subscriptions match
                                   is about to run for an acked append;
                                   a fault here must never un-ack the
                                   rows (matching is post-ack — the
                                   cursor replay path re-derives the
                                   missed alerts)
- ``fail.sub.deliver``          -- a matched alert event is about to be
                                   written to a push stream; a fault
                                   tears down that one connection and
                                   the client resumes from its cursor

Activation: programmatic (``set_failpoint``/``failpoint_override``) or
the ``GEOMESA_TPU_FAILPOINTS`` environment variable, a comma-separated
``name=action`` list — the env form is how a chaos test arms a point in
a subprocess it is about to kill. Actions:

- ``kill``     -- SIGKILL this process (the crash simulator)
- ``exit[:N]`` -- ``os._exit(N)`` (default 1)
- ``raise``    -- raise :class:`FailpointError` every evaluation
- ``raise:N``  -- raise for the first N evaluations, then pass
                  (transient-error injection for retry paths)
- ``sleep:MS`` -- sleep MS milliseconds, then pass (latency injection —
                  slow disks, slow launches — without any error)
- ``off``      -- disarmed (same as absent)
"""

from __future__ import annotations

import os
import signal
from contextlib import contextmanager

from geomesa_tpu.locking import checked_lock

__all__ = [
    "FailpointError",
    "POINTS",
    "clear_failpoint",
    "fail_hit",
    "fail_point",
    "failpoint_override",
    "set_failpoint",
]

ENV_VAR = "GEOMESA_TPU_FAILPOINTS"

#: the named points the store layer evaluates (documentation/validation
#: aid -- arbitrary names are accepted so subsystems can add their own)
POINTS = (
    "fail.flush.after_write",
    "fail.flush.before_publish",
    "fail.flush.after_publish",
    "fail.read.io",
    "fail.read.corrupt",
    "fail.read.slow",
    "fail.device.launch",
    "fail.stage.oom",
    "fail.sched.worker",
    "fail.wal.append",
    "fail.wal.rotate",
    "fail.wal.replay",
    "fail.compact.publish",
    "fail.replica.apply",
    "fail.replica.promote",
    "fail.snapshot.stream",
    "fail.snapshot.install",
    "fail.sub.match",
    "fail.sub.deliver",
)


class FailpointError(OSError):
    """Raised by a ``raise`` action. An OSError so injected transient
    read failures ride the same retry handler as real I/O errors.
    ``name`` records WHICH failpoint fired — handlers that give one
    site's injection special semantics (e.g. ``fail.stage.oom`` as a
    simulated OOM) must match on it, not on whichever failpoint happens
    to be armed."""

    def __init__(self, msg: str, name: "str | None" = None):
        super().__init__(msg)
        self.name = name


_lock = checked_lock("failpoints")
_overrides: "dict[str, str]" = {}
_counts: "dict[str, int]" = {}
# (raw env string, parsed) -- re-parsed only when the env value changes,
# so per-evaluation cost with no failpoints armed is two dict probes
_env_cache: "tuple[str | None, dict]" = (None, {})


def _parse(spec: str) -> dict:
    out: dict = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        name, _, action = pair.partition("=")
        out[name.strip()] = (action or "raise").strip()
    return out


def _env_actions() -> dict:
    global _env_cache
    raw = os.environ.get(ENV_VAR)
    if raw == _env_cache[0]:
        return _env_cache[1]
    parsed = _parse(raw) if raw else {}
    _env_cache = (raw, parsed)
    return parsed


def action_for(name: str) -> "str | None":
    """The armed action for ``name`` (programmatic override wins over
    the environment), or None when disarmed."""
    if name in _overrides:
        return _overrides[name]
    return _env_actions().get(name)


def set_failpoint(name: str, action: str) -> None:
    with _lock:
        _overrides[name] = action
        _counts.pop(name, None)  # fresh raise:N budget


def clear_failpoint(name: str) -> None:
    with _lock:
        _overrides.pop(name, None)
        _counts.pop(name, None)


@contextmanager
def failpoint_override(name: str, action: str):
    """Arm ``name`` for the with-body, restoring the previous state."""
    prev = _overrides.get(name)
    set_failpoint(name, action)
    try:
        yield
    finally:
        if prev is None:
            clear_failpoint(name)
        else:
            set_failpoint(name, prev)


def fail_hit(name: str) -> bool:
    """Evaluate a failpoint, RETURNING True instead of raising for
    ``raise`` actions — for sites that inject their own domain failure
    (e.g. a simulated checksum mismatch). ``kill``/``exit`` still
    terminate the process."""
    action = action_for(name)
    if not action or action == "off":
        return False
    base, _, arg = action.partition(":")
    if base == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if base == "exit":
        os._exit(int(arg or 1))
    if base == "raise":
        if arg:  # raise:N -- only the first N evaluations fire
            with _lock:
                seen = _counts.get(name, 0)
                if seen >= int(arg):
                    return False
                _counts[name] = seen + 1
        return True
    if base == "sleep":  # latency injection: pause, then pass
        import time

        time.sleep(max(float(arg or 0), 0.0) / 1e3)
        return False
    raise ValueError(f"unknown failpoint action {action!r} for {name!r}")


def fail_point(name: str) -> None:
    """Evaluate a failpoint at a named site; no-op unless armed."""
    if fail_hit(name):
        raise FailpointError(f"failpoint {name} triggered", name=name)
