"""Per-feature visibility security (ref: geomesa-security --
SecurityUtils, AuthorizationsProvider SPI, VisibilityEvaluator parsing
``A&(B|C)`` expressions; honored by Accumulo cell visibility [UNVERIFIED -
empty reference mount]).

Features carry a visibility expression (Accumulo-style boolean label
grammar: ``&`` and, ``|`` or, parentheses, empty = public; tokens may be
quoted). A query with authorizations {A, C} sees a feature labeled
``A&(B|C)`` iff the expression evaluates true under that auth set. The
rebuild stores the label in a reserved ``__vis__`` batch column and masks
result batches host-side after the device scan (visibility is a
row-security decision, not a scan predicate -- small cardinality, cached
parse + memoized per-label verdicts).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

VIS_COLUMN = "__vis__"
VIS_USER_DATA = "geomesa.feature.visibility"  # ref user-data key


class VisibilityParseError(ValueError):
    pass


# -- expression AST ----------------------------------------------------------


@dataclass(frozen=True)
class _Tok:
    value: str

    def evaluate(self, auths: frozenset) -> bool:
        return self.value in auths


@dataclass(frozen=True)
class _And:
    children: tuple

    def evaluate(self, auths: frozenset) -> bool:
        return all(c.evaluate(auths) for c in self.children)


@dataclass(frozen=True)
class _Or:
    children: tuple

    def evaluate(self, auths: frozenset) -> bool:
        return any(c.evaluate(auths) for c in self.children)


_TOKEN_CHARS = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-.:/"
)


def parse_visibility(expr: str):
    """Parse an Accumulo-style visibility expression; None for public."""
    expr = expr.strip()
    if not expr:
        return None
    node, pos = _parse_expr(expr, 0)
    if pos != len(expr):
        raise VisibilityParseError(f"trailing input at {pos}: {expr!r}")
    return node


def _parse_expr(s: str, pos: int):
    """expr := term ((& term)* | (\\| term)*) -- like Accumulo, mixing
    & and | at one level without parens is an error."""
    node, pos = _parse_term(s, pos)
    op = None
    children = [node]
    while pos < len(s) and s[pos] in "&|":
        if op is None:
            op = s[pos]
        elif s[pos] != op:
            raise VisibilityParseError(
                f"mixed & and | need parentheses at {pos}: {s!r}"
            )
        nxt, pos2 = _parse_term(s, pos + 1)
        children.append(nxt)
        pos = pos2
    if op is None:
        return node, pos
    cls = _And if op == "&" else _Or
    return cls(tuple(children)), pos


def _parse_term(s: str, pos: int):
    if pos >= len(s):
        raise VisibilityParseError(f"unexpected end of expression: {s!r}")
    if s[pos] == "(":
        node, pos = _parse_expr(s, pos + 1)
        if pos >= len(s) or s[pos] != ")":
            raise VisibilityParseError(f"unbalanced parens in {s!r}")
        return node, pos + 1
    if s[pos] == '"':
        end = s.find('"', pos + 1)
        if end < 0:
            raise VisibilityParseError(f"unterminated quote in {s!r}")
        return _Tok(s[pos + 1 : end]), end + 1
    end = pos
    while end < len(s) and s[end] in _TOKEN_CHARS:
        end += 1
    if end == pos:
        raise VisibilityParseError(f"unexpected char {s[pos]!r} at {pos}")
    return _Tok(s[pos:end]), end


# -- evaluation --------------------------------------------------------------


class VisibilityEvaluator:
    """Evaluates labels against one auth set, memoizing per distinct label
    (typical datasets reuse a handful of labels across millions of rows)."""

    def __init__(self, auths):
        self.auths = frozenset(str(a) for a in auths)
        self._memo: dict = {}

    def can_see(self, label) -> bool:
        if label is None:
            return True
        label = str(label)
        if label not in self._memo:
            node = parse_visibility(label)
            self._memo[label] = node is None or node.evaluate(self.auths)
        return self._memo[label]

    def mask(self, labels: np.ndarray) -> np.ndarray:
        return np.fromiter(
            (self.can_see(v) for v in labels), dtype=bool, count=len(labels)
        )


class AuthorizationsProvider:
    """Ref AuthorizationsProvider SPI: yields the auths for the current
    caller. The default is a static set; subclass to wire real principals."""

    def __init__(self, auths=()):
        self._auths = tuple(auths)

    def get_authorizations(self) -> tuple:
        return self._auths


def filter_by_visibility(batch, auths) -> "np.ndarray | None":
    """Bool mask of rows visible under auths, or None if the batch carries
    no visibility column (everything visible). ``auths=None`` means *no*
    authorizations -- labeled rows hide (fail closed), same as ``()``."""
    vis = batch.columns.get(VIS_COLUMN)
    if vis is None:
        return None
    return VisibilityEvaluator(auths or ()).mask(vis)
