"""Mergeable streaming sketches.

Vectorized ``observe(values)`` over numpy columns (the write-path
StatUpdater analog); ``merge`` folds partials from distributed ingest;
``to_json``/``from_json`` round-trip for store metadata persistence.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np


def _hash64(values: np.ndarray) -> np.ndarray:
    """Stable 64-bit hashes of arbitrary values (vectorized-ish)."""
    if values.dtype.kind in "iuf":
        # splitmix64 over the bit pattern
        h = values.astype(np.int64).view(np.uint64).copy()
        h ^= h >> np.uint64(30)
        h *= np.uint64(0xBF58476D1CE4E5B9)
        h ^= h >> np.uint64(27)
        h *= np.uint64(0x94D049BB133111EB)
        h ^= h >> np.uint64(31)
        return h
    # strings/objects: vectorized FNV-1a over a fixed-width byte matrix
    # (the per-element blake2b loop made stats updates the fs-flush
    # bottleneck at bench scales). Rows longer than 256 bytes hash their
    # prefix -- fine for sketch-quality hashing.
    s = np.asarray(values, dtype="U")
    b = np.char.encode(s, "utf-8", "replace")
    if b.dtype.itemsize == 0:  # all-empty column
        return np.full(len(b), np.uint64(0xCBF29CE484222325))
    width = min(b.dtype.itemsize, 256)
    mat = np.frombuffer(
        np.ascontiguousarray(b).tobytes(), dtype=np.uint8
    ).reshape(len(b), b.dtype.itemsize)[:, :width]
    h = np.full(len(b), np.uint64(0xCBF29CE484222325))
    prime = np.uint64(0x100000001B3)
    live = np.ones(len(b), dtype=bool)
    for j in range(width):
        c = mat[:, j]
        live = live & (c != 0)  # S-dtype zero-pads; stop at first NUL
        h = np.where(live, (h ^ c.astype(np.uint64)) * prime, h)
    # final avalanche so short strings spread across the register space
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(33)
    return h


def _bit_length(x: np.ndarray) -> np.ndarray:
    """Exact vectorized bit_length for uint64 lanes."""
    x = x.astype(np.uint64).copy()
    bl = np.zeros(x.shape, dtype=np.uint64)
    for s in (32, 16, 8, 4, 2, 1):
        y = x >> np.uint64(s)
        m = y != 0
        bl += np.where(m, np.uint64(s), np.uint64(0))
        x = np.where(m, y, x)
    return bl + (x != 0).astype(np.uint64)


class Stat:
    """Base: observe / merge / value / json."""

    def observe(self, values: np.ndarray) -> None:  # pragma: no cover
        raise NotImplementedError

    def merge(self, other: "Stat") -> "Stat":  # pragma: no cover
        raise NotImplementedError

    def to_json(self) -> dict:  # pragma: no cover
        raise NotImplementedError


@dataclass
class CountStat(Stat):
    count: int = 0

    def observe(self, values):
        self.count += len(values)

    def merge(self, other):
        self.count += other.count
        return self

    @property
    def value(self):
        return self.count

    def to_json(self):
        return {"type": "count", "count": self.count}


@dataclass
class MinMax(Stat):
    attr: str
    min: "float | None" = None
    max: "float | None" = None
    count: int = 0

    def observe(self, values):
        v = np.asarray(values)
        if len(v) == 0:
            return
        self.count += len(v)
        lo, hi = v.min(), v.max()
        lo = lo.item() if hasattr(lo, "item") else lo
        hi = hi.item() if hasattr(hi, "item") else hi
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)

    def merge(self, other):
        if other.min is not None:
            self.observe(np.array([other.min, other.max]))
            self.count += other.count - 2
        return self

    @property
    def bounds(self):
        return (self.min, self.max)

    def selectivity(self, lo, hi) -> float:
        """Fraction of rows expected in [lo, hi] under a uniform-range
        assumption (ref: stat-based attribute costing)."""
        if self.min is None or self.max is None:
            return 1.0
        span = float(self.max) - float(self.min)
        if span <= 0:
            return 1.0 if lo <= self.min <= hi else 0.0
        ov = min(float(hi), float(self.max)) - max(float(lo), float(self.min))
        return max(0.0, min(1.0, ov / span))

    def to_json(self):
        return {
            "type": "minmax",
            "attr": self.attr,
            "min": self.min,
            "max": self.max,
            "count": self.count,
        }


@dataclass
class Cardinality(Stat):
    """HyperLogLog distinct-count (ref Stat.Cardinality backed by HLL++)."""

    attr: str
    p: int = 12  # 2^12 registers -> ~1.6% error
    registers: np.ndarray = None

    def __post_init__(self):
        if self.registers is None:
            self.registers = np.zeros(1 << self.p, dtype=np.uint8)

    def observe(self, values):
        v = np.asarray(values)
        if len(v) == 0:
            return
        h = _hash64(v)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        rest = h << np.uint64(self.p)
        # rank = leading zeros of the (64-p)-bit remainder + 1; exact
        # branchless bit_length (float log2 rounds at power-of-two edges)
        lz = np.uint64(64) - _bit_length(rest)
        rank = np.minimum(lz + np.uint64(1), np.uint64(64 - self.p + 1))
        np.maximum.at(self.registers, idx, rank.astype(np.uint8))

    def merge(self, other):
        np.maximum(self.registers, other.registers, out=self.registers)
        return self

    @property
    def estimate(self) -> float:
        m = float(len(self.registers))
        alpha = 0.7213 / (1 + 1.079 / m)
        inv = np.power(2.0, -self.registers.astype(np.float64))
        e = alpha * m * m / inv.sum()
        zeros = int((self.registers == 0).sum())
        if e <= 2.5 * m and zeros:
            e = m * np.log(m / zeros)  # linear counting for small n
        return float(e)

    def to_json(self):
        import base64

        return {
            "type": "cardinality",
            "attr": self.attr,
            "p": self.p,
            "registers": base64.b64encode(self.registers.tobytes()).decode(),
        }


@dataclass
class TopK(Stat):
    """Space-saving top-k heavy hitters (ref Stat.TopK)."""

    attr: str
    k: int = 10
    counters: dict = field(default_factory=dict)

    def observe(self, values):
        vals, counts = np.unique(np.asarray(values), return_counts=True)
        for v, c in zip(vals.tolist(), counts.tolist()):
            v = str(v)  # canonical str keys: survives the JSON round trip
            if v in self.counters:
                self.counters[v] += c
            elif len(self.counters) < self.k * 4:
                self.counters[v] = c
            else:
                victim = min(self.counters, key=self.counters.get)
                base = self.counters.pop(victim)
                self.counters[v] = base + c

    def merge(self, other):
        for v, c in other.counters.items():
            v = str(v)
            self.counters[v] = self.counters.get(v, 0) + c
        return self

    @property
    def topk(self):
        return sorted(self.counters.items(), key=lambda kv: -kv[1])[: self.k]

    def to_json(self):
        return {
            "type": "topk",
            "attr": self.attr,
            "k": self.k,
            "counters": {str(k): v for k, v in self.topk},
        }


@dataclass
class Frequency(Stat):
    """Count-min sketch (ref Stat.Frequency)."""

    attr: str
    depth: int = 4
    width: int = 1 << 12
    table: np.ndarray = None

    def __post_init__(self):
        if self.table is None:
            self.table = np.zeros((self.depth, self.width), dtype=np.int64)

    def observe(self, values):
        v = np.asarray(values)
        if len(v) == 0:
            return
        h = _hash64(v)
        for d in range(self.depth):
            # derive row hash: xor-fold with row-salt splitmix step
            salt = np.uint64((0x9E3779B97F4A7C15 * (d + 1)) & 0xFFFFFFFFFFFFFFFF)
            hd = h ^ salt
            idx = (hd % np.uint64(self.width)).astype(np.int64)
            np.add.at(self.table[d], idx, 1)

    def count(self, value) -> int:
        h = _hash64(np.array([value]))
        est = []
        for d in range(self.depth):
            salt = np.uint64((0x9E3779B97F4A7C15 * (d + 1)) & 0xFFFFFFFFFFFFFFFF)
            hd = h ^ salt
            est.append(int(self.table[d][int(hd[0] % np.uint64(self.width))]))
        return min(est)

    def merge(self, other):
        self.table += other.table
        return self

    def to_json(self):
        return {
            "type": "frequency",
            "attr": self.attr,
            "depth": self.depth,
            "width": self.width,
            "total": int(self.table[0].sum()),
            "table": self.table.tolist(),
        }


@dataclass
class Histogram(Stat):
    """Fixed-bin histogram over [lo, hi] (ref Stat.Histogram); also the
    device-reduction path (jnp scatter-add) used by density/stats queries."""

    attr: str
    bins: int
    lo: float
    hi: float
    counts: np.ndarray = None

    def __post_init__(self):
        if self.counts is None:
            self.counts = np.zeros(self.bins, dtype=np.int64)

    def bin_of(self, values):
        v = np.asarray(values, dtype=np.float64)
        scale = self.bins / (self.hi - self.lo) if self.hi > self.lo else 0.0
        idx = np.floor((v - self.lo) * scale).astype(np.int64)
        return np.clip(idx, 0, self.bins - 1)

    def observe(self, values):
        v = np.asarray(values)
        if len(v) == 0:
            return
        np.add.at(self.counts, self.bin_of(v), 1)

    def merge(self, other):
        self.counts += other.counts
        return self

    def selectivity(self, lo: float, hi: float) -> float:
        """Estimated fraction of values in [lo, hi] (planner costing), with
        linear interpolation inside the boundary bins."""
        total = int(self.counts.sum())
        if total == 0 or self.hi <= self.lo:
            return 1.0
        width = (self.hi - self.lo) / self.bins
        b0, b1 = int(self.bin_of(lo)), int(self.bin_of(hi))
        if b0 == b1:
            frac = min(hi, self.hi) - max(lo, self.lo)
            return float(self.counts[b0]) * max(frac, 0) / width / total
        acc = float(self.counts[b0 + 1 : b1].sum())
        lo_edge = self.lo + (b0 + 1) * width
        acc += float(self.counts[b0]) * np.clip((lo_edge - lo) / width, 0, 1)
        hi_edge = self.lo + b1 * width
        acc += float(self.counts[b1]) * np.clip((hi - hi_edge) / width, 0, 1)
        return acc / total

    def to_json(self):
        return {
            "type": "histogram",
            "attr": self.attr,
            "bins": self.bins,
            "lo": self.lo,
            "hi": self.hi,
            "counts": self.counts.tolist(),
        }


@dataclass
class Z3HistogramStat(Stat):
    """Coarse spatio-temporal occupancy histogram keyed by (bin, z-prefix)
    (ref Stat.Z3Histogram): drives spatial selectivity estimates."""

    geom_attr: str
    dtg_attr: str
    period: str = "week"
    prefix_bits: int = 12
    counts: dict = field(default_factory=dict)

    def observe_xyt(self, x, y, t_ms):
        from geomesa_tpu.curves import Z3SFC, TimePeriod
        from geomesa_tpu.curves.binnedtime import to_binned_time

        sfc = Z3SFC(TimePeriod.parse(self.period))
        b, off = to_binned_time(np.asarray(t_ms), self.period)
        z = sfc.index(x, y, off)
        self.observe_binned(b, z)

    def observe_binned(self, b, z):
        """Observe pre-encoded (bin, z) keys — the flush path already
        computed them for the sorted-index build; re-encoding 4M rows
        just for the histogram doubled the encode cost."""
        key = (np.asarray(b).astype(np.int64) << np.int64(self.prefix_bits)) | (
            np.asarray(z) >> np.uint64(63 - self.prefix_bits)
        ).astype(np.int64)
        if len(key) == 0:
            return
        # occupancy keys are COARSE (a few bins x 2^prefix_bits cells):
        # when the key span is small, bincount over the shifted range is
        # a single linear pass — np.unique sorts all n keys (~4s at 2^25)
        kmin = int(key.min())
        span = int(key.max()) - kmin + 1
        if span <= max(1 << 24, 4 * len(key)):
            cnts = np.bincount(key - kmin, minlength=span)
            nz = np.nonzero(cnts)[0]
            vals, cnts = nz + kmin, cnts[nz]
        else:  # pathological spread: fall back to sort-based unique
            vals, cnts = np.unique(key, return_counts=True)
        for k, c in zip(vals.tolist(), cnts.tolist()):
            self.counts[k] = self.counts.get(k, 0) + c

    def observe(self, values):  # pragma: no cover - use observe_xyt
        raise TypeError("Z3Histogram observes (x, y, t) triples")

    def merge(self, other):
        for k, c in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + c
        return self

    def estimate(self, envelopes, t_intervals_ms) -> float:
        """Estimated rows intersecting any (envelope, time-interval) pair
        (ref: the stat-based side of StrategyDecider). Each occupancy
        cell's count is prorated by the fraction of its (lon, lat, time)
        box the query covers (uniform-within-cell assumption); disjoint
        query ranges SUM their per-cell coverage (clipped to 1)."""
        from geomesa_tpu.curves.binnedtime import to_binned_time

        if not self.counts or not envelopes or not t_intervals_ms:
            return 0.0
        keys, cnts, bins, (cx0, cy0, ct0), (cw_x, cw_y, cw_t), mx_off, period = (
            self._cells()
        )
        # time fraction is envelope-independent: compute it once
        tf = np.zeros(len(keys), dtype=np.float64)
        for t0, t1 in t_intervals_ms:
            b0, o0 = to_binned_time(np.int64(t0), period)
            b1, o1 = to_binned_time(np.int64(t1), period)
            b0, o0 = int(b0), float(o0)
            b1, o1 = int(b1), float(o1)
            # per-bin offset window: full bins cover [0, mx_off]
            q0 = np.where(bins == b0, o0, 0.0)
            q1 = np.where(bins == b1, o1, mx_off)
            inside = (bins >= b0) & (bins <= b1)
            tf += np.where(inside, self._overlap(ct0, cw_t, q0, q1), 0.0)
        tf = np.clip(tf, 0.0, 1.0)
        sp = self._spatial_fraction(envelopes, cx0, cy0, cw_x, cw_y)
        return float((cnts * sp * tf).sum())

    def _cells(self):
        """Decode occupancy keys -> (keys, counts, bins, cx0, cy0, ct0) cell
        origins at the coarse grid resolution (shared by both estimators)."""
        from geomesa_tpu.curves import TimePeriod
        from geomesa_tpu.curves.binnedtime import max_offset
        from geomesa_tpu.curves.zorder import decode_3d_np

        period = TimePeriod.parse(self.period)
        mx_off = float(max_offset(period))
        bpd = self.prefix_bits // 3
        grid = 1 << bpd
        keys = np.fromiter(self.counts.keys(), dtype=np.int64)
        cnts = np.fromiter(self.counts.values(), dtype=np.float64)
        bins = keys >> np.int64(self.prefix_bits)
        prefix = (keys & np.int64((1 << self.prefix_bits) - 1)).astype(np.uint64)
        ix, iy, it = decode_3d_np(prefix << np.uint64(63 - self.prefix_bits))
        ix = (ix >> np.uint64(21 - bpd)).astype(np.int64)
        iy = (iy >> np.uint64(21 - bpd)).astype(np.int64)
        it = (it >> np.uint64(21 - bpd)).astype(np.int64)
        cw = (360.0 / grid, 180.0 / grid, mx_off / grid)
        origins = (
            -180.0 + ix * cw[0],
            -90.0 + iy * cw[1],
            it * cw[2],
        )
        return keys, cnts, bins, origins, cw, mx_off, period

    @staticmethod
    def _overlap(lo, width, q0, q1):
        return np.clip(
            np.minimum(lo + width, q1) - np.maximum(lo, q0), 0.0, width
        ) / width

    def _spatial_fraction(self, envelopes, cx0, cy0, cw_x, cw_y):
        sp = np.zeros(len(cx0), dtype=np.float64)
        for env, _ in envelopes:
            sp += self._overlap(cx0, cw_x, env.xmin, env.xmax) * self._overlap(
                cy0, cw_y, env.ymin, env.ymax
            )
        return np.clip(sp, 0.0, 1.0)

    def estimate_spatial(self, envelopes) -> float:
        """Estimated rows intersecting any envelope, time-marginalized
        (drives z2/xz2 costing with the same data-aware model as z3)."""
        if not self.counts or not envelopes:
            return 0.0
        _, cnts, _, (cx0, cy0, _), (cw_x, cw_y, _), _, _ = self._cells()
        sp = self._spatial_fraction(envelopes, cx0, cy0, cw_x, cw_y)
        return float((cnts * sp).sum())

    def to_json(self):
        return {
            "type": "z3histogram",
            "geom": self.geom_attr,
            "dtg": self.dtg_attr,
            "period": self.period,
            "prefix_bits": self.prefix_bits,
            "nonzero": len(self.counts),
            "total": sum(self.counts.values()),
            # full occupancy map: needed for the round-trip that feeds
            # reopened stores' stat-based planning. Parallel key/count
            # lists, not a dict -- a 100k-entry dict dominated the whole
            # manifest dump (json encodes dict items one at a time)
            "cell_keys": list(self.counts.keys()),
            "cell_counts": list(self.counts.values()),
        }


# -- JSON codec (store-metadata persistence; completes to_json round-trip) ---


def stat_from_json(d: dict):
    """Inverse of each Stat.to_json (used by store metadata persistence;
    no pickle: manifests are plain JSON an operator may edit)."""
    import base64

    t = d.get("type")
    if t == "count":
        return CountStat(count=int(d["count"]))
    if t == "minmax":
        return MinMax(d["attr"], d.get("min"), d.get("max"), int(d.get("count", 0)))
    if t == "cardinality":
        regs = np.frombuffer(
            base64.b64decode(d["registers"]), dtype=np.uint8
        ).copy()
        return Cardinality(d["attr"], int(d["p"]), regs)
    if t == "topk":
        s = TopK(d["attr"], int(d.get("k", 10)))
        s.counters = {k: int(v) for k, v in d.get("counters", {}).items()}
        return s
    if t == "histogram":
        s = Histogram(d["attr"], int(d["bins"]), float(d["lo"]), float(d["hi"]))
        s.counts = np.asarray(d["counts"], dtype=np.int64)
        return s
    if t == "frequency":
        st = Frequency(d["attr"], int(d.get("depth", 4)), int(d.get("width", 1 << 12)))
        if "table" in d:
            st.table = np.asarray(d["table"], dtype=np.int64)
        return st
    if t == "z3histogram":
        s = Z3HistogramStat(
            d["geom"],
            d["dtg"],
            d.get("period", "week"),
            int(d.get("prefix_bits", 12)),
        )
        if "cell_keys" in d:
            s.counts = dict(
                zip(map(int, d["cell_keys"]), map(int, d["cell_counts"]))
            )
        else:  # manifests written before the parallel-list format
            s.counts = {int(k): int(v) for k, v in d.get("cells", {}).items()}
        return s
    raise ValueError(f"unknown stat json type {t!r}")


def seq_from_json(items: list):
    from geomesa_tpu.stats.dsl import SeqStat

    return SeqStat([stat_from_json(d) for d in items])
