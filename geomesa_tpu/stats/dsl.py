"""The Stat DSL: string specs -> sketch instances.

(ref: geomesa-utils .../stats/Stat.scala tiny parser: 'MinMax("age")',
'Histogram("age",20,0,100)', 'Enumeration(...)', combined with ';'
[UNVERIFIED - empty reference mount]). Supported:

    Count()
    MinMax("attr")
    Cardinality("attr")
    TopK("attr"[,k])
    Frequency("attr")
    Histogram("attr",bins,lo,hi)
    Z3Histogram("geom","dtg"[,"week"])

Multiple stats combine with ';' into a SeqStat.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from geomesa_tpu.stats.sketches import (
    Cardinality,
    CountStat,
    Frequency,
    Histogram,
    MinMax,
    Stat,
    TopK,
    Z3HistogramStat,
)

_CALL = re.compile(r"^\s*(\w+)\s*\((.*)\)\s*$")


@dataclass
class SeqStat(Stat):
    stats: list

    def observe_batch(self, batch) -> None:
        for s in self.stats:
            _observe_on_batch(s, batch)

    def observe(self, values):
        for s in self.stats:
            s.observe(values)

    def merge(self, other: "SeqStat"):
        for a, b in zip(self.stats, other.stats):
            a.merge(b)
        return self

    def to_json(self):
        return [s.to_json() for s in self.stats]


def _args(argstr: str) -> list:
    out = []
    for part in filter(None, (p.strip() for p in argstr.split(","))):
        if part.startswith('"') or part.startswith("'"):
            out.append(part[1:-1])
        elif "." in part or "e" in part.lower():
            out.append(float(part))
        else:
            out.append(int(part))
    return out


def parse_stat(spec: str) -> SeqStat:
    stats: list[Stat] = []
    for piece in filter(None, (p.strip() for p in spec.split(";"))):
        m = _CALL.match(piece)
        if not m:
            raise ValueError(f"bad stat spec {piece!r}")
        name, args = m.group(1).lower(), _args(m.group(2))
        if name == "count":
            stats.append(CountStat())
        elif name == "minmax":
            stats.append(MinMax(args[0]))
        elif name == "cardinality":
            stats.append(Cardinality(args[0]))
        elif name == "topk":
            stats.append(TopK(args[0], *([int(args[1])] if len(args) > 1 else [])))
        elif name == "frequency":
            stats.append(Frequency(args[0]))
        elif name == "histogram":
            stats.append(Histogram(args[0], int(args[1]), float(args[2]), float(args[3])))
        elif name == "z3histogram":
            stats.append(
                Z3HistogramStat(args[0], args[1], args[2] if len(args) > 2 else "week")
            )
        else:
            raise ValueError(f"unknown stat {name!r}")
    return SeqStat(stats)


def _observe_on_batch(stat: Stat, batch) -> None:
    """Feed a FeatureBatch into a sketch, resolving attribute columns."""
    if isinstance(stat, CountStat):
        stat.observe(np.empty(len(batch)))
        return
    if isinstance(stat, Z3HistogramStat):
        x, y = batch.point_coords(stat.geom_attr)
        stat.observe_xyt(x, y, batch.column(stat.dtg_attr))
        return
    attr = getattr(stat, "attr", None)
    if attr is None:  # pragma: no cover
        raise TypeError(f"cannot route batch into {type(stat)}")
    desc = batch.sft.descriptor(attr)
    if desc.is_point:
        x, y = batch.point_coords(attr)
        stat.observe(x)  # convention: point stats observe longitude
    else:
        stat.observe(batch.column(attr))
