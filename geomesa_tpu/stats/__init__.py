"""Streaming stats sketches + the Stat DSL (maps reference stats stack).

(ref: geomesa-utils .../stats/Stat.scala MinMax/TopK/Frequency/Z3Histogram +
geomesa-index-api .../stats/GeoMesaStats [UNVERIFIED - empty reference
mount]). Sketches summarize written data; the planner uses them for
selectivity-based strategy costing and the CLI surfaces them (stats-*
commands). All sketches are mergeable (distributed ingest folds partial
sketches) and serializable to JSON for store metadata.
"""

from geomesa_tpu.stats.sketches import (
    Cardinality,
    CountStat,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3HistogramStat,
)
from geomesa_tpu.stats.dsl import parse_stat, SeqStat

__all__ = [
    "MinMax",
    "CountStat",
    "Cardinality",
    "TopK",
    "Frequency",
    "Histogram",
    "Z3HistogramStat",
    "parse_stat",
    "SeqStat",
]
