"""Chunked columnar partition statistics (partition format v2).

Ref role: the server-side aggregation tier of the reference system
(density heatmaps, stats sketches -- geomesa-accumulo DensityIterator /
StatsIterator [UNVERIFIED - empty reference mount]) rebuilt as WRITE-TIME
pre-aggregation, in the manner of Spatial Parquet's chunked column
layout and Zarr-style chunk-level cumulative sums (PAPERS.md): every
generation-scoped partition file is split into fixed-size row chunks
(``store.chunk.rows``), and the manifest records per-chunk statistics --

- row count (``rows``),
- Z-order key min/max (``key_lo``/``key_hi``; the file is sorted by the
  primary key columns, so a chunk's first/last row IS its lexicographic
  key extremum),
- bbox and time range,
- a sparse per-cell density histogram on a fixed world grid
  (``store.chunk.grid`` cells per dimension over lon/lat),
- stats-sketch partials (:mod:`geomesa_tpu.stats.sketches` MinMax
  records, parseable by ``stat_from_json``),
- the encoded byte size of the chunk's parquet row group (chunks align
  1:1 with row groups, so a pruned read skips real file bytes).

Two consumers:

1. **Aggregation pushdown** (store/pushdown.py): density/count/stats
   queries whose filter is exactly a bbox+time conjunction classify
   chunks as interior (fully covered -- answered from the manifest,
   rows never read), boundary (read + exact row-level refinement) or
   disjoint (skipped).
2. **Scan pruning** (store/oocscan.py): chunk key min/max double as a
   sub-partition pruning index -- the streamed scan drops chunks whose
   key span misses every planned Z range BEFORE read/decode, and
   chunk-selective parquet reads skip the pruned row groups' bytes.

Everything here is advisory-but-verified: the ``fsck`` CLI cross-checks
chunk stats against decoded rows (:meth:`FileSystemDataStore.
verify_chunk_stats`) and drift fails the check loudly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

#: manifest format versions (``"format"`` manifest key; absent = v1)
FORMAT_V1 = 1
FORMAT_V2 = 2

#: world extents the coarse density grid quantizes (lon/lat degrees)
WORLD = (-180.0, -90.0, 180.0, 90.0)

#: chunk classification against aggregate bounds
DISJOINT, BOUNDARY, INTERIOR = 0, 1, 2


@dataclass
class ChunkSet:
    """Per-chunk statistics for ONE partition file (parallel arrays,
    one entry per chunk; chunk row offsets are partition-relative)."""

    starts: np.ndarray  # (m,) int64, starts[0] == 0
    stops: np.ndarray  # (m,) int64, stops[-1] == partition row count
    key_lo: list  # m key tuples (primary index key columns)
    key_hi: list
    grid: int  # density grid edge (grid x grid world cells)
    cells: list  # m int64 arrays: occupied world-grid cell ids
    cell_counts: list  # m int64 arrays, aligned with ``cells``
    partials: list  # m lists of stat-json dicts (minmax sketches)
    bbox: "np.ndarray | None" = None  # (m, 4) xmin ymin xmax ymax
    time_range: "np.ndarray | None" = None  # (m, 2) ms
    nbytes: "np.ndarray | None" = None  # (m,) encoded row-group bytes
    has_vis: bool = False  # any row carries a visibility label
    chunk_rows: int = 0  # the nominal chunk size this set was built at

    def __len__(self) -> int:
        return len(self.starts)

    @property
    def rows(self) -> np.ndarray:
        return self.stops - self.starts

    @property
    def total_rows(self) -> int:
        return int(self.stops[-1]) if len(self.starts) else 0


def _key_tuple(key_cols, i: int) -> tuple:
    """Key tuple at sorted row ``i`` (numpy scalars -> python for exact
    lexicographic comparison against KeyRange tuples)."""
    out = []
    for c in key_cols:
        v = c[i]
        out.append(v.item() if isinstance(v, np.generic) else v)
    return tuple(out)


def world_cells(x: np.ndarray, y: np.ndarray, grid: int) -> np.ndarray:
    """World-grid cell id (iy * grid + ix) per point. Non-finite
    coordinates clamp deterministically to cell 0's axis (NaN.astype is
    undefined behavior); chunks holding such rows have a non-finite
    bbox, which classify()/density force down the row-refinement path,
    so the polluted cells are never SERVED — they only keep the
    build/fsck recomputation deterministic."""
    ix = np.clip(
        np.nan_to_num(
            (np.asarray(x, dtype=np.float64) - WORLD[0])
            / (WORLD[2] - WORLD[0])
            * grid
        ).astype(np.int64),
        0,
        grid - 1,
    )
    iy = np.clip(
        np.nan_to_num(
            (np.asarray(y, dtype=np.float64) - WORLD[1])
            / (WORLD[3] - WORLD[1])
            * grid
        ).astype(np.int64),
        0,
        grid - 1,
    )
    return iy * grid + ix


def _minmax_attrs(sft) -> list:
    """Attributes that get per-chunk MinMax partials: the same numeric/
    date set ``build_default_stats`` sketches, so chunk partials merge
    into the stats the planner and the stats API already speak."""
    return [
        a.name
        for a in sft.attributes
        if not a.is_geometry
        and a.column_dtype is not None
        and a.column_dtype != np.bool_
    ]


def build_chunk_set(
    keyspace,
    batch,
    keys: dict,
    start: int,
    stop: int,
    chunk_rows: int,
    grid: int,
) -> ChunkSet:
    """Chunk statistics for the ``[start, stop)`` partition slice of a
    SORTED built index (``batch``/``keys`` sorted by the key columns, so
    each chunk's first/last row is its lexicographic key min/max). One
    vectorized ``reduceat`` pass per statistic -- the same discipline as
    ``index.build.make_partitions``, one level finer."""
    sft = batch.sft
    n = stop - start
    starts = np.arange(0, max(n, 1), max(int(chunk_rows), 1), dtype=np.int64)
    starts = starts[starts < max(n, 1)]
    if n == 0:
        starts = np.array([0], dtype=np.int64)
        stops = np.array([0], dtype=np.int64)
    else:
        stops = np.minimum(starts + int(chunk_rows), n)
    key_cols = [keys[c] for c in keyspace.key_columns]
    key_lo = [_key_tuple(key_cols, start + int(s)) for s in starts] if n else [
        ()
    ]
    key_hi = [
        _key_tuple(key_cols, start + int(e) - 1) for e in stops
    ] if n else [()]

    geom = sft.geom_field
    dtg = sft.dtg_field
    abs_starts = starts + start
    bbox = None
    cells: list = [np.array([], dtype=np.int64)] * len(starts)
    cell_counts: list = [np.array([], dtype=np.int64)] * len(starts)
    if geom is not None and n:
        col = batch.columns[geom]
        if col.dtype != object:
            x = np.ascontiguousarray(col[start:stop, 0])
            y = np.ascontiguousarray(col[start:stop, 1])
            xmn, ymn = x, y
            xmx, ymx = x, y
            # density cells only for point schemas: the coarse histogram
            # counts point locations, which is what density() rasterizes
            cell = world_cells(x, y, grid)
            cells, cell_counts = [], []
            for s, e in zip(starts.tolist(), stops.tolist()):
                v, c = np.unique(cell[s:e], return_counts=True)
                cells.append(v.astype(np.int64))
                cell_counts.append(c.astype(np.int64))
        else:
            bb = batch.bboxes(geom)[start:stop]
            xmn, ymn = bb[:, 0], bb[:, 1]
            xmx, ymx = bb[:, 2], bb[:, 3]
        bbox = np.stack(
            [
                np.minimum.reduceat(xmn, starts),
                np.minimum.reduceat(ymn, starts),
                np.maximum.reduceat(xmx, starts),
                np.maximum.reduceat(ymx, starts),
            ],
            axis=1,
        ).astype(np.float64)
    time_range = None
    if dtg is not None and n:
        d = np.asarray(batch.column(dtg))[start:stop]
        time_range = np.stack(
            [np.minimum.reduceat(d, starts), np.maximum.reduceat(d, starts)],
            axis=1,
        ).astype(np.int64)

    partials: list = [[] for _ in starts]
    if n:
        for name in _minmax_attrs(sft):
            col = np.asarray(batch.column(name))[start:stop]
            mns = np.minimum.reduceat(col, starts)
            mxs = np.maximum.reduceat(col, starts)
            for i in range(len(starts)):
                partials[i].append(
                    {
                        "type": "minmax",
                        "attr": name,
                        "min": mns[i].item(),
                        "max": mxs[i].item(),
                        "count": int(stops[i] - starts[i]),
                    }
                )

    has_vis = False
    vis = batch.visibilities
    if vis is not None and n:
        sl = vis[start:stop]
        has_vis = bool(
            np.any(np.array([v is not None and str(v) != "" for v in sl]))
        )
    return ChunkSet(
        starts=starts,
        stops=stops,
        key_lo=key_lo,
        key_hi=key_hi,
        grid=int(grid),
        cells=cells,
        cell_counts=cell_counts,
        partials=partials,
        bbox=bbox,
        time_range=time_range,
        has_vis=has_vis,
        chunk_rows=int(chunk_rows),
    )


# -- manifest JSON round trip ------------------------------------------------


def chunkset_to_json(cs: "ChunkSet | None") -> "dict | None":
    if cs is None:
        return None
    return {
        "grid": cs.grid,
        "chunk_rows": cs.chunk_rows,
        "has_vis": cs.has_vis,
        "rows": cs.rows.tolist(),
        "key_lo": [list(t) for t in cs.key_lo],
        "key_hi": [list(t) for t in cs.key_hi],
        "bbox": cs.bbox.tolist() if cs.bbox is not None else None,
        "time_range": (
            cs.time_range.tolist() if cs.time_range is not None else None
        ),
        "nbytes": cs.nbytes.tolist() if cs.nbytes is not None else None,
        "cells": [c.tolist() for c in cs.cells],
        "cell_counts": [c.tolist() for c in cs.cell_counts],
        "partials": cs.partials,
    }


def chunkset_from_json(d: "dict | None") -> "ChunkSet | None":
    if not d:
        return None
    rows = np.asarray(d["rows"], dtype=np.int64)
    stops = np.cumsum(rows)
    starts = stops - rows
    return ChunkSet(
        starts=starts,
        stops=stops,
        key_lo=[tuple(t) for t in d["key_lo"]],
        key_hi=[tuple(t) for t in d["key_hi"]],
        grid=int(d.get("grid", 0)),
        cells=[np.asarray(c, dtype=np.int64) for c in d.get("cells", [])],
        cell_counts=[
            np.asarray(c, dtype=np.int64) for c in d.get("cell_counts", [])
        ],
        partials=d.get("partials", [[] for _ in rows]),
        bbox=(
            np.asarray(d["bbox"], dtype=np.float64)
            if d.get("bbox") is not None
            else None
        ),
        time_range=(
            np.asarray(d["time_range"], dtype=np.int64)
            if d.get("time_range") is not None
            else None
        ),
        nbytes=(
            np.asarray(d["nbytes"], dtype=np.int64)
            if d.get("nbytes") is not None
            else None
        ),
        has_vis=bool(d.get("has_vis", False)),
        chunk_rows=int(d.get("chunk_rows", 0)),
    )


# -- classification ----------------------------------------------------------


def classify(cs: ChunkSet, envs, ivals) -> np.ndarray:
    """Per-chunk classification against a CONJUNCTION of aggregate
    bounds (``QueryPlan.agg_bounds`` semantics): ``envs`` is a union of
    Envelopes or None (spatially unconstrained), ``ivals`` a union of
    inclusive ``(t0_ms, t1_ms)`` intervals or None. Returns INTERIOR
    (2: every row in the chunk satisfies the bounds -- its bbox sits
    inside a single envelope and its time range inside a single
    interval), DISJOINT (0: provably no row matches) or BOUNDARY (1).
    Chunks without a bbox/time record classify conservatively as
    BOUNDARY on that dimension."""
    m = len(cs)
    inside_g = np.ones(m, dtype=bool)
    meets_g = np.ones(m, dtype=bool)
    if envs is not None:
        if cs.bbox is None:
            inside_g[:] = False  # cannot prove containment
        else:
            b = cs.bbox
            inside_g[:] = False
            meets_g[:] = False
            for e in envs:
                inside_g |= (
                    (b[:, 0] >= e.xmin)
                    & (b[:, 2] <= e.xmax)
                    & (b[:, 1] >= e.ymin)
                    & (b[:, 3] <= e.ymax)
                )
                meets_g |= (
                    (b[:, 0] <= e.xmax)
                    & (b[:, 2] >= e.xmin)
                    & (b[:, 1] <= e.ymax)
                    & (b[:, 3] >= e.ymin)
                )
            # a NaN coordinate anywhere in the chunk poisons its bbox
            # (reduceat propagates NaN) and every NaN comparison above
            # is False — which would classify the chunk DISJOINT and
            # silently drop its VALID rows. Non-finite bboxes are
            # undecidable: always BOUNDARY (row-level refinement)
            bad = ~np.isfinite(b).all(axis=1)
            inside_g[bad] = False
            meets_g[bad] = True
    inside_t = np.ones(m, dtype=bool)
    meets_t = np.ones(m, dtype=bool)
    if ivals is not None:
        if cs.time_range is None:
            inside_t[:] = False
        else:
            t = cs.time_range
            inside_t[:] = False
            meets_t[:] = False
            for t0, t1 in ivals:
                inside_t |= (t[:, 0] >= t0) & (t[:, 1] <= t1)
                meets_t |= (t[:, 0] <= t1) & (t[:, 1] >= t0)
    out = np.full(m, BOUNDARY, dtype=np.int8)
    out[~(meets_g & meets_t)] = DISJOINT
    out[inside_g & inside_t & meets_g & meets_t] = INTERIOR
    return out


def chunks_overlapping(cs: ChunkSet, ranges) -> np.ndarray:
    """Bool mask of chunks whose key span overlaps ANY planned KeyRange
    (the partition-level ``PartitionMeta.overlaps`` test, one level
    finer). Sound the same way partition pruning is: the planner's
    ranges cover every key a filter-matching row can have, so a chunk
    overlapping none contains no matching rows.

    Ranges are sorted by ``lo`` but may nest/overlap, so per chunk we
    bisect to the last range starting at-or-below the chunk's key_hi
    and test the PREFIX MAXIMUM of range highs against key_lo -- exact,
    O((chunks + ranges) log ranges)."""
    from bisect import bisect_right

    m = len(cs)
    if not ranges:
        return np.zeros(m, dtype=bool)
    rs = sorted(ranges, key=lambda r: r.lo)
    los = [r.lo for r in rs]
    max_hi: list = []
    cur = None
    for r in rs:
        cur = r.hi if cur is None or r.hi > cur else cur
        max_hi.append(cur)
    out = np.zeros(m, dtype=bool)
    for i in range(m):
        j = bisect_right(los, cs.key_hi[i])
        if j > 0 and max_hi[j - 1] >= cs.key_lo[i]:
            out[i] = True
    return out


# -- density proration -------------------------------------------------------


def _overlap_matrix(
    grid: int, lo: float, hi: float, q0: float, q1: float, pixels: int
) -> np.ndarray:
    """(grid, pixels) fraction-of-cell matrix along one axis: entry
    ``[c, p]`` is (cell c ∩ pixel p) / cell width."""
    cw = (hi - lo) / grid
    pw = (q1 - q0) / pixels
    c0 = lo + np.arange(grid, dtype=np.float64)[:, None] * cw
    p0 = q0 + np.arange(pixels, dtype=np.float64)[None, :] * pw
    ov = np.minimum(c0 + cw, p0 + pw) - np.maximum(c0, p0)
    return np.clip(ov, 0.0, None) / cw


def prorate_coarse(
    coarse: np.ndarray,
    grid: int,
    env,
    width: int,
    height: int,
) -> np.ndarray:
    """Distribute a (grid, grid) world-cell count matrix onto a query
    raster by area overlap (uniform-within-cell assumption -- the
    chunk-granularity tolerance the pushdown contract documents). A
    cell's mass outside the raster drops proportionally, matching the
    row scan's inside-the-viewport test to within cell granularity."""
    wx = _overlap_matrix(grid, WORLD[0], WORLD[2], env.xmin, env.xmax, width)
    wy = _overlap_matrix(grid, WORLD[1], WORLD[3], env.ymin, env.ymax, height)
    return (wy.T @ coarse @ wx).astype(np.float32)
