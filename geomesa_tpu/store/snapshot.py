"""Consistent store snapshots with GC pins — the bulk-provision plane.

Ref role: GeoMesa production deployments bulk-provision replicas and
take point-in-time backups through the backing store's snapshot/clone
machinery (Accumulo table cloning; the FS store's immutable partition
layout). This module is that plane for the TPU store: a snapshot is the
published manifest of one generation plus that generation's partition
files plus the WAL watermark recorded in the manifest — everything a
fresh node needs to serve the type and resume tailing the leader's WAL
from ``watermark + 1``.

Consistency comes for free from the store's write-new-then-publish
discipline (ISSUE 3): a published generation's files are immutable, so
a snapshot captured under the publish lock names a frozen, checksummed
file set. The only hazard is garbage collection — the very next compact
publishes a NEW generation and sweeps the old one's files out from
under a stream in progress. A **pin** closes that hole: capture writes
a pin file (the snapshot doc itself) under ``<type>/_pins/`` before
releasing the lock, and ``_gc_stale_parts`` unions every live pin's
file set into its keep-set. Pins are leases, not locks: a stream
touches its pin after every shipped file, and a pin untouched for
``snapshot.pin.ttl.s`` (its stream died — SIGKILL mid-ship) is
reclaimed by the next sweep, so a crashed snapshot can delay GC but
never wedge it.

Wire framing (``GET /snapshot/<type>``) follows the WAL ship
discipline: length-prefixed records with a crc-protected header, over
chunked transfer encoding, so truncation is always detectable (the
stream ends without its END record). Per-file integrity rides the PR 3
manifest checksum entries — the receiver verifies every file as it
lands, incrementally, before anything installs. Resume is per-file:
``?id=<snapshot_id>&from_file=K`` re-opens the same pin and skips the
K files already landed.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import time
import uuid
import zlib

__all__ = [
    "KIND_BEGIN",
    "KIND_END",
    "KIND_FILE",
    "SNAPSHOT_CONTENT_TYPE",
    "SnapshotError",
    "SnapshotFormatError",
    "capture",
    "install_files",
    "iter_stream",
    "load_pin",
    "pinned_paths",
    "read_stream",
    "release",
    "stage_path",
    "touch_pin",
]

SNAPSHOT_CONTENT_TYPE = "application/x-geomesa-snapshot"

#: record header: magic, kind, payload length, crc32 of the record's
#: JSON metadata (file BYTES are covered by the manifest checksums the
#: metadata carries — framing integrity here, content integrity there)
_MAGIC = 0x50534D47  # "GMSP" little-endian
_HEADER = struct.Struct("<IIQI")
_LEN = struct.Struct("<I")

KIND_BEGIN = 1  # payload: the snapshot doc (json)
KIND_FILE = 2  # payload: u32 meta_len + meta json + raw file bytes
KIND_END = 3  # payload: totals (json) — its presence proves completeness


class SnapshotError(RuntimeError):
    """A snapshot operation failed (capture, stream, or install)."""


class SnapshotFormatError(SnapshotError):
    """A snapshot stream violated its framing (bad magic/crc/length)."""


def _safe_rel(rel: str) -> str:
    """Reject path traversal in a received file record: rel paths come
    off the wire and are joined under the install dir."""
    if not rel or os.path.isabs(rel):
        raise SnapshotFormatError(f"unsafe snapshot path {rel!r}")
    parts = rel.replace("\\", "/").split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise SnapshotFormatError(f"unsafe snapshot path {rel!r}")
    return os.path.join(*parts)


def _pins_dir(store, type_name: str) -> str:
    return os.path.join(store._dir(type_name), "_pins")


def stage_path(store, type_name: str, snapshot_id: str) -> str:
    """Download staging dir for one incoming snapshot. Lives under the
    type dir (same filesystem: the install swap is an atomic rename)
    but underscore-prefixed, so the GC walk never descends into it;
    stale stages age out with the pins under ``snapshot.pin.ttl.s``."""
    return os.path.join(
        store._dir(type_name), "_snapstage", str(snapshot_id)
    )


# -- capture / pins ----------------------------------------------------------


def capture(store, type_name: str) -> dict:
    """Capture a consistent snapshot of ``type_name`` under the publish
    lock and PIN it: returns the snapshot doc (also persisted as the
    pin file), whose ``files`` list names the manifest plus every
    partition file of the published generation, each with its manifest
    checksum. Until :func:`release` (or the pin's TTL expiry), GC and
    recovery sweeps keep those files on disk even across compactions
    that supersede the generation."""
    from geomesa_tpu import metrics
    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.store.fs import _write_file, checksum_bytes

    with store._exclusive():
        # re-sync first: another process may have published a newer
        # generation; pinning a stale in-memory view would name files
        # a sweep already reclaimed
        store._refresh_from_disk(type_name)
        st = store._types[type_name]
        d = store._dir(type_name)
        with open(os.path.join(d, "schema.json"), "rb") as fh:
            mbytes = fh.read()
        manifest = json.loads(mbytes)
        files = []
        for p in st.partitions:
            path = store._part_path(type_name, p)
            files.append({
                "rel": os.path.relpath(path, d).replace(os.sep, "/"),
                "nbytes": int(os.path.getsize(path)),
                "checksum": p.checksum,
            })
        # the manifest ships LAST: the installer lands data files
        # first and publishes the manifest over them (the store's own
        # write-new-then-publish order)
        algo, value = checksum_bytes(mbytes)
        files.append({
            "rel": "schema.json",
            "nbytes": len(mbytes),
            "checksum": {
                "algo": algo, "value": value, "length": len(mbytes),
            },
        })
        sid = uuid.uuid4().hex[:12]
        doc = {
            "snapshot_id": sid,
            "type": type_name,
            "generation": manifest.get("generation"),
            "file_gen": manifest.get("file_gen"),
            "wal_watermark": int(manifest.get("wal_watermark", -1)),
            "created_unix": time.time(),  # lint: disable=GT003(epoch timestamp persisted into the snapshot doc)
            "files": files,
            "total_bytes": int(sum(f["nbytes"] for f in files)),
        }
        pdir = _pins_dir(store, type_name)
        os.makedirs(pdir, exist_ok=True)
        tmp = os.path.join(pdir, sid + ".pin.tmp")
        _write_file(
            tmp, json.dumps(doc).encode("utf-8"),
            bool(sys_prop("store.fsync")),
        )
        os.replace(tmp, os.path.join(pdir, sid + ".json"))
        store._active_pins.add((type_name, sid))
    metrics.snapshot_captures.inc()
    return doc


def load_pin(store, type_name: str, snapshot_id: str) -> "dict | None":
    """The pin doc for an existing snapshot, or None if released or
    reclaimed (the resuming client must restart with a fresh capture)."""
    path = os.path.join(_pins_dir(store, type_name), snapshot_id + ".json")
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def touch_pin(store, type_name: str, snapshot_id: str) -> None:
    """Refresh a pin's lease (mtime): live streams call this per
    shipped file so only DEAD streams' pins age past the TTL."""
    path = os.path.join(_pins_dir(store, type_name), snapshot_id + ".json")
    try:
        os.utime(path)
    except OSError:
        pass  # reclaimed under us: the stream fails on its next record


def release(store, type_name: str, snapshot_id: str) -> None:
    """Drop a pin: the snapshot's superseded generations become
    reclaimable by the next sweep."""
    store._active_pins.discard((type_name, snapshot_id))
    path = os.path.join(_pins_dir(store, type_name), snapshot_id + ".json")
    try:
        os.unlink(path)
    except OSError:
        pass


def pinned_paths(store, type_name: str) -> "set[str]":
    """Abspaths of every file a live pin protects — the GC keep-set
    (``_gc_stale_parts`` unions this into its manifest ``expected``).
    Doubles as the pin sweeper: pins whose file has not been touched
    for ``snapshot.pin.ttl.s`` (their stream is dead) are reclaimed
    here, as are stale download staging dirs, so orphans from a
    SIGKILLed stream bound GC delay instead of wedging it. In-process
    active pins are exempt from the TTL (a slow-but-live local stream
    must not be torn)."""
    import logging

    from geomesa_tpu.conf import sys_prop

    d = store._dir(type_name)
    pdir = _pins_dir(store, type_name)
    ttl = float(sys_prop("snapshot.pin.ttl.s"))
    now = time.time()  # lint: disable=GT003(ages are measured against file mtimes, which are wall-clock)
    out: "set[str]" = set()
    try:
        names = sorted(os.listdir(pdir))
    except OSError:
        names = []
    for f in names:
        if not f.endswith(".json"):
            continue
        sid = f[: -len(".json")]
        path = os.path.join(pdir, f)
        if (type_name, sid) not in store._active_pins:
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age > ttl:
                from geomesa_tpu import metrics

                try:
                    os.unlink(path)
                except OSError:
                    continue
                metrics.snapshot_pins_reclaimed.inc()
                logging.getLogger(__name__).warning(
                    "dataset %r: reclaimed orphaned snapshot pin %s "
                    "(untouched %.1fs > snapshot.pin.ttl.s=%.1fs)",
                    type_name, sid, age, ttl,
                )
                continue
        doc = load_pin(store, type_name, sid)
        if not doc:
            continue  # unreadable pin: pins nothing, TTL reclaims it
        for rec in doc.get("files", ()):
            try:
                rel = _safe_rel(str(rec.get("rel", "")))
            except SnapshotFormatError:
                continue
            out.add(os.path.abspath(os.path.join(d, rel)))
    # stale download stages (a reprovision that died mid-fetch)
    sdir = os.path.join(d, "_snapstage")
    try:
        stages = sorted(os.listdir(sdir))
    except OSError:
        stages = []
    for s in stages:
        path = os.path.join(sdir, s)
        try:
            if now - os.path.getmtime(path) > ttl:
                shutil.rmtree(path, ignore_errors=True)
        except OSError:
            continue
    return out


# -- wire framing ------------------------------------------------------------


def _json_record(kind: int, doc: dict) -> bytes:
    body = json.dumps(doc).encode("utf-8")
    return _HEADER.pack(
        _MAGIC, kind, len(body), zlib.crc32(body) & 0xFFFFFFFF
    ) + body


def iter_stream(store, type_name: str, doc: dict, from_file: int = 0):
    """Yield the snapshot stream's bytes: BEGIN record (the doc), one
    length-prefixed FILE record per entry in ``doc["files"]`` (skipping
    the first ``from_file`` on a resume), END record. The pin is
    touched after every file so a live stream never ages past the TTL;
    a raise mid-walk (disk error, ``fail.snapshot.stream``) ends the
    generator without the END record — detectable truncation, exactly
    the /wal gap-stop discipline."""
    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.failpoints import fail_point

    chunk = max(int(sys_prop("snapshot.chunk.bytes")), 1)
    d = store._dir(type_name)
    sid = str(doc.get("snapshot_id", ""))
    yield _json_record(KIND_BEGIN, doc)
    sent_files = sent_bytes = 0
    for i, rec in enumerate(doc.get("files", ())):
        if i < int(from_file):
            continue
        fail_point("fail.snapshot.stream")
        meta = dict(rec)
        meta["index"] = i
        mb = json.dumps(meta).encode("utf-8")
        nbytes = int(rec["nbytes"])
        yield _HEADER.pack(
            _MAGIC, KIND_FILE, _LEN.size + len(mb) + nbytes,
            zlib.crc32(mb) & 0xFFFFFFFF,
        ) + _LEN.pack(len(mb)) + mb
        remaining = nbytes
        with open(os.path.join(d, _safe_rel(rec["rel"])), "rb") as fh:
            while remaining:
                b = fh.read(min(chunk, remaining))
                if not b:
                    raise SnapshotError(
                        f"pinned file {rec['rel']!r} shorter on disk "
                        f"than its snapshot record ({nbytes} bytes)"
                    )
                remaining -= len(b)
                yield b
        sent_files += 1
        sent_bytes += nbytes
        touch_pin(store, type_name, sid)
    yield _json_record(
        KIND_END, {"files": sent_files, "bytes": sent_bytes}
    )


class _Verifier:
    """Incremental per-file verification against a manifest checksum
    record (``verify_bytes`` semantics without buffering the file):
    rolling crc32/crc32c plus the always-checked length; unknown algos
    degrade to length-only."""

    def __init__(self, checksum: "dict | None"):
        self._c = checksum or {}
        self._len = 0
        self._crc = 0
        algo = self._c.get("algo")
        if algo == "crc32c":
            from geomesa_tpu.store.fs import _crc32c

            self._fn = _crc32c  # None when the module is absent
        elif algo == "crc32":
            self._fn = lambda b, v: zlib.crc32(b, v) & 0xFFFFFFFF
        else:
            self._fn = None

    def update(self, b: bytes) -> None:
        self._len += len(b)
        if self._fn is not None:
            self._crc = int(self._fn(b, self._crc))

    def error(self) -> "str | None":
        want_len = self._c.get("length")
        if want_len is not None and self._len != int(want_len):
            return f"length {self._len} != manifest {int(want_len)}"
        if self._fn is None:
            return None
        want = int(self._c.get("value", -1))
        if self._crc != want:
            return (
                f"{self._c.get('algo')} {self._crc:#010x} != "
                f"manifest {want:#010x}"
            )
        return None


def _read_exact(fp, n: int) -> "bytes | None":
    """Read exactly n bytes, or None on a clean/short end (the resume
    signal; framing errors raise instead)."""
    buf = b""
    while len(buf) < n:
        try:
            b = fp.read(n - len(buf))
        except Exception:  # lint: disable=GT011(short-read protocol: a dead transport IS the truncation signal the resume loop keys on)
            return None  # transport died mid-read: truncation
        if not b:
            return None
        buf += b
    return buf


def read_stream(fp, dest_dir: str) -> "tuple[dict | None, int, bool]":
    """Consume a snapshot stream from file-like ``fp``, landing each
    verified file under ``dest_dir`` at its ``rel`` path. Returns
    ``(doc, files_done, complete)`` — ``complete`` only when the END
    record arrived, ``files_done`` counting fully-landed-and-verified
    files (the resume offset for the next attempt). A checksum or
    framing violation raises; a mere truncation returns what landed."""
    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.store.fs import _fsync_dir

    fsync = bool(sys_prop("store.fsync"))
    chunk = max(int(sys_prop("snapshot.chunk.bytes")), 1)
    doc: "dict | None" = None
    done = 0
    complete = False
    while True:
        head = _read_exact(fp, _HEADER.size)
        if head is None:
            break
        magic, kind, length, crc = _HEADER.unpack(head)
        if magic != _MAGIC:
            raise SnapshotFormatError(
                f"bad snapshot record magic {magic:#010x}"
            )
        if kind in (KIND_BEGIN, KIND_END):
            body = _read_exact(fp, int(length))
            if body is None:
                break
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise SnapshotFormatError("snapshot record crc mismatch")
            if kind == KIND_BEGIN:
                doc = json.loads(body)
            else:
                complete = True
                break
            continue
        if kind != KIND_FILE:
            raise SnapshotFormatError(f"unknown snapshot record kind {kind}")
        lb = _read_exact(fp, _LEN.size)
        if lb is None:
            break
        (mlen,) = _LEN.unpack(lb)
        mb = _read_exact(fp, int(mlen))
        if mb is None:
            break
        if zlib.crc32(mb) & 0xFFFFFFFF != crc:
            raise SnapshotFormatError("snapshot file-record crc mismatch")
        meta = json.loads(mb)
        nbytes = int(length) - _LEN.size - int(mlen)
        if nbytes != int(meta.get("nbytes", -1)):
            raise SnapshotFormatError(
                f"file record length disagrees with meta for "
                f"{meta.get('rel')!r}"
            )
        rel = _safe_rel(str(meta.get("rel", "")))
        path = os.path.join(dest_dir, rel)
        os.makedirs(os.path.dirname(path) or dest_dir, exist_ok=True)
        verifier = _Verifier(meta.get("checksum"))
        got = 0
        truncated = False
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            while got < nbytes:
                b = _read_exact(fp, min(chunk, nbytes - got))
                if b is None:
                    truncated = True
                    break
                verifier.update(b)
                view = memoryview(b)
                while view:
                    view = view[os.write(fd, view):]
                got += len(b)
            if not truncated and fsync:
                os.fsync(fd)
        finally:
            os.close(fd)
        if truncated:
            # partial file: unlink so a resume re-lands it whole
            try:
                os.unlink(path)
            except OSError:
                pass
            break
        err = verifier.error()
        if err:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise SnapshotError(
                f"snapshot file {rel!r} failed verification: {err}"
            )
        done += 1
        # refresh the stage lease so the TTL sweep never reclaims a
        # stage a live download is still filling
        try:
            os.utime(dest_dir)
        except OSError:
            pass
    if complete and fsync:
        _fsync_dir(dest_dir)
    return doc, done, complete


# -- install -----------------------------------------------------------------


def install_files(type_dir: str, doc: dict, src_dir: str) -> int:
    """Swap a fully-landed snapshot into ``type_dir`` with the store's
    own publish order: data files first (atomic renames — ``src_dir``
    lives on the same filesystem), directories fsynced, the manifest
    (+ its ``.gen`` sidecar) published LAST. A crash at any instant
    leaves the previous manifest published with its files intact (the
    new generation's files are just unpinned orphans the sweep
    reclaims). Returns data bytes installed. Caller holds the store's
    exclusive lock when a live store is attached to ``type_dir``."""
    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.store.fs import FileSystemDataStore, _fsync_dir

    fsync = bool(sys_prop("store.fsync"))
    moved = 0
    dirs = {type_dir}
    for rec in doc.get("files", ()):
        rel = _safe_rel(str(rec.get("rel", "")))
        if rel == "schema.json":
            continue
        src = os.path.join(src_dir, rel)
        dst = os.path.join(type_dir, rel)
        if not os.path.exists(src):
            raise SnapshotError(
                f"snapshot install missing staged file {rel!r}"
            )
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(src, dst)  # lint: disable=GT007(read_stream fsynced each staged file as it landed; the target dirs fsync below before the manifest publishes)
        dirs.add(os.path.dirname(dst))
        moved += int(rec.get("nbytes", 0))
    if fsync:
        for d in sorted(dirs):
            _fsync_dir(d)
    src_manifest = os.path.join(src_dir, "schema.json")
    if not os.path.exists(src_manifest):
        raise SnapshotError("snapshot install missing staged manifest")
    with open(src_manifest) as fh:
        body = fh.read()
    FileSystemDataStore._publish_manifest(
        os.path.join(type_dir, "schema.json"), body,
        str(doc.get("generation") or json.loads(body).get("generation")),
    )
    return moved
