"""In-memory columnar DataStore.

The backend-free integration surface (ref: geomesa-index-api test
TestGeoMesaDataStore [UNVERIFIED - empty reference mount]): a full
schema -> write -> index-build -> plan -> device-scan path with no external
storage, exercising exactly the code the TPU bench and the Parquet store
share.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.audit import observe_query
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.index.api import BuiltIndex
from geomesa_tpu.index.build import DEFAULT_PARTITION_SIZE, build_index
from geomesa_tpu.index.keyspaces import default_indices, keyspace_for
from geomesa_tpu.query.plan import Query, QueryPlan, as_query, plan_query
from geomesa_tpu.query.runner import QueryResult, run_query


@dataclass
class _TypeState:
    sft: SimpleFeatureType
    pending: "list[FeatureBatch]" = field(default_factory=list)
    data: "FeatureBatch | None" = None
    indices: "dict[str, BuiltIndex]" = field(default_factory=dict)
    data_interval: "tuple[int, int] | None" = None
    stats: object = None  # SeqStat maintained at flush (GeoMesaStats analog)


class MemoryDataStore:
    """create_schema / write / query / explain over in-memory partitions."""

    def __init__(
        self,
        partition_size: int = DEFAULT_PARTITION_SIZE,
        audit_writer=None,
    ):
        self._types: dict[str, _TypeState] = {}
        self.partition_size = partition_size
        self.audit_writer = audit_writer  # geomesa_tpu.audit.AuditWriter

    # -- schema ------------------------------------------------------------

    def create_schema(self, sft: "SimpleFeatureType | str", spec: "str | None" = None):
        if isinstance(sft, str):
            sft = SimpleFeatureType.create(sft, spec)
        if sft.type_name in self._types:
            raise ValueError(f"schema {sft.type_name!r} exists")
        self._types[sft.type_name] = _TypeState(sft)
        return sft

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._state(type_name).sft

    @property
    def type_names(self) -> list:
        return list(self._types)

    def remove_schema(self, type_name: str) -> None:
        del self._types[type_name]

    def _state(self, type_name: str) -> _TypeState:
        if type_name not in self._types:
            raise KeyError(f"no schema {type_name!r}; call create_schema first")
        return self._types[type_name]

    # -- writes ------------------------------------------------------------

    def write(self, type_name: str, columns_or_batch, fids=None) -> int:
        """Append a batch (dict of columns or FeatureBatch); indices are
        rebuilt lazily at the next query (the BatchWriter flush analog)."""
        st = self._state(type_name)
        if isinstance(columns_or_batch, FeatureBatch):
            batch = columns_or_batch
        else:
            batch = FeatureBatch.from_columns(st.sft, columns_or_batch, fids)
        if st.pending or st.data is None:
            st.pending.append(batch)
        else:
            st.pending = [st.data, batch]
            st.data = None
        st.indices = {}
        return len(batch)

    def delete(self, type_name: str, fids) -> int:
        st = self._state(type_name)
        self._flush(st)
        if st.data is None:
            return 0
        # object dtype: a mixed int/str id list must not collapse to all-str
        keep = ~np.isin(st.data.fids, np.asarray(list(fids), dtype=object))
        removed = int((~keep).sum())
        st.pending = [st.data.take(np.nonzero(keep)[0])]
        st.data = None
        st.indices = {}
        return removed

    def age_off(self, type_name: str, before_ms: int) -> int:
        from geomesa_tpu.store.ageoff import age_off

        return age_off(self, type_name, self._state(type_name).sft, before_ms)

    def _flush(self, st: _TypeState) -> None:
        if st.pending:
            batches = ([st.data] if st.data is not None else []) + st.pending
            st.data = (
                batches[0] if len(batches) == 1 else FeatureBatch.concat(batches)
            )
            st.pending = []
            st.indices = {}
        if st.data is not None and not st.indices:
            for name in default_indices(st.sft):
                ks = keyspace_for(st.sft, name)
                st.indices[name] = build_index(ks, st.data, self.partition_size)
            dtg = st.sft.dtg_field
            if dtg is not None and len(st.data):
                d = st.data.column(dtg)
                st.data_interval = (int(d.min()), int(d.max()))
            st.stats = self._build_stats(st)

    def _build_stats(self, st: _TypeState):
        return build_default_stats(st.sft, st.data)

    def stats(self, type_name: str):
        """The maintained SeqStat for a type (ref GeoMesaStats.getStats).
        Always returns a SeqStat (zero-observation sketches before any
        write)."""
        st = self._state(type_name)
        self._flush(st)
        if st.stats is None:
            st.stats = self._build_stats(st)
        return st.stats

    # -- queries -----------------------------------------------------------

    def plan(self, type_name: str, query: "Query | str | ast.Filter") -> QueryPlan:
        """Plan a query; on an empty type plans against the schema's default
        key spaces so filter errors surface and explain() works uniformly."""
        st = self._state(type_name)
        self._flush(st)
        q = as_query(query)
        indices = st.indices or {
            name: keyspace_for(st.sft, name) for name in default_indices(st.sft)
        }
        return plan_query(
            st.sft,
            indices,
            q,
            data_interval=st.data_interval,
            stats=self.stats(type_name),
        )

    def query(self, type_name: str, query: "Query | str | ast.Filter" = ast.Include) -> QueryResult:
        import time as _time

        t0 = _time.perf_counter()
        plan = self.plan(type_name, query)  # flushes
        t1 = _time.perf_counter()
        st = self._state(type_name)
        if st.data is None or len(st.data) == 0:
            from geomesa_tpu.query.runner import _post_process

            empty = (
                st.data
                if st.data is not None
                else FeatureBatch.from_columns(
                    st.sft, {a.name: [] for a in st.sft.attributes}
                )
            )
            result = QueryResult(_post_process(empty, plan), plan, 0, 0)
        else:
            result = run_query(st.indices[plan.index_name], plan)
        observe_query(
            "memory", type_name, plan, t0, t1, _time.perf_counter(), result,
            self.audit_writer,
        )
        return result

    def explain(self, type_name: str, query: "Query | str | ast.Filter") -> str:
        return self.plan(type_name, query).explain()

    def get_by_ids(self, type_name: str, fids) -> FeatureBatch:
        """Direct id-index lookup (the Id-filter fast path)."""
        st = self._state(type_name)
        self._flush(st)
        built = st.indices.get("id")
        want = np.asarray(fids)
        if built is None or built.n == 0:
            empty = np.array([], dtype=np.int64)
            if built is not None:
                return built.batch.take(empty)
            raise ValueError(f"no data written to {type_name!r}")
        sorted_fids = built.keys["fid"]
        pos = np.clip(np.searchsorted(sorted_fids, want), 0, built.n - 1)
        hit = sorted_fids[pos] == want
        return built.batch.take(pos[hit])

    def count(self, type_name: str, query: "Query | str | ast.Filter" = ast.Include) -> int:
        return len(self.query(type_name, query))


def build_default_stats(
    sft: SimpleFeatureType,
    data: "FeatureBatch | None",
    z3_keys: "tuple | None" = None,
):
    """Write-time stats (ref MetadataBackedStats/StatUpdater): count,
    MinMax per numeric/date attribute, Z3Histogram for point+time
    schemas. Used by the stats API/CLI and selectivity estimates.

    ``z3_keys=(bin, z)`` feeds pre-encoded keys to the Z3 histogram — the
    FS flush already encoded every row for the sorted-index build, and
    re-encoding for the histogram doubled the flush's encode cost. Only
    valid when the keys were computed with the schema's own interval."""
    from geomesa_tpu.stats import SeqStat
    from geomesa_tpu.stats.sketches import (
        Cardinality,
        CountStat,
        MinMax,
        Z3HistogramStat,
    )

    stats: list = [CountStat()]
    for a in sft.attributes:
        if a.column_dtype is not None and a.column_dtype != np.bool_:
            stats.append(MinMax(a.name))
        if a.indexed and not a.is_geometry:
            # equality-selectivity input for the stat-based planner
            stats.append(Cardinality(a.name))
    z3_hist = None
    geom, dtg = sft.geom_field, sft.dtg_field
    if geom and dtg and sft.descriptor(geom).is_point:
        z3_hist = Z3HistogramStat(geom, dtg, sft.z3_interval)
        stats.append(z3_hist)
    seq = SeqStat(stats)
    if data is not None and len(data):
        if z3_hist is not None and z3_keys is not None:
            seq = SeqStat([s for s in seq.stats if s is not z3_hist])
            seq.observe_batch(data)
            z3_hist.observe_binned(*z3_keys)
            seq = SeqStat(seq.stats + [z3_hist])
        else:
            seq.observe_batch(data)
    return seq





