"""Sorted key-value DataStore: the Accumulo/HBase/Cassandra/Redis/Bigtable
backend family, rebuilt as one adapter over pluggable sorted-KV engines.

(ref: geomesa-accumulo AccumuloIndexAdapter + iterators/Z3Iterator +
GeoMesaMetadata/TableBasedMetadata; geomesa-hbase HBaseIndexAdapter;
geomesa-redis RedisIndexAdapter (ZSET score = z) [UNVERIFIED - empty
reference mount].)

Design: every enabled index materializes each feature as one row in a
sorted byte-key table::

    row key  = shard byte ++ big-endian order-preserving key tuple ++ fid
    value    = compact lazy binary blob (features.binser), visibility in
               user-data (the Accumulo cell-visibility analog)

Queries reuse the shared planner (query.plan) unchanged -- only range
*execution* differs from the columnar stores: key ranges become byte
ranges fanned out across shards, scanned in chunks, with a vectorized
z-decode prefilter on the raw keys (the Z3Iterator/Z2Iterator analog,
NumPy-vectorized instead of per-KV scalar code) before any value bytes are
deserialized. Exact predicate evaluation then runs on the deserialized
columnar chunk via the same compiled filter the TPU scan path uses.

Backends:

- ``MemoryKV``   -- in-process sorted map. Doubles as the reference's
  TestGeoMesaDataStore (backend-free integration) and the Redis
  sorted-set model (score = z-key).
- ``SqliteKV``   -- stdlib sqlite3 B-tree, disk-backed, range scans via
  PRIMARY KEY order. The Accumulo/HBase tablet analog: durable sorted
  tables + metadata table in one catalog file.

A backend reports ``supports_filters`` (server-side pushdown; the
coprocessor/iterator capability). Bigtable's no-coprocessor shape is
``supports_filters=False`` -- the store then runs the same prefilter
client-side, exactly how geomesa-bigtable degrades.
"""

from __future__ import annotations

import bisect
import json
import sqlite3
import struct
import time as _time

import numpy as np

from geomesa_tpu.audit import observe_query
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.binser import deserialize_batch, serialize_batch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.ast import attributes_of
from geomesa_tpu.index.keyspaces import default_indices, keyspace_for
from geomesa_tpu.query.plan import Query, QueryPlan, as_query, plan_query
from geomesa_tpu.query.runner import QueryResult, _post_process

DEFAULT_SHARDS = 4  # ref ShardStrategy default z-shard count
# rows per server-side iterator batch: the 'scan.chunk' system property


# ---------------------------------------------------------------------------
# order-preserving byte encodings
# ---------------------------------------------------------------------------


def _enc_u64(v: int) -> bytes:
    return struct.pack(">Q", int(v) & 0xFFFFFFFFFFFFFFFF)


def _enc_i64(v: int) -> bytes:
    return struct.pack(">Q", (int(v) + (1 << 63)) & 0xFFFFFFFFFFFFFFFF)


def _enc_i32(v: int) -> bytes:
    return struct.pack(">I", (int(v) + (1 << 31)) & 0xFFFFFFFF)


def _enc_f64(v: float) -> bytes:
    (bits,) = struct.unpack(">Q", struct.pack(">d", float(v)))
    if bits & (1 << 63):
        bits = ~bits & 0xFFFFFFFFFFFFFFFF  # negative: invert all
    else:
        bits |= 1 << 63  # positive: flip sign bit
    return struct.pack(">Q", bits)


def _enc_attr(v) -> bytes:
    """Typed order-preserving encoding for attribute/id key parts. Strings
    are null-terminated so shorter strings sort before their extensions'
    successors correctly within mixed-length keys."""
    if isinstance(v, (bool, np.bool_)):
        return b"\x01" if v else b"\x00"
    if isinstance(v, (int, np.integer)):
        return _enc_i64(int(v))
    if isinstance(v, (float, np.floating)):
        return _enc_f64(float(v))
    return str(v).encode("utf-8") + b"\x00"


_COL_ENC = {
    "bin": _enc_i32,
    "z": _enc_u64,
    "xz": _enc_i64,
    "value": _enc_attr,
    "fid": _enc_attr,
}


def _stats_bytes(seq) -> bytes:
    """Stats persist as the JSON codec (no pickle in store metadata)."""
    import json as _json

    return _json.dumps(seq.to_json()).encode("utf-8")


def _stats_from_bytes(raw: bytes):
    """None on undecodable blobs (e.g. a legacy pickled payload): stats
    are advisory, a reopened store must keep working."""
    import json as _json

    from geomesa_tpu.stats.sketches import seq_from_json

    try:
        return seq_from_json(_json.loads(raw.decode("utf-8")))
    except Exception:  # lint: disable=GT011(persisted sketches are advisory: a corrupt blob degrades estimates, never a failed reopen)
        return None


def _keyspace_attrs(ks) -> set:
    """The schema attributes a keyspace reads to build its keys."""
    return {
        a
        for a in (
            getattr(ks, "geom_field", None),
            getattr(ks, "dtg_field", None),
            getattr(ks, "attr", None),
        )
        if a is not None
    }


def _incr(key: bytes) -> "bytes | None":
    """Smallest byte string > every string with prefix ``key`` (None =
    unbounded: key was all 0xff)."""
    b = bytearray(key)
    while b and b[-1] == 0xFF:
        b.pop()
    if not b:
        return None
    b[-1] += 1
    return bytes(b)


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


class MemoryKV:
    """Sorted in-process KV (ref test role: TestGeoMesaDataStore's sorted
    in-memory adapter; data-model match for Redis ZSET-per-index)."""

    supports_filters = True  # in-process == always "server side"

    def __init__(self):
        self._tables: dict = {}

    def create_table(self, name: str) -> None:
        self._tables.setdefault(name, ({}, []))

    def drop_table(self, name: str) -> None:
        self._tables.pop(name, None)

    def list_tables(self) -> list:
        return sorted(self._tables)

    def write(self, table: str, rows) -> None:
        data, keys = self._tables[table]
        for k, v in rows:
            if k not in data:
                bisect.insort(keys, k)
            data[k] = v

    def delete(self, table: str, keys) -> None:
        data, sorted_keys = self._tables[table]
        for k in keys:
            if k in data:
                del data[k]
                i = bisect.bisect_left(sorted_keys, k)
                del sorted_keys[i]

    def scan(self, table: str, lo: bytes, hi: "bytes | None"):
        """Yield (key, value) for lo <= key < hi, in key order."""
        data, keys = self._tables[table]
        i = bisect.bisect_left(keys, lo)
        j = bisect.bisect_left(keys, hi) if hi is not None else len(keys)
        for k in keys[i:j]:
            yield k, data[k]

    def close(self) -> None:
        pass


class SqliteKV:
    """sqlite3-backed sorted KV: each table is (k BLOB PRIMARY KEY,
    v BLOB); range scans ride the B-tree. One file = one catalog (the
    Accumulo instance analog); ':memory:' works for tests."""

    supports_filters = True

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._db = sqlite3.connect(path)
        self._db.execute("PRAGMA journal_mode=WAL") if path != ":memory:" else None

    @staticmethod
    def _q(name: str) -> str:
        if not name.replace("_", "").replace("-", "").isalnum():
            raise ValueError(f"bad table name {name!r}")
        return '"' + name + '"'

    def create_table(self, name: str) -> None:
        self._db.execute(
            f"CREATE TABLE IF NOT EXISTS {self._q(name)} "
            "(k BLOB PRIMARY KEY, v BLOB) WITHOUT ROWID"
        )
        self._db.commit()

    def drop_table(self, name: str) -> None:
        self._db.execute(f"DROP TABLE IF EXISTS {self._q(name)}")
        self._db.commit()

    def list_tables(self) -> list:
        rows = self._db.execute(
            "SELECT name FROM sqlite_master WHERE type='table' ORDER BY name"
        ).fetchall()
        return [r[0] for r in rows]

    def write(self, table: str, rows) -> None:
        self._db.executemany(
            f"INSERT OR REPLACE INTO {self._q(table)} VALUES (?, ?)",
            [(sqlite3.Binary(k), sqlite3.Binary(v)) for k, v in rows],
        )
        self._db.commit()

    def delete(self, table: str, keys) -> None:
        self._db.executemany(
            f"DELETE FROM {self._q(table)} WHERE k = ?",
            [(sqlite3.Binary(k),) for k in keys],
        )
        self._db.commit()

    def scan(self, table: str, lo: bytes, hi: "bytes | None"):
        if hi is None:
            cur = self._db.execute(
                f"SELECT k, v FROM {self._q(table)} WHERE k >= ? ORDER BY k",
                (sqlite3.Binary(lo),),
            )
        else:
            cur = self._db.execute(
                f"SELECT k, v FROM {self._q(table)} WHERE k >= ? AND k < ? ORDER BY k",
                (sqlite3.Binary(lo), sqlite3.Binary(hi)),
            )
        for k, v in cur:
            yield bytes(k), bytes(v)

    def compact(self) -> None:
        self._db.execute("VACUUM")

    def close(self) -> None:
        self._db.close()


# ---------------------------------------------------------------------------
# vectorized key prefilters (the Z3Iterator / Z2Iterator analog)
# ---------------------------------------------------------------------------


def _key_prefilter(keyspace, plan: QueryPlan):
    """Vectorized (keys: list[bytes]) -> bool mask over raw row keys, or
    None when the index/bounds don't support key-level pruning.

    Decodes the z/xz portion of each key and rejects rows whose quantized
    x/y cell falls outside every query envelope -- exactly what the
    reference's Z3Iterator does per-KV on the tablet server, vectorized
    over the scan chunk. False positives are fine (exact filter follows);
    false negatives are impossible because envelope bounds quantize with
    the same NormalizedDimension floor/clamp as the index keys.
    """
    from geomesa_tpu.curves import zorder
    from geomesa_tpu.index.keyspaces import Z2KeySpace, Z3KeySpace

    if plan.geom_bounds.unbounded or plan.geom_bounds.empty:
        return None
    envs = [v[0] for v in plan.geom_bounds.values]

    if isinstance(keyspace, Z3KeySpace):
        sfc = keyspace.sfc
        off = 1 + 4  # shard + bin
        decode = zorder.decode_3d_np
    elif isinstance(keyspace, Z2KeySpace):
        sfc = keyspace.sfc
        off = 1
        decode = zorder.decode_2d_np
    else:
        return None

    boxes = [
        (
            int(sfc.lon.normalize(e.xmin)),
            int(sfc.lon.normalize(e.xmax)),
            int(sfc.lat.normalize(e.ymin)),
            int(sfc.lat.normalize(e.ymax)),
        )
        for e in envs
    ]

    def prefilter(keys: list) -> np.ndarray:
        raw = b"".join(k[off : off + 8] for k in keys)
        z = np.frombuffer(raw, dtype=">u8").astype(np.uint64)
        xy = decode(z)
        nx, ny = xy[0].astype(np.int64), xy[1].astype(np.int64)
        m = np.zeros(len(keys), dtype=bool)
        for xlo, xhi, ylo, yhi in boxes:
            m |= (nx >= xlo) & (nx <= xhi) & (ny >= ylo) & (ny <= yhi)
        return m

    return prefilter


# ---------------------------------------------------------------------------
# the datastore
# ---------------------------------------------------------------------------


class KVDataStore:
    """GeoMesaDataStore over a sorted-KV backend: createSchema writes
    metadata rows, writes fan each feature into every enabled index table,
    queries run planner -> byte ranges x shards -> chunked scan ->
    key prefilter -> lazy deserialize -> exact filter."""

    def __init__(
        self,
        backend=None,
        catalog: str = "geomesa",
        n_shards: int = DEFAULT_SHARDS,
        audit_writer=None,
    ):
        self.backend = backend if backend is not None else MemoryKV()
        self.catalog = catalog
        self.n_shards = n_shards
        self.audit_writer = audit_writer
        self._types: dict = {}
        self._stats: dict = {}
        self._intervals: dict = {}
        self.backend.create_table(catalog)
        # reopen: load schemas from the metadata table
        for k, v in self.backend.scan(self.catalog, b"", None):
            key = k.decode("utf-8")
            if key.endswith("~attributes"):
                name = key[: -len("~attributes")]
                self._types[name] = SimpleFeatureType.create(
                    name, v.decode("utf-8")
                )
        for name in self._types:
            iv = self._meta_get(f"{name}~interval")
            if iv:
                self._intervals[name] = tuple(json.loads(iv))

    # -- metadata (ref GeoMesaMetadata / TableBasedMetadata) ----------------

    def _meta_put(self, key: str, value: bytes) -> None:
        self.backend.write(self.catalog, [(key.encode("utf-8"), value)])

    def _meta_get(self, key: str) -> "bytes | None":
        k = key.encode("utf-8")
        for kk, v in self.backend.scan(self.catalog, k, _incr(k)):
            if kk == k:
                return v
        return None

    def _table(self, type_name: str, index: str) -> str:
        return f"{self.catalog}_{type_name}_{index}".replace(":", "_")

    # -- schema -------------------------------------------------------------

    def create_schema(self, sft: "SimpleFeatureType | str", spec: "str | None" = None):
        if isinstance(sft, str):
            sft = SimpleFeatureType.create(sft, spec)
        if sft.type_name in self._types:
            raise ValueError(f"schema {sft.type_name!r} exists")
        self._types[sft.type_name] = sft
        self._meta_put(
            f"{sft.type_name}~attributes", sft.spec.encode("utf-8")
        )
        for index in default_indices(sft):
            self.backend.create_table(self._table(sft.type_name, index))
        return sft

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._types[type_name]

    @property
    def type_names(self) -> list:
        return sorted(self._types)

    def indices(self, type_name: str) -> list:
        return default_indices(self._types[type_name])

    def add_index(self, type_name: str, index: str) -> int:
        """Add an index to an existing schema and back-populate it from
        stored data (ref: geomesa-jobs index back-population / attribute
        re-index MapReduce jobs). Returns rows written. Value blobs are
        copied straight from the id table; only the key attributes are
        deserialized."""
        sft = self._types[type_name]
        current = default_indices(sft)
        if index in current:
            raise ValueError(f"index {index!r} already enabled")
        ks = keyspace_for(sft, index)  # validates name against the schema
        table = self._table(type_name, index)
        self.backend.create_table(table)
        id_table = self._table(type_name, "id")
        # only the attributes the keyspace reads get deserialized
        key_attrs = [
            a for a in sft.attribute_names if a in _keyspace_attrs(ks)
        ] or None
        written = 0
        buf: list = []

        def flush() -> int:
            if not buf:
                return 0
            blobs = [v for _, v in buf]
            batch = deserialize_batch(sft, blobs, key_attrs)
            shards = self._shard_of(batch.fids)
            rows = self._row_keys(ks, batch, shards)
            self.backend.write(table, list(zip(rows, blobs)))
            n = len(buf)
            buf.clear()
            return n

        try:
            for k, v in self.backend.scan(id_table, b"", None):
                buf.append((k, v))
                if len(buf) >= 8192:
                    written += flush()
            written += flush()
        except Exception:
            # don't leave a half-built orphan table behind
            self.backend.drop_table(table)
            raise
        # persist the new index list in the schema's user data
        sft.user_data["geomesa.indices"] = ",".join([*current, index])
        self._meta_put(f"{type_name}~attributes", sft.spec.encode("utf-8"))
        return written

    def remove_index(self, type_name: str, index: str) -> None:
        """Disable and drop an index (the id index is load-bearing for
        upserts/deletes and cannot be removed)."""
        sft = self._types[type_name]
        current = default_indices(sft)
        if index not in current:
            raise ValueError(f"index {index!r} not enabled")
        if index == "id":
            raise ValueError("the id index cannot be removed")
        self.backend.drop_table(self._table(type_name, index))
        sft.user_data["geomesa.indices"] = ",".join(
            i for i in current if i != index
        )
        self._meta_put(f"{type_name}~attributes", sft.spec.encode("utf-8"))

    def remove_schema(self, type_name: str) -> None:
        sft = self._types.pop(type_name)
        for index in default_indices(sft):
            self.backend.drop_table(self._table(type_name, index))
        self.backend.delete(
            self.catalog,
            [
                f"{type_name}~attributes".encode(),
                f"{type_name}~stats".encode(),
                f"{type_name}~interval".encode(),
            ],
        )
        self._stats.pop(type_name, None)
        self._intervals.pop(type_name, None)

    # -- writes -------------------------------------------------------------

    def _shard_of(self, fids: np.ndarray) -> np.ndarray:
        """Deterministic fid hash -> shard byte (ref ShardStrategy).
        crc32, not Python hash(): shard bytes are persisted in row keys, so
        the hash must be stable across processes (PYTHONHASHSEED salts
        str hashes)."""
        import zlib

        out = np.empty(len(fids), dtype=np.uint8)
        for i, f in enumerate(fids):
            h = (
                int(f)
                if isinstance(f, (int, np.integer))
                else zlib.crc32(str(f).encode("utf-8"))
            )
            out[i] = (h & 0x7FFFFFFF) % self.n_shards
        return out

    def _row_keys(self, keyspace, batch: FeatureBatch, shards: np.ndarray):
        keys = keyspace.index_keys(batch)
        cols = [keys[c] for c in keyspace.key_columns]
        encs = [_COL_ENC[c] for c in keyspace.key_columns]
        fids = batch.fids
        out = []
        for r in range(len(batch)):
            parts = [bytes([shards[r]])]
            parts.extend(enc(c[r]) for enc, c in zip(encs, cols))
            if keyspace.key_columns != ("fid",):
                parts.append(_enc_attr(fids[r]))
            out.append(b"".join(parts))
        return out

    def write(
        self, type_name: str, columns_or_batch, fids=None, assume_new: bool = False
    ) -> int:
        """Upsert features. Re-writing an existing fid replaces all of its
        index rows (the old z/attribute rows are removed first, so queries
        never see stale locations). ``assume_new=True`` skips the
        existing-fid lookup for bulk loads of known-fresh data."""
        sft = self._types[type_name]
        if isinstance(columns_or_batch, FeatureBatch):
            batch = columns_or_batch
        else:
            batch = FeatureBatch.from_columns(sft, columns_or_batch, fids)
        if not len(batch):
            return 0
        if not assume_new:
            old = self.get_by_ids(type_name, list(batch.fids))
            if len(old):
                self._delete_rows(type_name, old)
                self._stats_remove(type_name, len(old))
        values = serialize_batch(batch)
        shards = self._shard_of(batch.fids)
        for index in default_indices(sft):
            ks = keyspace_for(sft, index)
            rows = self._row_keys(ks, batch, shards)
            self.backend.write(
                self._table(type_name, index), list(zip(rows, values))
            )
        # stats + data interval (ref StatUpdater flush)
        st = self.stats(type_name)
        st.observe_batch(batch)
        self._meta_put(
            f"{type_name}~stats", _stats_bytes(st)
        )
        dtg = sft.dtg_field
        if dtg is not None:
            col = batch.column(dtg)
            lo, hi = int(col.min()), int(col.max())
            cur = self._intervals.get(type_name)
            if cur:
                lo, hi = min(lo, cur[0]), max(hi, cur[1])
            self._intervals[type_name] = (lo, hi)
            self._meta_put(
                f"{type_name}~interval", json.dumps([lo, hi]).encode()
            )
        return len(batch)

    def _delete_rows(self, type_name: str, batch: FeatureBatch) -> None:
        sft = self._types[type_name]
        shards = self._shard_of(batch.fids)
        for index in default_indices(sft):
            ks = keyspace_for(sft, index)
            rows = self._row_keys(ks, batch, shards)
            self.backend.delete(self._table(type_name, index), rows)

    def _stats_remove(self, type_name: str, n: int) -> None:
        """Decrement the exact count on delete; sketch stats (MinMax/HLL/
        TopK/histograms) cannot unobserve and stay conservative, matching
        the reference's delete-time stats behavior."""
        from geomesa_tpu.stats.sketches import CountStat

        st = self.stats(type_name)
        for s in st.stats:
            if isinstance(s, CountStat):
                s.count = max(0, s.count - n)
        self._meta_put(
            f"{type_name}~stats", _stats_bytes(st)
        )

    def delete(self, type_name: str, fids) -> int:
        batch = self.get_by_ids(type_name, fids)
        if not len(batch):
            return 0
        self._delete_rows(type_name, batch)
        self._stats_remove(type_name, len(batch))
        return len(batch)

    def age_off(self, type_name: str, before_ms: int) -> int:
        from geomesa_tpu.store.ageoff import age_off

        return age_off(self, type_name, self._types[type_name], before_ms)

    # -- stats --------------------------------------------------------------

    def stats(self, type_name: str):
        if type_name not in self._stats:
            raw = self._meta_get(f"{type_name}~stats")
            loaded = _stats_from_bytes(raw) if raw is not None else None
            if loaded is not None:
                self._stats[type_name] = loaded
            else:
                from geomesa_tpu.store.memory import build_default_stats

                self._stats[type_name] = build_default_stats(
                    self._types[type_name], None
                )
        return self._stats[type_name]

    # -- queries ------------------------------------------------------------

    def plan(self, type_name: str, query: "Query | str | ast.Filter") -> QueryPlan:
        sft = self._types[type_name]
        q = as_query(query)
        indices = {
            name: keyspace_for(sft, name) for name in default_indices(sft)
        }
        return plan_query(
            sft,
            indices,
            q,
            data_interval=self._intervals.get(type_name),
            stats=self.stats(type_name),
        )

    def _byte_ranges(self, keyspace, plan: QueryPlan):
        """KeyRanges -> [(lo_bytes, hi_bytes_exclusive)] x shards."""
        encs = [_COL_ENC[c] for c in keyspace.key_columns]
        out = []
        if plan.ranges is None:
            for s in range(self.n_shards):
                lo = bytes([s])
                out.append((lo, _incr(lo)))
            return out
        for s in range(self.n_shards):
            sb = bytes([s])
            for r in plan.ranges:
                lo = sb + b"".join(
                    enc(v) for enc, v in zip(encs, r.lo) if not _is_neg_inf(v)
                )
                if any(_is_pos_inf(v) for v in r.hi):
                    hi_prefix = sb + b"".join(
                        enc(v)
                        for enc, v in zip(encs, r.hi)
                        if not _is_pos_inf(v)
                    )
                    hi = _incr(hi_prefix) if hi_prefix != sb else _incr(sb)
                else:
                    hi = _incr(
                        sb + b"".join(enc(v) for enc, v in zip(encs, r.hi))
                    )
                out.append((lo, hi))
        return out

    def query(
        self, type_name: str, query: "Query | str | ast.Filter" = ast.Include
    ) -> QueryResult:
        t0 = _time.perf_counter()
        sft = self._types[type_name]
        plan = self.plan(type_name, query)
        t1 = _time.perf_counter()
        ks = keyspace_for(sft, plan.index_name)
        table = self._table(type_name, plan.index_name)
        prefilter = _key_prefilter(ks, plan)

        q = plan.query
        columns = None
        if q.properties is not None:
            need = set(q.properties) | attributes_of(plan.filter)
            if q.sort_by:
                need.add(q.sort_by)
            columns = [a.name for a in sft.attributes if a.name in need]

        chunks: list[FeatureBatch] = []
        scanned = 0
        buf_k: list = []
        buf_v: list = []

        def flush_chunk():
            nonlocal scanned
            if not buf_k:
                return
            scanned += len(buf_k)
            vals = buf_v
            if prefilter is not None:
                m = prefilter(buf_k)
                vals = [v for v, keep in zip(buf_v, m) if keep]
            if vals:
                sub = deserialize_batch(sft, vals, columns)
                mask = plan.compiled.host_mask(sub)
                idx = np.nonzero(mask)[0]
                if len(idx):
                    chunks.append(sub.take(idx))
            buf_k.clear()
            buf_v.clear()

        from geomesa_tpu.conf import QueryTimeout, sys_prop

        timeout_ms = sys_prop("query.timeout")
        deadline = t0 + timeout_ms / 1000.0 if timeout_ms else None
        chunk_rows = max(1, sys_prop("scan.chunk"))

        def check_deadline():
            if deadline and _time.perf_counter() > deadline:
                raise QueryTimeout(
                    f"query on {type_name!r} exceeded {timeout_ms}ms"
                )

        for lo, hi in _coalesce(self._byte_ranges(ks, plan)):
            check_deadline()  # per range, so small scans still time out
            for k, v in self.backend.scan(table, lo, hi):
                buf_k.append(k)
                buf_v.append(v)
                if len(buf_k) >= chunk_rows:
                    flush_chunk()
                    check_deadline()
        flush_chunk()
        check_deadline()

        if chunks:
            out = chunks[0] if len(chunks) == 1 else FeatureBatch.concat(chunks)
        else:
            empty_sft = sft
            cols = {a.name: [] for a in sft.attributes}
            if columns is not None:
                empty_sft = SimpleFeatureType(
                    sft.type_name,
                    tuple(sft.descriptor(c) for c in columns),
                    sft.user_data,
                )
                cols = {c: [] for c in columns}
            out = FeatureBatch.from_columns(empty_sft, cols)
        out = _post_process(out, plan)
        from geomesa_tpu.stats.sketches import CountStat

        total = sum(
            s.count for s in self.stats(type_name).stats
            if isinstance(s, CountStat)
        )
        result = QueryResult(out, plan, scanned, total)
        observe_query(
            "kv", type_name, plan, t0, t1, _time.perf_counter(), result,
            self.audit_writer,
        )
        return result

    def explain(self, type_name: str, query) -> str:
        return self.plan(type_name, query).explain()

    def count(self, type_name: str, query=ast.Include) -> int:
        return len(self.query(type_name, query))

    def get_by_ids(self, type_name: str, fids) -> FeatureBatch:
        sft = self._types[type_name]
        table = self._table(type_name, "id")
        vals = []
        for f in fids:
            shard = self._shard_of(np.array([f], dtype=object))[0]
            lo = bytes([shard]) + _enc_attr(f)
            for k, v in self.backend.scan(table, lo, _incr(lo)):
                vals.append(v)
        if not vals:
            return FeatureBatch.from_columns(
                sft, {a.name: [] for a in sft.attributes}
            )
        return deserialize_batch(sft, vals)

    def close(self) -> None:
        self.backend.close()


def _coalesce(ranges: list) -> list:
    """Merge overlapping/adjacent byte ranges so each key is scanned at
    most once (per-envelope z-ranges from OR'd predicates can overlap)."""
    if len(ranges) <= 1:
        return ranges
    ranges = sorted(ranges)
    out = [ranges[0]]
    for lo, hi in ranges[1:]:
        plo, phi = out[-1]
        if lo <= phi:
            if hi > phi:
                out[-1] = (plo, hi)
        else:
            out.append((lo, hi))
    return out


def _is_neg_inf(v) -> bool:
    return isinstance(v, float) and v == float("-inf")


def _is_pos_inf(v) -> bool:
    return isinstance(v, float) and v == float("inf")



