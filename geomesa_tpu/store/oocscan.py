"""Out-of-core streamed device scan: datasets larger than HBM.

Ref role: the reference's scans are inherently streaming — Accumulo
iterators stream tablets through the scan servers and nothing ever
requires the dataset to fit anywhere (BatchScanPlan, SURVEY section 3.1
[UNVERIFIED - empty reference mount]). The resident ``DeviceIndex`` is
the opposite trade: every scanned column pinned in HBM. This module
fills the gap between them: partitions stream through a DOUBLE-BUFFERED
device slab, the H2D upload of slab i+1 overlapping the fused scan
kernel on slab i (jax dispatch is async; the one sync point is the final
fetch), with the planner's zrange partition pruning deciding what
streams at all. Peak device memory is a couple of slabs — dataset size
is bounded by disk, not HBM.

Two layers:

- :class:`SlabStream` — the pump. Feed it host column chunks and a
  per-slab aggregation; it keeps a bounded number of slabs in flight
  and returns the per-slab results. Slab shapes pad to power-of-two
  row buckets so the jit executable set stays bounded; every 4-byte
  plane of a slab rides ONE packed uint32 upload (the staging transfer
  discipline from device_cache — per-plane uploads pay per-transfer
  latency for nothing).
- :class:`StreamedDeviceScan` — the store integration. Plans a query,
  prunes partitions by the manifest, streams the survivors from the
  store's partition files, and counts (or collects) with the SAME
  compiled fused mask the resident path uses.

    scan = StreamedDeviceScan(store, "gdelt")
    n = scan.count("BBOX(geom, -10, 35, 30, 60) AND dtg DURING ...")

The HOST side of the stream is pipelined (store/prefetch.py): slab
chunks are grouped by the manifest's partition row counts, then read +
Arrow-decoded + column-staged on worker threads with bounded read-ahead,
delivered as explicit ``(host_cols, source_batch)`` pairs in
deterministic partition order — host decode of chunk i+k overlaps both
the disk and the device kernel on slab i. ``io=`` tunes it
(PrefetchConfig / worker count int / None = the ``io.*`` system
properties); ``io=0`` is the serial baseline. Peak host memory is the
in-flight chunks (read-ahead depth, byte-budgeted) — never the dataset.

Durability interplay (ISSUE 3): the partition reads beneath a streamed
scan ride the store's crash-consistent read path — transient I/O errors
retry on the workers with bounded backoff (``io.retries`` x
``io.backoff.ms``), ``store.verify=always`` checksums every file before
decode, and a corrupt partition raises a loud per-partition
PartitionCorruptError out of the scan instead of streaming silent
garbage through the slab pump (scans pruned away from it still serve).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

__all__ = ["SlabStream", "StreamedDeviceScan"]


def _bucket(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class SlabStream:
    """Double-buffered device slab pump.

    ``agg_fn(cols, valid) -> pytree of device values`` runs jitted once
    per slab; :meth:`run` feeds it host chunks and returns the per-slab
    outputs (fetched at the end — dispatches pipeline freely, so the
    upload of slab i+1 overlaps the kernel on slab i). At most
    ``in_flight`` slabs are unfinished at any moment, bounding device
    memory at ``in_flight`` packed slabs. Counters (``slabs``, ``rows``,
    ``bytes_streamed``) accumulate across runs; they are diagnostics,
    not results.
    """

    def __init__(self, agg_fn, in_flight: int = 2):
        import jax

        if in_flight < 1:
            raise ValueError("in_flight must be >= 1")
        self._agg = agg_fn
        self._in_flight = in_flight
        self._jit = jax.jit(self._slab, static_argnums=1)
        self.slabs = 0
        self.rows = 0
        self.bytes_streamed = 0

    def _slab(self, mat, dtypes_names, rest, valid):
        import jax

        cols = dict(rest)
        for i, (dt, name) in enumerate(dtypes_names):
            cols[name] = jax.lax.bitcast_convert_type(mat[i], np.dtype(dt))
        return self._agg(cols, valid)

    def run(self, chunks) -> list:
        """Stream ``chunks`` (iterable of host-column dicts of
        equal-length arrays) through the device; returns the per-slab
        agg outputs as host values, in chunk order (empty chunks are
        skipped and produce no output)."""
        return [out for out, _ in self.stream((c, None) for c in chunks)]

    def stream(self, pairs):
        """Generator form of :meth:`run`: consume ``(host_cols, aux)``
        pairs, yield ``(agg_output_host, aux)`` lazily as slabs retire —
        the caller holds at most ``in_flight`` auxes alive, never the
        whole stream (the larger-than-memory query path rides this).
        Empty chunks are skipped WITH their aux (outputs never
        misalign)."""
        import jax
        import jax.numpy as jnp

        pending: list = []  # (device out, aux)
        for host, aux in pairs:
            if not host:
                continue
            n = len(next(iter(host.values())))
            if n == 0:
                continue
            cap = _bucket(n)
            four = sorted(
                k for k, v in host.items()
                if v.ndim == 1 and v.dtype.itemsize == 4
            )
            rest_names = sorted(set(host) - set(four))
            # zero rows when no 4-byte planes ride: never ship (or count
            # in bytes_streamed) an uninitialized placeholder row
            mat = np.empty((len(four), cap), np.uint32)
            mat[:, n:] = 0
            for i, k in enumerate(four):
                mat[i, :n] = np.ascontiguousarray(host[k]).view(np.uint32)
            rest = {}
            for k in rest_names:
                buf = np.empty((cap,) + host[k].shape[1:], host[k].dtype)
                buf[:n] = host[k]
                buf[n:] = 0
                rest[k] = jnp.asarray(buf)
            valid = np.zeros(cap, bool)
            valid[:n] = True
            # dtype/name pairs are a STATIC argument: one executable per
            # (schema, bucket) pair, regardless of chunk count
            out = self._jit(
                jnp.asarray(mat),
                tuple((str(host[k].dtype), k) for k in four),
                rest,
                jnp.asarray(valid),
            )
            self.slabs += 1
            self.rows += n
            self.bytes_streamed += mat.nbytes + cap + sum(
                int(v.nbytes) for v in rest.values()
            )
            pending.append((out, aux))
            if len(pending) >= self._in_flight:
                # bound in-flight slabs (and so device memory): retire
                # the oldest before dispatching more
                o, a = pending.pop(0)
                yield jax.device_get(o), a
        for o, a in pending:
            yield jax.device_get(o), a


class StreamedDeviceScan:
    """Partition-streaming device scan over a partitioned store type.

    Serves the same fused-mask counts/queries the resident DeviceIndex
    does, but for datasets that exceed HBM: manifest pruning picks the
    partitions a query can touch, and only those stream through the
    slab pump. Parity contract: ``count``/``query`` match the store's
    host path exactly, at every ``io`` worker count
    (tests/test_oocscan.py, tests/test_prefetch.py). Per-filter slab
    kernels are cached (bounded LRU), so repeated queries recompile
    nothing and long-lived servers issuing many distinct filters cannot
    grow the cache without limit."""

    #: compiled-stream LRU bound: (filter, kind) entries kept hot; a
    #: re-queried evicted filter re-jits its tiny agg wrapper, while XLA's
    #: own executable cache still spares the actual kernel compile
    STREAM_CACHE_MAX = 8

    def __init__(
        self,
        store,
        type_name: str,
        slab_rows: "int | None" = None,
        io=None,
    ):
        self.store = store
        self.type_name = type_name
        self.sft = store.get_schema(type_name)
        #: target rows per slab; partitions group into slabs up to this
        self.slab_rows = slab_rows or (1 << 22)
        from geomesa_tpu.locking import checked_lock

        #: host-I/O pipeline: PrefetchConfig, an int worker count, or
        #: None (= the ``io.*`` system properties, resolved per scan)
        self.io = io
        self._streams: OrderedDict = OrderedDict()
        # the LRU's get+move_to_end / insert+evict must be atomic: server
        # threads share one scan object, and a move_to_end racing an
        # eviction raises KeyError on an OrderedDict
        self._streams_lock = checked_lock("oocscan.streams")

    # -- internals ---------------------------------------------------------

    def _parts(self, query):
        plan = self.store.plan(self.type_name, query)
        return plan, self.store._pruned_parts(self.type_name, plan)

    def _slab_groups(self, parts):
        """Group partitions into slab_rows-sized chunks (fewer, larger
        uploads) by the MANIFEST row counts — no reads needed, so the
        chunk plan exists before the pipeline starts and grouping is
        identical at every worker count (count == file rows by the
        manifest contract)."""
        group: list = []
        rows = 0
        for p in parts:
            group.append(p)
            rows += int(p.count)
            if rows >= self.slab_rows:
                yield group
                group, rows = [], 0
        if group:
            yield group

    def _load_group(self, group, read, names, want_batch: bool):
        """One pipeline work item: read + decode the group's partition
        files, concat, stage the device planes host-side. Returns the
        explicit ``(host_cols, source_batch)`` pair — chunk and batch
        travel together, so the query path's hit gather can never pair a
        mask with the wrong rows. The count path sets
        ``want_batch=False`` and gets ``(host_cols, None)``: holding the
        decoded rows in the queue when only the staged planes are
        consumed would double the chunk's memory (and budget charge) for
        nothing."""
        from geomesa_tpu import metrics
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.ops.scan import stage_columns_host
        from geomesa_tpu.tracing import span

        batches = [read(p) for p in group]
        batch = (
            batches[0] if len(batches) == 1 else FeatureBatch.concat(batches)
        )
        with span("store.stage", rows=len(batch), parts=len(group)), \
                metrics.io_stage_seconds.time():
            cols = stage_columns_host(batch, names)
        return cols, (batch if want_batch else None)

    def _pairs(self, parts, names, want_batch: bool = True):
        """Yield ``(host_cols, source_batch)`` in deterministic partition
        order through the prefetch pipeline. Workers use PER-READ
        locking (same consistency window as the serial scan), so a
        multi-minute streamed scan never pins the store lock and other
        threads' queries interleave between partition reads; against an
        FS store the per-read guard is the shared flock alone
        (_read_partition_prefetch), which is concurrent across threads —
        reads, decode and staging all overlap. Streamed partitions are
        never pinned in the store cache — accumulating the dataset in
        host RAM is the thing this scan exists to avoid. The queue byte
        budget charges BOTH halves of a pair (staged planes and source
        batch): that is what a queued chunk actually holds alive."""
        from geomesa_tpu.store.prefetch import (
            PrefetchConfig,
            batch_nbytes,
            prefetch_map,
        )

        cfg = PrefetchConfig.coerce(self.io)
        held = getattr(self.store, "scan_lock_held", None)
        if held is not None and held():
            # the CALLING thread holds the store's exclusive lock (a
            # maintenance job scanning in-place): worker threads can
            # neither see its thread-local lock depth nor take a shared
            # flock against our own exclusive one — degrade to in-line
            # serial reads through the depth-aware locked reader
            cfg = PrefetchConfig(
                workers=0, depth=cfg.depth, byte_budget=cfg.byte_budget
            )
            prefetch_read = None
        else:
            prefetch_read = getattr(
                self.store, "_read_partition_prefetch", None
            )
        if cfg.workers > 0 and prefetch_read is not None:
            read = lambda p: prefetch_read(self.type_name, p)  # noqa: E731
        else:
            read = lambda p: self.store._read_partition(  # noqa: E731
                self.type_name, p, cache=False
            )
        size_of = lambda pair: (  # noqa: E731
            sum(int(v.nbytes) for v in pair[0].values())
            + (batch_nbytes(pair[1]) if pair[1] is not None else 0)
        )
        yield from prefetch_map(
            lambda g: self._load_group(g, read, names, want_batch),
            self._slab_groups(parts),
            cfg,
            size_of=size_of,
        )

    def _stream(self, plan, kind: str) -> SlabStream:
        import jax.numpy as jnp

        compiled = plan.compiled
        key = (repr(plan.filter), kind)
        with self._streams_lock:
            stream = self._streams.get(key)
            if stream is not None:
                self._streams.move_to_end(key)  # LRU touch
                return stream
        if kind == "count":
            # int32 per-slab is safe (a slab never exceeds 2^31
            # rows); totals accumulate in python ints
            def agg(cols, valid):
                return jnp.sum(
                    compiled.device_fn(cols) & valid, dtype=jnp.int32
                )

        else:  # mask

            def agg(cols, valid):
                return compiled.device_fn(cols) & valid

        stream = SlabStream(agg)
        with self._streams_lock:
            # a racing thread may have built the same stream: keep the
            # first-installed one so both callers share its counters
            stream = self._streams.setdefault(key, stream)
            self._streams.move_to_end(key)
            while len(self._streams) > self.STREAM_CACHE_MAX:
                self._streams.popitem(last=False)  # evict least-recent
        return stream

    # -- public surface ----------------------------------------------------

    def count(self, query) -> int:
        """Streamed fused count. Filters with host-only predicates fall
        back to the store's own (streaming, host) scan."""
        from geomesa_tpu.tracing import span

        plan, parts = self._parts(query)
        compiled = plan.compiled
        if not compiled.device_cols or not compiled.fully_on_device:
            return len(self.store.query(self.type_name, query).batch)
        with span(
            "oocscan.count", type=self.type_name, parts=len(parts)
        ):
            outs = self._stream(plan, "count").stream(
                self._pairs(parts, compiled.device_cols, want_batch=False)
            )
            return int(sum(int(o) for o, _ in outs))

    def query(self, query):
        """Streamed fused scan returning the hit FeatureBatch: device
        masks per slab, hits gathered host-side AS SLABS RETIRE (via
        SlabStream.stream) — host memory holds the hits plus the
        in-flight slabs' source batches, never the dataset. The pipeline
        delivers each chunk WITH its source batch as one tuple, so mask
        and rows cannot skew even when the prefetcher runs chunks ahead.
        """
        from geomesa_tpu.tracing import span

        plan, parts = self._parts(query)
        compiled = plan.compiled
        if not compiled.device_cols:
            return self.store.query(self.type_name, query).batch
        with span("oocscan.query", type=self.type_name, parts=len(parts)):
            return self._query_streamed(plan, parts)

    def _query_streamed(self, plan, parts):
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.query.runner import _post_process

        compiled = plan.compiled
        pairs = self._pairs(parts, compiled.device_cols)
        hits: list = []
        for mask, batch in self._stream(plan, "mask").stream(pairs):
            m = np.asarray(mask)[: len(batch)]
            idx = np.nonzero(m)[0]
            if len(idx) and not compiled.fully_on_device:
                keep = compiled.residual_mask(batch.take(idx))
                idx = idx[keep]
            if len(idx):
                hits.append(batch.take(idx))
        if not hits:
            out = FeatureBatch.from_columns(
                self.sft, {a.name: [] for a in self.sft.attributes}
            )
        else:
            out = hits[0] if len(hits) == 1 else FeatureBatch.concat(hits)
        return _post_process(out, plan)
