"""Out-of-core streamed device scan: datasets larger than HBM.

Ref role: the reference's scans are inherently streaming — Accumulo
iterators stream tablets through the scan servers and nothing ever
requires the dataset to fit anywhere (BatchScanPlan, SURVEY section 3.1
[UNVERIFIED - empty reference mount]). The resident ``DeviceIndex`` is
the opposite trade: every scanned column pinned in HBM. This module
fills the gap between them: partitions stream through a DOUBLE-BUFFERED
device slab, the H2D upload of slab i+1 overlapping the fused scan
kernel on slab i (jax dispatch is async; the one sync point is the final
fetch), with the planner's zrange partition pruning deciding what
streams at all. Peak device memory is a couple of slabs — dataset size
is bounded by disk, not HBM.

Two layers:

- :class:`SlabStream` — the pump. Feed it host column chunks and a
  per-slab aggregation; it keeps a bounded number of slabs in flight
  and returns the per-slab results. Slab shapes pad to power-of-two
  row buckets so the jit executable set stays bounded; every 4-byte
  plane of a slab rides ONE packed uint32 upload (the staging transfer
  discipline from device_cache — per-plane uploads pay per-transfer
  latency for nothing).
- :class:`StreamedDeviceScan` — the store integration. Plans a query,
  prunes partitions by the manifest, streams the survivors from the
  store's partition files, and counts (or collects) with the SAME
  compiled fused mask the resident path uses.

    scan = StreamedDeviceScan(store, "gdelt")
    n = scan.count("BBOX(geom, -10, 35, 30, 60) AND dtg DURING ...")

The HOST side of the stream is pipelined (store/prefetch.py): slab
chunks are grouped by the manifest's partition row counts, then read +
Arrow-decoded + column-staged on worker threads with bounded read-ahead,
delivered as explicit ``(host_cols, source_batch)`` pairs in
deterministic partition order — host decode of chunk i+k overlaps both
the disk and the device kernel on slab i. ``io=`` tunes it
(PrefetchConfig / worker count int / None = the ``io.*`` system
properties); ``io=0`` is the serial baseline. Peak host memory is the
in-flight chunks (read-ahead depth, byte-budgeted) — never the dataset.

Chunk pruning (ISSUE 6): v2 partitions carry per-chunk statistics
(store/chunkstats.py), so pruning happens one level below the manifest's
partition prune — chunks whose Z key span misses every planned range (or
whose bbox/time range misses the query bounds) are dropped BEFORE
read/decode, and the surviving chunks read as selective parquet row
groups (pruned chunks' bytes never leave the disk). ``count`` goes
further: bbox+time filters answer interior chunks straight from the
manifest pre-aggregates and stream only boundary chunks
(store/pushdown.py has the classification contract).

Durability interplay (ISSUE 3): the partition reads beneath a streamed
scan ride the store's crash-consistent read path — transient I/O errors
retry on the workers with bounded backoff (``io.retries`` x
``io.backoff.ms``), ``store.verify=always`` checksums every file before
decode, and a corrupt partition raises a loud per-partition
PartitionCorruptError out of the scan instead of streaming silent
garbage through the slab pump (scans pruned away from it still serve).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

__all__ = ["SlabStream", "StreamedDeviceScan"]


def _bucket(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class SlabStream:
    """Double-buffered device slab pump.

    ``agg_fn(cols, valid) -> pytree of device values`` runs jitted once
    per slab; :meth:`run` feeds it host chunks and returns the per-slab
    outputs (fetched at the end — dispatches pipeline freely, so the
    upload of slab i+1 overlaps the kernel on slab i). At most
    ``in_flight`` slabs are unfinished at any moment, bounding device
    memory at ``in_flight`` packed slabs. Counters (``slabs``, ``rows``,
    ``bytes_streamed``) accumulate across runs; they are diagnostics,
    not results.
    """

    def __init__(self, agg_fn, in_flight: int = 2):
        import jax

        if in_flight < 1:
            raise ValueError("in_flight must be >= 1")
        self._agg = agg_fn
        self._in_flight = in_flight
        self._jit = jax.jit(self._slab, static_argnums=1)
        self.slabs = 0
        self.rows = 0
        self.bytes_streamed = 0

    def _slab(self, mat, dtypes_names, rest, valid):
        import jax

        cols = dict(rest)
        for i, (dt, name) in enumerate(dtypes_names):
            cols[name] = jax.lax.bitcast_convert_type(mat[i], np.dtype(dt))
        return self._agg(cols, valid)

    def run(self, chunks) -> list:
        """Stream ``chunks`` (iterable of host-column dicts of
        equal-length arrays) through the device; returns the per-slab
        agg outputs as host values, in chunk order (empty chunks are
        skipped and produce no output)."""
        return [out for out, _ in self.stream((c, None) for c in chunks)]

    def stream(self, pairs):
        """Generator form of :meth:`run`: consume ``(host_cols, aux)``
        pairs, yield ``(agg_output_host, aux)`` lazily as slabs retire —
        the caller holds at most ``in_flight`` auxes alive, never the
        whole stream (the larger-than-memory query path rides this).
        Empty chunks are skipped WITH their aux (outputs never
        misalign)."""
        import jax
        import jax.numpy as jnp

        pending: list = []  # (device out, aux)
        for host, aux in pairs:
            if not host:
                continue
            n = len(next(iter(host.values())))
            if n == 0:
                continue
            cap = _bucket(n)
            four = sorted(
                k for k, v in host.items()
                if v.ndim == 1 and v.dtype.itemsize == 4
            )
            rest_names = sorted(set(host) - set(four))
            # zero rows when no 4-byte planes ride: never ship (or count
            # in bytes_streamed) an uninitialized placeholder row
            mat = np.empty((len(four), cap), np.uint32)
            mat[:, n:] = 0
            for i, k in enumerate(four):
                mat[i, :n] = np.ascontiguousarray(host[k]).view(np.uint32)
            from geomesa_tpu import ledger

            # the slab launch (and its staging converts) compile under
            # the streamed-scan family — scoped per slab, NOT across the
            # yield below (the consumer's own compiles are its own)
            with ledger.compile_scope("store.scan"):
                rest = {}
                for k in rest_names:
                    buf = np.empty(
                        (cap,) + host[k].shape[1:], host[k].dtype
                    )
                    buf[:n] = host[k]
                    buf[n:] = 0
                    rest[k] = jnp.asarray(buf)
                valid = np.zeros(cap, bool)
                valid[:n] = True
                # dtype/name pairs are a STATIC argument: one executable
                # per (schema, bucket) pair, regardless of chunk count
                out = self._jit(
                    jnp.asarray(mat),
                    tuple((str(host[k].dtype), k) for k in four),
                    rest,
                    jnp.asarray(valid),
                )
            self.slabs += 1
            self.rows += n
            self.bytes_streamed += mat.nbytes + cap + sum(
                int(v.nbytes) for v in rest.values()
            )
            pending.append((out, aux))
            if len(pending) >= self._in_flight:
                # bound in-flight slabs (and so device memory): retire
                # the oldest before dispatching more
                o, a = pending.pop(0)
                yield jax.device_get(o), a
        for o, a in pending:
            yield jax.device_get(o), a


class StreamedDeviceScan:
    """Partition-streaming device scan over a partitioned store type.

    Serves the same fused-mask counts/queries the resident DeviceIndex
    does, but for datasets that exceed HBM: manifest pruning picks the
    partitions a query can touch, and only those stream through the
    slab pump. Parity contract: ``count``/``query`` match the store's
    host path exactly, at every ``io`` worker count
    (tests/test_oocscan.py, tests/test_prefetch.py). Per-filter slab
    kernels are cached (bounded LRU), so repeated queries recompile
    nothing and long-lived servers issuing many distinct filters cannot
    grow the cache without limit."""

    #: compiled-stream LRU bound: (filter, kind) entries kept hot; a
    #: re-queried evicted filter re-jits its tiny agg wrapper, while XLA's
    #: own executable cache still spares the actual kernel compile
    STREAM_CACHE_MAX = 8

    def __init__(
        self,
        store,
        type_name: str,
        slab_rows: "int | None" = None,
        io=None,
    ):
        self.store = store
        self.type_name = type_name
        self.sft = store.get_schema(type_name)
        #: target rows per slab; partitions group into slabs up to this
        self.slab_rows = slab_rows or (1 << 22)
        from geomesa_tpu.locking import checked_lock

        #: host-I/O pipeline: PrefetchConfig, an int worker count, or
        #: None (= the ``io.*`` system properties, resolved per scan)
        self.io = io
        self._streams: OrderedDict = OrderedDict()
        # the LRU's get+move_to_end / insert+evict must be atomic: server
        # threads share one scan object, and a move_to_end racing an
        # eviction raises KeyError on an OrderedDict
        self._streams_lock = checked_lock("oocscan.streams")

    # -- internals ---------------------------------------------------------

    def _parts(self, query):
        plan = self.store.plan(self.type_name, query)
        return plan, self.store._pruned_parts(self.type_name, plan)

    def _chunk_plan(self, plan, parts):
        """Sub-partition pruning (partition format v2): ``(partition,
        chunk_sel, rows)`` work items where ``chunk_sel`` lists the
        chunks whose key span overlaps a planned Z range AND whose
        bbox/time range meets the query bounds — everything else is
        skipped BEFORE read/decode (pruned parquet row groups never
        leave the disk). ``chunk_sel=None`` means the whole file (v1
        partitions, pruning disabled, or nothing pruned). Sound exactly
        like partition pruning, one level finer: the planner's ranges
        cover every key a filter-matching row can have.

        Returns ``(items, prune_stats)``; PURE — the caller records
        ``prune_stats`` via :meth:`_record_prune` only when it actually
        EXECUTES the plan (a fallback that re-reads everything must not
        report chunks as skipped)."""
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.store import chunkstats as cks

        prune = bool(sys_prop("store.chunk.prune"))
        can_prune = plan.ranges is not None or (
            not plan.geom_bounds.unbounded or not plan.time_bounds.unbounded
        )
        items: list = []
        skipped_chunks = 0
        skipped_bytes = 0
        read_chunks = 0
        for p in parts:
            cs = p.chunks
            if not prune or not can_prune or cs is None or len(cs) <= 1:
                items.append((p, None, int(p.count)))
                continue
            keep = np.ones(len(cs), dtype=bool)
            if plan.ranges is not None:
                keep &= cks.chunks_overlapping(cs, plan.ranges)
            envs = (
                None
                if plan.geom_bounds.unbounded
                else [env for env, _ in plan.geom_bounds.values]
            )
            ivals = (
                None
                if plan.time_bounds.unbounded
                else list(plan.time_bounds.values)
            )
            if envs is not None or ivals is not None:
                keep &= cks.classify(cs, envs, ivals) != cks.DISJOINT
            sel = np.nonzero(keep)[0]
            read_chunks += len(sel)
            skipped_chunks += len(cs) - len(sel)
            if cs.nbytes is not None and len(sel) < len(cs):
                skipped_bytes += int(cs.nbytes[~keep].sum())
            if len(sel) == len(cs):
                items.append((p, None, int(p.count)))
            elif len(sel):
                items.append((
                    p,
                    [int(i) for i in sel],
                    int(cs.rows[sel].sum()),
                ))
            # else: every chunk pruned -- the partition drops entirely
        return items, (read_chunks, skipped_chunks, skipped_bytes)

    @staticmethod
    def _record_prune(prune_stats) -> None:
        from geomesa_tpu import metrics

        read_chunks, skipped_chunks, skipped_bytes = prune_stats
        if skipped_chunks:
            metrics.store_chunks_read.inc(read_chunks)
            metrics.store_chunks_skipped.inc(skipped_chunks)
            if skipped_bytes:
                metrics.store_chunk_bytes_skipped.inc(skipped_bytes)

    def _slab_groups(self, items):
        """Group ``(partition, chunk_sel, rows)`` work items into
        slab_rows-sized chunks (fewer, larger uploads) by the MANIFEST
        row counts — no reads needed, so the chunk plan exists before
        the pipeline starts and grouping is identical at every worker
        count (count == file rows by the manifest contract). Bare
        PartitionMeta items coerce to whole-file work (chunk_sel
        None)."""
        group: list = []
        rows = 0
        for item in items:
            if not isinstance(item, tuple):
                item = (item, None, int(item.count))
            group.append(item)
            rows += int(item[2])
            if rows >= self.slab_rows:
                yield group
                group, rows = [], 0
        if group:
            yield group

    def _load_group(self, group, read, names, want_batch: bool):
        """One pipeline work item: read + decode the group's partition
        files, concat, stage the device planes host-side. Returns the
        explicit ``(host_cols, source_batch)`` pair — chunk and batch
        travel together, so the query path's hit gather can never pair a
        mask with the wrong rows. The count path sets
        ``want_batch=False`` and gets ``(host_cols, None)``: holding the
        decoded rows in the queue when only the staged planes are
        consumed would double the chunk's memory (and budget charge) for
        nothing."""
        from geomesa_tpu import metrics
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.ops.scan import stage_columns_host
        from geomesa_tpu.tracing import span

        batches = [read(p, sel) for p, sel, _rows in group]
        batch = (
            batches[0] if len(batches) == 1 else FeatureBatch.concat(batches)
        )
        import time as _time

        from geomesa_tpu import ledger

        t_stage = _time.perf_counter()
        with span("store.stage", rows=len(batch), parts=len(group)), \
                metrics.io_stage_seconds.time():
            cols = stage_columns_host(batch, names)
        ledger.charge("stage_seconds", _time.perf_counter() - t_stage)
        try:
            ledger.charge(
                "stage_bytes",
                sum(int(c.nbytes) for c in cols.values()
                    if hasattr(c, "nbytes")),
            )
        except Exception:  # lint: disable=GT011(metering fallback: a plane without nbytes skips the byte charge, the scan itself is unaffected)  # staged planes without nbytes: skip the charge
            pass
        return cols, (batch if want_batch else None)

    def _pairs(self, items, names, want_batch: bool = True):
        """Yield ``(host_cols, source_batch)`` in deterministic partition
        order through the prefetch pipeline. Workers use PER-READ
        locking (same consistency window as the serial scan), so a
        multi-minute streamed scan never pins the store lock and other
        threads' queries interleave between partition reads; against an
        FS store the per-read guard is the shared flock alone
        (_read_partition_prefetch), which is concurrent across threads —
        reads, decode and staging all overlap. Streamed partitions are
        never pinned in the store cache — accumulating the dataset in
        host RAM is the thing this scan exists to avoid. The queue byte
        budget charges BOTH halves of a pair (staged planes and source
        batch): that is what a queued chunk actually holds alive."""
        from geomesa_tpu.store.prefetch import (
            PrefetchConfig,
            batch_nbytes,
            prefetch_map,
        )

        cfg = PrefetchConfig.coerce(self.io)
        held = getattr(self.store, "scan_lock_held", None)
        if held is not None and held():
            # the CALLING thread holds the store's exclusive lock (a
            # maintenance job scanning in-place): worker threads can
            # neither see its thread-local lock depth nor take a shared
            # flock against our own exclusive one — degrade to in-line
            # serial reads through the depth-aware locked reader
            cfg = PrefetchConfig(
                workers=0, depth=cfg.depth, byte_budget=cfg.byte_budget
            )
            prefetch_read = None
        else:
            prefetch_read = getattr(
                self.store, "_read_partition_prefetch", None
            )
        # chunk_sel rides as a kwarg ONLY when a selection exists: the
        # whole-file read keeps the legacy call shape (stores and test
        # doubles predating chunk_sel stay compatible)
        if cfg.workers > 0 and prefetch_read is not None:
            read = lambda p, sel: (  # noqa: E731
                prefetch_read(self.type_name, p, chunk_sel=sel)
                if sel is not None
                else prefetch_read(self.type_name, p)
            )
        else:
            read = lambda p, sel: (  # noqa: E731
                self.store._read_partition(
                    self.type_name, p, cache=False, chunk_sel=sel
                )
                if sel is not None
                else self.store._read_partition(
                    self.type_name, p, cache=False
                )
            )
        size_of = lambda pair: (  # noqa: E731
            sum(int(v.nbytes) for v in pair[0].values())
            + (batch_nbytes(pair[1]) if pair[1] is not None else 0)
        )
        yield from prefetch_map(
            lambda g: self._load_group(g, read, names, want_batch),
            self._slab_groups(items),
            cfg,
            size_of=size_of,
        )

    def _stream(self, plan, kind: str) -> SlabStream:
        import jax.numpy as jnp

        compiled = plan.compiled
        key = (repr(plan.filter), kind)
        with self._streams_lock:
            stream = self._streams.get(key)
            if stream is not None:
                self._streams.move_to_end(key)  # LRU touch
                return stream
        if kind == "count":
            # int32 per-slab is safe (a slab never exceeds 2^31
            # rows); totals accumulate in python ints
            def agg(cols, valid):
                return jnp.sum(
                    compiled.device_fn(cols) & valid, dtype=jnp.int32
                )

        else:  # mask

            def agg(cols, valid):
                return compiled.device_fn(cols) & valid

        stream = SlabStream(agg)
        with self._streams_lock:
            # a racing thread may have built the same stream: keep the
            # first-installed one so both callers share its counters
            stream = self._streams.setdefault(key, stream)
            self._streams.move_to_end(key)
            while len(self._streams) > self.STREAM_CACHE_MAX:
                self._streams.popitem(last=False)  # evict least-recent
        return stream

    # -- public surface ----------------------------------------------------

    def _agg_split(self, plan, parts):
        """Count-pushdown split over the chunk stats: ``(base, items,
        pushed)`` where ``base`` rows come straight from interior-chunk
        summaries (never read) and ``items`` are the boundary work items
        that still stream through the device. Falls back to the plain
        chunk plan (base 0, pushed False) when the filter or the
        partitions cannot support pushdown — including any partition
        holding visibility-labeled rows: the device count path ignores
        labels by contract, but the NON-device fallback is store.query
        (which hides them), and a manifest summary must never widen what
        that fallback would return. For an agg_bounds-shaped (bbox+time)
        filter the device mask IS the exact predicate, so summary +
        refined counts compose bit-identically with the full streamed
        count. Callers record the ``geomesa_agg_pushdown_*`` metrics
        when (and only when) they actually USE the split."""
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.store import chunkstats as cks

        q = plan.query
        eligible = (
            plan.agg_bounds is not None
            and bool(sys_prop("store.chunk.pushdown"))
            and q.hints.get("agg.pushdown") is not False
            and q.max_features is None  # incl. interceptor-applied caps
            and all(
                p.chunks is not None and not p.chunks.has_vis
                for p in parts
            )
        )
        if not eligible:
            items, prune_stats = self._chunk_plan(plan, parts)
            return 0, items, False, prune_stats
        from geomesa_tpu.store.pushdown import _boundary_sel

        envs, ivals = plan.agg_bounds
        base = 0
        items: list = []
        for p in parts:
            cs = p.chunks
            klass = cks.classify(cs, envs, ivals)
            base += int(cs.rows[klass == cks.INTERIOR].sum())
            # boundary selection + Z-range refinement: the one shared
            # rule (store/pushdown._boundary_sel) — the two count paths
            # must never diverge on which chunks row-refine
            sel = _boundary_sel(plan, cs, klass)
            if len(sel) == len(cs):
                items.append((p, None, int(p.count)))
            elif len(sel):
                items.append(
                    (p, [int(i) for i in sel], int(cs.rows[sel].sum()))
                )
        return base, items, True, None

    @staticmethod
    def _record_pushdown(base: int, items) -> None:
        from geomesa_tpu import metrics

        metrics.agg_pushdown_queries.inc(kind="count")
        metrics.agg_pushdown_rows.inc(base)
        refined = sum(
            len(sel) for _p, sel, _r in items if sel is not None
        )
        if refined:
            metrics.agg_pushdown_chunks_refined.inc(refined)

    @staticmethod
    def _degrade_or_raise(e: BaseException) -> None:
        """Degradation rung for streamed-scan faults: a failed or stuck
        device launch (incl. the ``fail.device.launch`` injection) lets
        the caller retry the whole question through the store's HOST
        scan — exact, just slower; the result is stamped degraded. FATAL
        faults (bad filters, programming errors) and degrade-off
        propagate. The host fallback composes with the store's own
        partition-level degradation (an unreachable partition is skipped
        and stamped there). The stamped reason distinguishes store/disk
        faults that bubbled out of the stream from device faults — a
        corrupt partition labeled ``device-launch-failed`` would send
        the operator to the accelerator for a disk problem."""
        from geomesa_tpu import resilience
        from geomesa_tpu.store.fs import PartitionCorruptError

        if (
            not resilience.degrade_allowed()
            or resilience.classify(e) == resilience.FATAL
        ):
            raise e
        if resilience.is_oom(e) or (
            getattr(e, "name", None) == "fail.stage.oom"
        ):
            reason = "device-oom"
        elif isinstance(
            e,
            (PartitionCorruptError, resilience.PartitionUnavailableError),
        ) or (
            # OSError = read/disk fault (FailpointError rides OSError;
            # only the device-launch injection is a DEVICE fault)
            isinstance(e, OSError)
            and getattr(e, "name", None) != "fail.device.launch"
        ):
            reason = "partition-unavailable"
        else:
            reason = "device-launch-failed"
        resilience.note_degraded(reason)

    def count(self, query) -> int:
        """Streamed fused count. Filters with host-only predicates fall
        back to the store's own (streaming, host) scan. bbox+time
        filters over v2 partitions short-circuit through the chunk
        pre-aggregates: interior chunks are answered from the manifest
        and only boundary chunks stream through the device — a fully
        pre-aggregated answer (e.g. INCLUDE) reads no file at all."""
        from geomesa_tpu.tracing import span

        plan, parts = self._parts(query)
        compiled = plan.compiled
        device_ok = bool(
            compiled.device_cols and compiled.fully_on_device
        )
        if not device_ok:
            # no usable device predicate; a PURE summary answer (every
            # surviving chunk interior) still needs no rows at all
            base, items, pushed, _prune = self._agg_split(plan, parts)
            if pushed and not items:
                self._record_pushdown(base, items)
                return int(base)
            # boundary chunks would need the (absent) device mask: the
            # store's host scan answers instead — the split (and its
            # prune accounting) is discarded, so neither may be recorded
            return len(self.store.query(self.type_name, query).batch)
        with span(
            "oocscan.count", type=self.type_name, parts=len(parts)
        ) as sp:
            base, items, pushed, prune_stats = self._agg_split(plan, parts)
            try:
                outs = self._stream(plan, "count").stream(
                    self._pairs(
                        items, compiled.device_cols, want_batch=False
                    )
                )
                total = base + int(sum(int(o) for o, _ in outs))
            except Exception as e:
                self._degrade_or_raise(e)
                # the cheapest host rung that COUNTS without
                # materializing the row set (we are degrading under
                # memory pressure): the store's pushdown-served count
                if hasattr(self.store, "count"):
                    return int(self.store.count(self.type_name, query))
                return len(self.store.query(self.type_name, query).batch)
            # metrics only after the split/plan actually answered — a
            # degraded fallback re-reads everything and must not report
            # chunks as skipped or rows as pre-aggregated
            if pushed:
                self._record_pushdown(base, items)
            elif prune_stats is not None:
                self._record_prune(prune_stats)
            sp.set(rows_preagg=int(base))
            return total

    def query(self, query):
        """Streamed fused scan returning the hit FeatureBatch: device
        masks per slab, hits gathered host-side AS SLABS RETIRE (via
        SlabStream.stream) — host memory holds the hits plus the
        in-flight slabs' source batches, never the dataset. The pipeline
        delivers each chunk WITH its source batch as one tuple, so mask
        and rows cannot skew even when the prefetcher runs chunks ahead.
        """
        from geomesa_tpu.tracing import span

        plan, parts = self._parts(query)
        compiled = plan.compiled
        if not compiled.device_cols:
            return self.store.query(self.type_name, query).batch
        with span("oocscan.query", type=self.type_name, parts=len(parts)):
            try:
                return self._query_streamed(plan, parts)
            except Exception as e:
                self._degrade_or_raise(e)
                return self.store.query(self.type_name, query).batch

    def _hit_batches(self, plan, parts):
        """Per-slab hit batches as slabs retire (row-local refinement
        applied; NO cross-batch post-processing — callers own
        visibility/projection/sort/limit semantics)."""
        compiled = plan.compiled
        # chunk-level pruning: non-intersecting chunks never read/decode
        # (the mask path still applies the exact filter to what remains,
        # so pruning only ever removes provably-empty work)
        items, prune_stats = self._chunk_plan(plan, parts)
        self._record_prune(prune_stats)
        pairs = self._pairs(items, compiled.device_cols)
        for mask, batch in self._stream(plan, "mask").stream(pairs):
            m = np.asarray(mask)[: len(batch)]
            idx = np.nonzero(m)[0]
            if len(idx) and not compiled.fully_on_device:
                keep = compiled.residual_mask(batch.take(idx))
                idx = idx[keep]
            if len(idx):
                yield batch.take(idx)

    def _query_streamed(self, plan, parts):
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.query.runner import _post_process

        hits = list(self._hit_batches(plan, parts))
        if not hits:
            out = FeatureBatch.from_columns(
                self.sft, {a.name: [] for a in self.sft.attributes}
            )
        else:
            out = hits[0] if len(hits) == 1 else FeatureBatch.concat(hits)
        return _post_process(out, plan)

    def query_batches(self, query):
        """Out-of-core RESULT streaming (the result-plane integration,
        results/stream.py): yield hit batches as slabs retire, so a
        larger-than-HBM scan feeds the chunked Arrow/BIN encoders batch
        by batch and neither the dataset nor the result set is ever
        materialized at once. Row-local post-processing (visibility,
        projection) applies per batch; cross-batch sort/limit do NOT —
        the same contract as the fs store's ``query_partitions``. The
        store-path fallback (non-device-expressible filter, degradable
        stream fault) fires only BEFORE the first yield; a mid-stream
        fault after rows went out raises instead of duplicating them."""
        import dataclasses

        from geomesa_tpu.query.runner import _post_process
        from geomesa_tpu.tracing import span

        plan, parts = self._parts(query)
        compiled = plan.compiled
        if not compiled.device_cols:
            b = self.store.query(self.type_name, query).batch
            if len(b):
                yield b
            return
        outer = dataclasses.replace(
            plan,
            query=dataclasses.replace(
                plan.query, sort_by=None, max_features=None
            ),
        )
        with span(
            "oocscan.query_batches", type=self.type_name, parts=len(parts)
        ):
            yielded = False
            try:
                for hit in self._hit_batches(plan, parts):
                    out = _post_process(hit, outer)
                    if len(out):
                        yielded = True
                        yield out
            except Exception as e:
                if yielded:
                    raise
                self._degrade_or_raise(e)
                b = self.store.query(self.type_name, query).batch
                if len(b):
                    yield b
