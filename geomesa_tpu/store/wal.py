"""Checksummed, segmented write-ahead log for streaming ingest.

Ref role: the commit-log tier every LSM store grows once ingest must be
durable before it is sorted (Accumulo's write-ahead log fronting the
in-memory map; Kafka's segment log as GeoMesa's live-layer transport
[UNVERIFIED - empty reference mount]). The contract here:

- ``append(payload) -> seq`` returns ONLY after the record is written
  (and fsynced when ``store.fsync`` is on — the durability point): a
  returned seq is an acked record and must survive a SIGKILL anywhere.
- Records are length-prefixed and CRC-checksummed. Replay verifies
  every record; a torn tail (a crash mid-append) is truncated at the
  last valid checksum — un-acked bytes vanish, acked bytes never do.
- Segments rotate at ``wal.segment.bytes`` (``wal-<firstseq>.seg``).
  ``truncate_through(seq)`` garbage-collects segments wholly consumed
  by compaction; replay skips already-compacted records via the
  manifest's generation watermark (the caller's job — the log itself
  only orders and persists).

Record layout (little-endian): ``magic u32 | seq u64 | length u32 |
crc32 u32 | payload``, crc computed over seq+length+payload so a record
can neither tear nor be misattributed to another offset.

The ``fail.wal.append`` / ``fail.wal.rotate`` / ``fail.wal.replay``
failpoints bracket each step for the chaos kill matrix.
"""

from __future__ import annotations

import os
import struct
import zlib

from geomesa_tpu.failpoints import fail_point
from geomesa_tpu.locking import checked_lock

__all__ = [
    "WriteAheadLog", "WalCorruption", "pack_record", "RecordParser",
]

_MAGIC = 0x474D5741  # "GMWA"
_HEADER = struct.Struct("<IQII")  # magic, seq, length, crc


class WalCorruption(RuntimeError):
    """A WAL segment failed validation somewhere OTHER than a torn
    tail (an interior record with a bad checksum): replay stops at the
    damage rather than inventing rows past it."""


def _crc(seq: int, payload: bytes) -> int:
    c = zlib.crc32(struct.pack("<QI", seq, len(payload)))
    return zlib.crc32(payload, c) & 0xFFFFFFFF


def pack_record(seq: int, payload: bytes) -> bytes:
    """One record in the on-disk framing. The replication wire format
    IS the segment format (magic/seq/length/crc + payload): the leader
    ships bytes it could have read back, and the follower verifies the
    same checksum replay would — one framing, no translation layer."""
    return _HEADER.pack(_MAGIC, seq, len(payload), _crc(seq, payload)) + payload


class RecordParser:
    """Incremental parser for a shipped record stream (the follower
    side of ``GET /wal/<type>``): ``feed()`` arbitrary byte chunks,
    get back the complete verified records they finish. A checksum or
    framing mismatch raises :class:`WalCorruption` — a replication
    stream has no legitimate torn tail; damage means the transport or
    the leader is lying and the follower must resync, not guess."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> "list[tuple[int, bytes]]":
        self._buf += data
        out: "list[tuple[int, bytes]]" = []
        off = 0
        n = len(self._buf)
        while off + _HEADER.size <= n:
            magic, seq, length, crc = _HEADER.unpack_from(self._buf, off)
            if magic != _MAGIC:
                raise WalCorruption(
                    f"replication stream framing lost at offset {off} "
                    f"(bad magic 0x{magic:08x})"
                )
            end = off + _HEADER.size + length
            if end > n:
                break  # incomplete record — wait for more bytes
            payload = bytes(self._buf[off + _HEADER.size:end])
            if _crc(seq, payload) != crc:
                raise WalCorruption(
                    f"replication stream record seq={seq} failed its "
                    f"checksum"
                )
            out.append((seq, payload))
            off = end
        del self._buf[:off]
        return out

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _seg_name(first_seq: int) -> str:
    return f"wal-{first_seq:016d}.seg"


class WriteAheadLog:
    """One directory of rotating, checksummed log segments.

    Thread-safe: one appender lock orders records (``blocking_ok`` —
    the lock's purpose is exactly to order the blocking writes, same
    discipline as the audit/slow-log appenders)."""

    def __init__(self, directory: str, segment_bytes: "int | None" = None,
                 fsync: "bool | None" = None, readonly: bool = False):
        """``readonly`` opens for INSPECTION only (the CLI's ``wal``
        command): no torn-tail truncation — a live appender's half-
        written record must never be cut out from under its O_APPEND
        fd (the writer would land the rest of the record after the cut,
        corrupting an ACKED region) — and ``append`` refuses."""
        self.dir = directory
        self._segment_bytes = segment_bytes
        self._fsync = fsync
        self._readonly = bool(readonly)
        self._lock = checked_lock("store.wal", blocking_ok=True)
        self._fd = -1
        self._seg_path: "str | None" = None
        self._seg_size = 0
        self._next_seq = 0
        #: sealed segments: path -> last seq recorded in it (active
        #: segment excluded; used by truncate_through)
        self._sealed: "dict[str, int]" = {}
        self.bytes_written = 0
        self.fsyncs = 0
        self.truncations = 0  # torn tails cut during replay
        os.makedirs(directory, exist_ok=True)
        self._scan_segments()

    # -- config ------------------------------------------------------------

    def _seg_bytes(self) -> int:
        if self._segment_bytes is not None:
            return int(self._segment_bytes)
        from geomesa_tpu.conf import sys_prop

        return max(int(sys_prop("wal.segment.bytes")), 1 << 12)

    def _sync_on(self) -> bool:
        if self._fsync is not None:
            return bool(self._fsync)
        from geomesa_tpu.conf import sys_prop

        return bool(sys_prop("store.fsync"))

    # -- segment discovery -------------------------------------------------

    def segments(self) -> "list[str]":
        """Segment paths in seq order (first-seq encoded in the name)."""
        names = sorted(
            n for n in os.listdir(self.dir)
            if n.startswith("wal-") and n.endswith(".seg")
        )
        return [os.path.join(self.dir, n) for n in names]

    def _scan_segments(self) -> None:
        """Derive next_seq and the sealed-segment index from disk (open
        / reopen). Only the LAST segment can have a torn tail; its scan
        truncates it. Interior bad records raise loudly."""
        segs = self.segments()
        self._sealed = {}
        last_seq = -1
        for i, path in enumerate(segs):
            tail_ok = i == len(segs) - 1
            seg_last = -1
            for seq, _ in self._scan_one(path, truncate_tail=tail_ok):
                seg_last = seq
            if seg_last >= 0:
                last_seq = max(last_seq, seg_last)
            if not tail_ok:
                self._sealed[path] = seg_last
        self._next_seq = last_seq + 1
        if segs:
            # append continues into the final segment
            self._seg_path = segs[-1]
            self._seg_size = os.path.getsize(segs[-1])

    def _scan_one(self, path: str, truncate_tail: bool, mutate: bool = True):
        """Yield ``(seq, payload)`` for every valid record of one
        segment. With ``truncate_tail`` a trailing invalid record is cut
        at the last valid offset (counted); without it, damage raises
        :class:`WalCorruption`. ``mutate=False`` (the :meth:`read_from`
        cursor) tolerates a torn tail like readonly mode does — stop at
        the damage, never truncate — so a concurrent reader can walk a
        live appender's log."""
        from geomesa_tpu import metrics

        good = 0
        with open(path, "rb") as fh:
            data = fh.read()
        off = 0
        n = len(data)
        while off < n:
            if off + _HEADER.size > n:
                break  # torn header
            magic, seq, length, crc = _HEADER.unpack_from(data, off)
            if magic != _MAGIC:
                break
            end = off + _HEADER.size + length
            if end > n:
                break  # torn payload
            payload = bytes(data[off + _HEADER.size:end])
            if _crc(seq, payload) != crc:
                break
            yield seq, payload
            off = end
            good = off
        if good < n:
            if not truncate_tail:
                raise WalCorruption(
                    f"WAL segment {path!r} damaged at offset {good} "
                    f"(of {n} bytes) before its tail"
                )
            if self._readonly or not mutate:
                return  # inspect, never mutate (a live appender owns it)
            import logging

            logging.getLogger(__name__).warning(
                "WAL segment %r: torn tail truncated at offset %d "
                "(of %d bytes) — un-acked record dropped", path, good, n,
            )
            with open(path, "r+b") as fh:
                fh.truncate(good)
            if self._sync_on():
                with open(path, "rb") as fh:
                    os.fsync(fh.fileno())
            self.truncations += 1
            metrics.stream_wal_truncations.inc()
            self._seg_size = good if path == self._seg_path else self._seg_size

    # -- append ------------------------------------------------------------

    def append(self, payload: bytes) -> int:
        """Durably append one record; returns its seq. The returned seq
        IS the ack: when ``store.fsync`` is on the record has hit disk
        platters; off, it has hit the OS page cache (the documented
        durability trade, same knob as partition flushes). Transient
        I/O errors retry with the ``resilience`` backoff budget under
        the ``wal`` failure domain."""
        if self._readonly:
            raise RuntimeError("WAL opened readonly (inspection only)")
        with self._lock:
            seq = self._next_seq
            self._append_locked(seq, payload)
            return seq

    def append_at(self, seq: int, payload: bytes) -> int:
        """Durably append one record with a CALLER-ASSIGNED seq: the
        replication follower's apply path. Shipped records keep the
        leader's sequence numbers so the manifest watermark, replay
        idempotence, and promotion ("the WAL position IS the truth")
        stay exact across the whole replica group. ``seq`` must be at
        or past ``next_seq`` — records apply in ship order; a seq the
        follower already holds is the caller's idempotent skip, not an
        append."""
        if self._readonly:
            raise RuntimeError("WAL opened readonly (inspection only)")
        with self._lock:
            if seq < self._next_seq:
                raise ValueError(
                    f"append_at seq {seq} below next_seq "
                    f"{self._next_seq} (already durable here)"
                )
            # advance BEFORE opening so a fresh segment's name encodes
            # the true first seq it will hold
            self._next_seq = seq
            self._append_locked(seq, payload)
            return seq

    def _append_locked(self, seq: int, payload: bytes) -> None:
        """Write + ack one record (caller holds the appender lock and
        has set ``seq == self._next_seq``); advances ``next_seq``."""
        from geomesa_tpu import ledger, metrics, resilience

        rec = _HEADER.pack(
            _MAGIC, seq, len(payload), _crc(seq, payload)
        ) + payload

        def _write():
            # inside the retry closure: an injected (or real)
            # transient failure rides the backoff budget exactly
            # like a flaky disk
            fail_point("fail.wal.append")
            self._rotate_if_needed(len(rec))
            start = self._seg_size
            try:
                self._write_record(rec)
            except BaseException:
                # a partial record must not linger ahead of the
                # retry's full copy — replay stops at the first
                # damage, which would drop the (acked) retry
                if self._fd >= 0:
                    try:
                        os.ftruncate(self._fd, start)
                        self._seg_size = start
                    except OSError:
                        pass
                raise

        resilience.retry_call(_write, domain="wal")
        self._next_seq = seq + 1
        self.bytes_written += len(rec)
        metrics.stream_wal_bytes.inc(len(rec))
        ledger.charge("wal_bytes", len(rec))
        if self._sync_on():
            self.fsyncs += 1
            metrics.stream_wal_fsyncs.inc()
            ledger.charge("wal_fsyncs", 1)

    def _write_record(self, rec: bytes) -> None:
        if self._fd < 0:
            self._open_segment()
        view = memoryview(rec)
        while view:
            view = view[os.write(self._fd, view):]
        if self._sync_on():
            os.fsync(self._fd)
        self._seg_size += len(rec)

    def _open_segment(self) -> None:
        if self._seg_path is None:
            self._seg_path = os.path.join(
                self.dir, _seg_name(self._next_seq)
            )
            self._seg_size = 0
        self._fd = os.open(
            self._seg_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        if self._sync_on():
            _fsync_dir(self.dir)

    def _rotate_if_needed(self, incoming: int) -> None:
        if self._seg_path is None or self._fd < 0:
            return
        if self._seg_size == 0 or self._seg_size + incoming <= self._seg_bytes():
            return
        fail_point("fail.wal.rotate")
        # seal: the previous segment's contents are already durable per
        # record; record its last seq for truncate_through
        os.close(self._fd)
        self._fd = -1
        self._sealed[self._seg_path] = self._next_seq - 1
        self._seg_path = None
        self._open_segment()

    def sync(self) -> None:
        if self._fd >= 0:
            os.fsync(self._fd)
            self.fsyncs += 1

    # -- replay / GC -------------------------------------------------------

    def replay(self, after_seq: int = -1):
        """Yield ``(seq, payload)`` for every durable record with
        ``seq > after_seq``, in order. Torn tails are truncated (see
        ``_scan_one``); the caller treats records at or below its
        manifest watermark as already compacted."""
        segs = self.segments()
        for i, path in enumerate(segs):
            fail_point("fail.wal.replay")
            tail_ok = i == len(segs) - 1
            for seq, payload in self._scan_one(path, truncate_tail=tail_ok):
                if seq > after_seq:
                    yield seq, payload

    def read_from(self, after_seq: int = -1):
        """Readonly streaming cursor: yield ``(seq, payload)`` for every
        durable record with ``seq > after_seq``, in order, and NEVER
        mutate — regardless of whether this instance is the live
        appender or a readonly inspector. A torn tail is simply where
        the stream ends (the next cursor pass picks up the retried
        copy); a segment unlinked mid-walk by ``truncate_through`` is
        skipped (its records are at or below the manifest watermark, so
        every consumer of this cursor already holds them). One cursor
        serves both the CLI ``wal`` command and the leader-side
        replication shipper."""
        segs = self.segments()
        for i, path in enumerate(segs):
            tail_ok = i == len(segs) - 1
            try:
                for seq, payload in self._scan_one(
                    path, truncate_tail=tail_ok, mutate=False
                ):
                    if seq > after_seq:
                        yield seq, payload
            except FileNotFoundError:
                continue  # racing truncate_through

    def first_seq(self) -> int:
        """Lowest seq still on disk, or -1 when the log is empty. The
        leader's ship endpoint uses this to detect a follower asking
        for records already garbage-collected by compaction (it must
        re-provision from a snapshot, not tail)."""
        for seq, _ in self.read_from(-1):
            return seq
        return -1

    def truncate_through(self, seq: int) -> int:
        """Delete sealed segments whose every record is ``<= seq``
        (compacted into a published generation). The active segment is
        never deleted (it may be mid-append). Returns segments
        removed."""
        removed = 0
        with self._lock:
            for path, last in sorted(self._sealed.items()):
                if last <= seq:
                    try:
                        os.unlink(path)
                    except FileNotFoundError:
                        pass
                    del self._sealed[path]
                    removed += 1
            if removed and self._sync_on():
                _fsync_dir(self.dir)
        return removed

    # -- introspection -----------------------------------------------------

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def stats(self) -> dict:
        segs = self.segments()
        nbytes = 0
        live = 0
        for p in segs:
            try:
                nbytes += os.path.getsize(p)
                live += 1
            except FileNotFoundError:
                # racing truncate_through: a just-GC'd segment is not
                # an error a stats scrape should 500 on
                continue
        return {
            "dir": self.dir,
            "segments": live,
            "bytes": int(nbytes),
            "next_seq": self._next_seq,
            "bytes_written": self.bytes_written,
            "fsyncs": self.fsyncs,
            "truncations": self.truncations,
        }

    def close(self) -> None:
        with self._lock:
            if self._fd >= 0:
                try:
                    if self._sync_on():
                        os.fsync(self._fd)  # lint: disable=GT002(the appender lock exists to order blocking WAL writes; blocking_ok=True on the checked lock)
                finally:
                    os.close(self._fd)
                self._fd = -1
