"""Shared age-off sweep (ref: geomesa-accumulo AgeOffIterator, run as a
sweep rather than a compaction hook [UNVERIFIED - empty reference mount]).

One implementation for every store: query features strictly older than the
cutoff through the store's own (internal, guard-exempt) query path, then
delete them by id.
"""

from __future__ import annotations

from geomesa_tpu.filter import ast
from geomesa_tpu.query.plan import internal_query


def age_off(store, type_name: str, sft, before_ms: int) -> int:
    """Remove features with ``dtg < before_ms``; returns the count removed."""
    dtg = sft.dtg_field
    if dtg is None:
        raise ValueError(f"{type_name!r} has no Date field")
    old = store.query(type_name, internal_query(ast.Compare("<", dtg, before_ms)))
    return store.delete(type_name, list(old.batch.fids))
