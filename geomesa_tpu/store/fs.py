"""Parquet/ORC filesystem DataStore.

The geomesa-fs analog (ref: geomesa-fs .../FileSystemDataStore,
storage/api/PartitionScheme, parquet/ParquetFileSystemStorage and
orc/OrcFileSystemStorage [UNVERIFIED - empty reference mount]): data lives
as sorted Parquet (or ORC) partition files plus a JSON manifest; queries
prune partitions by the manifest's key bounds (the partition-scheme prune +
parquet min/max pushdown, rolled together) and device-scan only surviving
files.

Layout under ``root/<type_name>/``:

- ``schema.json``   -- SFT spec + primary index + partition metadata
- ``schema.json.gen`` -- tiny staleness sidecar (the manifest generation)
- ``part-<gen>-NNNNN.parquet`` (or ``.orc``) -- sorted partition files,
  generation-scoped (legacy ``part-NNNNN.*`` names still read)

Durable state is exactly this directory (the reference's "source of truth
stays on the object store" elasticity model, SURVEY.md section 5): a store
can be reopened from disk alone, and device/host memory is a cache.

Crash consistency (write-new-then-publish, the immutable-file discipline
of spatial-Parquet lakes / chunked Zarr stores): every flush writes a
NEW generation of partition files next to the old one, fsyncs file
contents and directories, atomically publishes the manifest (itself
fsynced), and only then garbage-collects the previous generation — a
``kill -9`` at any instant leaves a store that reopens to exactly the
old or the new state. Interrupted-flush leftovers are reclaimed by the
recovery sweep at open (:meth:`FileSystemDataStore.recover`, the CLI
``fsck``). Each partition file carries a checksum + byte length in the
manifest, verified per the ``store.verify`` knob (``off``/``open``/
``always``); a corrupt file quarantines ONLY that partition
(:class:`PartitionCorruptError`) while the rest keep serving. The
``fail.flush.*``/``fail.read.*`` failpoints (:mod:`geomesa_tpu.failpoints`)
are evaluated at every step so the chaos suite can kill a flushing
process at each instant.
"""

from __future__ import annotations

import json
import os
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.index.api import BuiltIndex, KeyRange, PartitionMeta
from geomesa_tpu.index.build import DEFAULT_PARTITION_SIZE, build_index
from geomesa_tpu.index.keyspaces import default_indices, keyspace_for
from geomesa_tpu.query.plan import (
    Query,
    QueryPlan,
    as_query,
    internal_query,
    plan_query,
)
from geomesa_tpu.query.runner import QueryResult, run_query


@dataclass
class _FsTypeState:
    sft: SimpleFeatureType
    primary: str
    partitions: "list[PartitionMeta]" = field(default_factory=list)
    pending: "list[FeatureBatch]" = field(default_factory=list)
    data_interval: "tuple[int, int] | None" = None
    cache: "dict[int, FeatureBatch]" = field(default_factory=dict)
    encoding: str = "parquet"
    scheme: "object | None" = None  # PartitionScheme, from SFT user data
    stats: "object | None" = None  # SeqStat rebuilt at flush, persisted
    generation: "str | None" = None  # manifest token last read/written
    #: generation token embedded in the partition FILE names
    #: (``part-<file_gen>-NNNNN.*``); None = legacy un-scoped names
    file_gen: "str | None" = None
    #: manifest format version (chunkstats.FORMAT_V1/V2): v2 partitions
    #: carry per-chunk statistics and parquet row groups aligned to the
    #: chunk boundaries. Lazily upgraded -- any rewrite (flush/compact/
    #: reindex/repartition) re-publishes at ``store.format.version``
    format_version: int = 1
    # legacy manifests only: a pre-generation-era flush failed AFTER
    # unlinking its files, so the rows exist only in that writer's
    # memory. Readers of such a manifest fail loudly instead of seeing
    # an empty-but-valid dataset. New flushes never set this (the old
    # generation stays published until the new one lands).
    dirty: bool = False
    # process-local (never persisted/refreshed): True only in the process
    # whose failed flush raised the quarantine -- the one holding the data
    # in `pending`. Only that process may flush (and thereby lift) it.
    quarantine_owner: bool = False
    #: process-local per-PARTITION quarantine: pid -> checksum error.
    #: Reads of a quarantined partition raise PartitionCorruptError;
    #: sibling partitions keep serving. Cleared when a new generation
    #: is read or published.
    quarantined: "dict[int, str]" = field(default_factory=dict)
    #: highest WAL sequence folded into the published generation (the
    #: streaming layer's recovery watermark, store/stream.py): replay
    #: at open skips records at or below it — they are already in the
    #: partition files. -1 = nothing streamed/compacted yet. Persisted
    #: ATOMICALLY with the manifest, so a crash between publish and
    #: WAL truncation re-applies nothing.
    wal_watermark: int = -1


class PartitionCorruptError(RuntimeError):
    """A partition file failed checksum verification (or was already
    quarantined by an earlier failure). Scoped to ONE partition: queries
    pruned away from it keep serving; queries touching it fail loudly
    instead of silently dropping rows."""


def _write_table(table, path: str, encoding: str) -> None:
    if encoding == "orc":
        import pyarrow.orc as orc

        orc.write_table(table, path)
    else:
        import pyarrow as pa
        import pyarrow.parquet as pq

        # dictionary-encode ONLY string-ish columns (fids, vis labels,
        # WKT): dictionary pages on float/int data cost ~2.7x the write
        # time for zero size win, and parquet column statistics duplicate
        # what the partition manifest already records (key ranges, bbox,
        # time range)
        dict_cols = [
            f.name
            for f in table.schema
            if pa.types.is_string(f.type)
            or pa.types.is_large_string(f.type)
            or pa.types.is_binary(f.type)
        ]
        pq.write_table(
            table, path,
            use_dictionary=dict_cols or False,
            write_statistics=False,
        )


def _read_table(path: str, encoding: str, row_groups=None):
    """Read a partition file; ``row_groups`` (parquet only) reads ONLY
    those row groups -- the chunk-selective pruned read. Callers pass it
    only for v2 files whose chunks align 1:1 with row groups
    (:meth:`FileSystemDataStore._row_groups_for`)."""
    if encoding == "orc":
        import pyarrow.orc as orc

        return orc.read_table(path)
    import pyarrow.parquet as pq

    if row_groups is None:
        return pq.read_table(path)
    return pq.ParquetFile(path).read_row_groups(list(row_groups))


def _encode_table(table, encoding: str, row_group_rows=None) -> bytes:
    """Arrow table -> parquet/orc bytes in memory: the durable write
    path checksums (and fsyncs) the exact bytes that land on disk.
    ``row_group_rows`` (parquet only) sizes row groups to the v2 chunk
    boundaries so chunk-pruned reads skip real file bytes."""
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    if encoding == "orc":
        import pyarrow.orc as orc

        orc.write_table(table, sink)
    else:
        import pyarrow.parquet as pq

        # same dictionary policy as _write_table (see above)
        dict_cols = [
            f.name
            for f in table.schema
            if pa.types.is_string(f.type)
            or pa.types.is_large_string(f.type)
            or pa.types.is_binary(f.type)
        ]
        kwargs = {}
        if row_group_rows:
            kwargs["row_group_size"] = int(row_group_rows)
        pq.write_table(
            table, sink,
            use_dictionary=dict_cols or False,
            write_statistics=False,
            **kwargs,
        )
    return sink.getvalue().to_pybytes()


def _parse_table(data: bytes, encoding: str, row_groups=None):
    """Verified-read counterpart of :func:`_read_table`: parse a table
    from bytes already checksummed in memory (``row_groups`` as in
    :func:`_read_table` -- the whole file was read for the checksum, but
    only the surviving row groups pay the decompress/decode)."""
    import pyarrow as pa

    buf = pa.BufferReader(pa.py_buffer(data))
    if encoding == "orc":
        import pyarrow.orc as orc

        return orc.read_table(buf)
    import pyarrow.parquet as pq

    if row_groups is None:
        return pq.read_table(buf)
    return pq.ParquetFile(buf).read_row_groups(list(row_groups))


def _row_group_nbytes(data: bytes) -> "list[int]":
    """Per-row-group compressed byte sizes of encoded parquet bytes --
    recorded in the v2 manifest so chunk pruning can account the file
    bytes it skipped without opening the file."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    md = pq.ParquetFile(pa.BufferReader(pa.py_buffer(data))).metadata
    out = []
    for i in range(md.num_row_groups):
        rg = md.row_group(i)
        out.append(
            sum(rg.column(j).total_compressed_size for j in range(rg.num_columns))
        )
    return out


# resolved ONCE: a failed import is not cached by Python, and paying a
# sys.path scan per partition write/verified read would add up fast
try:
    from crc32c import crc32c as _crc32c  # optional accelerator
except ImportError:
    _crc32c = None


def checksum_bytes(data: bytes) -> "tuple[str, int]":
    """``(algo, value)`` content checksum. Prefers hardware crc32c when
    the optional module is present, zlib crc32 (always available)
    otherwise; the algo name persists in the manifest so verification
    works in an environment with a different preferred algo."""
    if _crc32c is not None:
        return "crc32c", int(_crc32c(data))
    import zlib

    return "crc32", int(zlib.crc32(data) & 0xFFFFFFFF)


def verify_bytes(data: bytes, checksum: dict) -> "str | None":
    """None when ``data`` matches the manifest checksum record, an
    error description otherwise. Unknown/unavailable algos fall back to
    the (always-checked) byte length rather than failing the read."""
    length = checksum.get("length")
    if length is not None and len(data) != int(length):
        return f"length {len(data)} != manifest {int(length)}"
    algo = checksum.get("algo")
    if algo == "crc32":
        import zlib

        got = int(zlib.crc32(data) & 0xFFFFFFFF)
    elif algo == "crc32c":
        if _crc32c is None:
            return None  # length already checked above
        got = int(_crc32c(data))
    else:
        return None
    want = int(checksum.get("value", -1))
    if got != want:
        return f"{algo} {got:#010x} != manifest {want:#010x}"
    return None


def _write_file(path: str, data: bytes, fsync: bool) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        # os.write may land fewer bytes than asked (signals; Linux caps a
        # single write at ~2GB): loop, or a giant partition file would
        # silently truncate while its manifest checksum covers the whole
        view = memoryview(data)
        while view:
            view = view[os.write(fd, view):]
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(d: str) -> None:
    """Durably record a directory's entries (new/renamed files). Best
    effort: some filesystems refuse directory fsync; the file-content
    fsyncs still stand."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_part_file(
    table, path: str, encoding: str, fsync: bool, chunk_rows=None
) -> "tuple[dict, list | None]":
    """Write one partition file durably — encode to bytes, checksum,
    single write (+fsync) — returning ``(checksum_record,
    chunk_nbytes)``. With ``chunk_rows`` set (v2 parquet), row groups
    align to the chunk boundaries and ``chunk_nbytes`` carries their
    compressed sizes for the manifest; None otherwise."""
    data = _encode_table(table, encoding, row_group_rows=chunk_rows)
    algo, value = checksum_bytes(data)
    chunk_nbytes = None
    if chunk_rows and encoding == "parquet":
        chunk_nbytes = _row_group_nbytes(data)
    _write_file(path, data, fsync)
    return {"algo": algo, "value": value, "length": len(data)}, chunk_nbytes


class _Sized:
    """Audit shim for pushdown-served aggregates: observe_query only
    needs ``len(result)`` (the hit count for the audit event)."""

    def __init__(self, n: int):
        self._n = int(n)

    def __len__(self) -> int:
        return self._n


class _PartFailure:
    """Sentinel a DEGRADABLE partition read returns instead of raising:
    the prefetch pipeline keeps flowing (an exception at item i would
    tear the whole scan down), and the CONSUMER decides — skip the
    partition and stamp the result degraded (``resilience.degrade``
    on), or surface the partition-scoped error."""

    __slots__ = ("p", "error")

    def __init__(self, p, error):
        self.p = p
        self.error = error


class _PresizedSink:
    """Streaming assembly of a FULL-scan result into buffers pre-sized
    from the manifest's row counts (the chunk-stats/manifest contract:
    recorded rows == file rows). The generic path collects every
    partition batch in a list and then concatenates — peak host memory
    is ~2x the dataset at exactly the moment the resident DeviceIndex
    stages it. This sink copies each batch into its slice as it arrives
    and drops it, so the peak is ONE dataset copy plus the in-flight
    prefetch chunks. Buffers grow (rare: manifest drift) and trim (a
    batch shorter than recorded) defensively, so the result is correct
    even when the pre-size hint was wrong."""

    def __init__(self, sft, total: int):
        self.sft = sft
        self.cap = int(total)
        self.filled = 0
        self._cols: "dict | None" = None
        self._fids = None

    def _alloc(self, like: np.ndarray, fill=None) -> np.ndarray:
        buf = np.empty((self.cap,) + like.shape[1:], dtype=like.dtype)
        if fill is not None:
            buf[: self.filled] = fill
        return buf

    def _grow(self, need: int) -> None:
        self.cap = max(self.cap * 2, need)
        for k, v in self._cols.items():
            nb = np.empty((self.cap,) + v.shape[1:], dtype=v.dtype)
            nb[: self.filled] = v[: self.filled]
            self._cols[k] = nb
        nf = np.empty(self.cap, dtype=self._fids.dtype)
        nf[: self.filled] = self._fids[: self.filled]
        self._fids = nf

    def add(self, batch: FeatureBatch) -> None:
        from geomesa_tpu.security import VIS_COLUMN

        n = len(batch)
        if n == 0:
            return
        if self._cols is None:
            self.cap = max(self.cap, n)
            self._cols = {
                k: self._alloc(v) for k, v in batch.columns.items()
            }
            self._fids = self._alloc(batch.fids)
        if self.filled + n > self.cap:
            self._grow(self.filled + n)
        a, b = self.filled, self.filled + n
        for k, buf in self._cols.items():
            v = batch.columns.get(k)
            if v is None:
                if k != VIS_COLUMN:
                    raise KeyError(f"column {k!r} missing from a partition")
                v = np.array([""] * n, dtype=object)
            if not np.can_cast(v.dtype, buf.dtype, casting="same_kind"):
                # preserve trailing dims (e.g. (n, 2) point columns):
                # a bare np.empty(0, dtype) template would allocate 1-D
                promoted = self._alloc(
                    np.empty(
                        (0,) + buf.shape[1:],
                        np.promote_types(buf.dtype, v.dtype),
                    )
                )
                promoted[:a] = buf[:a]
                self._cols[k] = buf = promoted
            buf[a:b] = v
        for k in batch.columns:
            if k not in self._cols:
                # a later partition introduces visibility labels: prior
                # rows are public ("") — same semantics as concat()
                self._cols[k] = self._alloc(batch.columns[k], fill="")
                self._cols[k][a:b] = batch.columns[k]
        if not np.can_cast(
            batch.fids.dtype, self._fids.dtype, casting="same_kind"
        ):
            nf = np.empty(
                self.cap,
                np.promote_types(self._fids.dtype, batch.fids.dtype),
            )
            nf[:a] = self._fids[:a]
            self._fids = nf
        self._fids[a:b] = batch.fids
        self.filled = b

    def finish(self) -> "FeatureBatch | None":
        if self._cols is None:
            return None
        n = self.filled
        return FeatureBatch(
            self.sft,
            self._fids[:n],
            {k: v[:n] for k, v in self._cols.items()},
        )


class FileSystemDataStore:
    def __init__(
        self,
        root: str,
        partition_size: int = DEFAULT_PARTITION_SIZE,
        audit: bool = False,
        encoding: str = "parquet",
        mesh=None,
        io=None,
    ):
        """``mesh``: an optional ``jax.sharding.Mesh`` — flushes then build
        their sorted indexes ON the device mesh (device key encode +
        all_to_all exchange sort, bit-identical to the host build; falls
        back to the host path for key spaces without a device encode).

        ``io``: host-I/O pipeline config for multi-partition reads
        (queries, flush merges, ``query_partitions`` — see
        store/prefetch.py): a PrefetchConfig, an int worker count, or
        None for the ``io.*`` system properties. 0 disables the pipeline
        (serial reads)."""
        if encoding not in ("parquet", "orc"):
            raise ValueError(f"unsupported encoding {encoding!r}")
        import threading

        from geomesa_tpu.locking import checked_rlock

        self.root = root
        self.partition_size = partition_size
        self.mesh = mesh
        self.io = io
        self.encoding = encoding
        self._types: dict[str, _FsTypeState] = {}
        os.makedirs(root, exist_ok=True)
        # inter-process coordination (DistributedLocking analog): one
        # flock sentinel per store root; exclusive for in-place rewrites
        # (flush/compact/reindex/repartition), shared for file reads so a
        # reader never observes a half-rewritten directory
        self._lock_path = os.path.join(root, ".lock")
        self._lock_tl = threading.local()
        # flock serializes PROCESSES; this RLock serializes THREADS of
        # this process (a ThreadingHTTPServer shares one store object,
        # and _refresh_from_disk mutates shared state in place).
        # blocking_ok: maintenance holds it across partition file I/O BY
        # DESIGN (the scan-consistency window); the lock-free worker
        # reads of PR 2 exist precisely because of that.
        self._mem_lock = checked_rlock("store.fs.mem", blocking_ok=True)
        self.audit_writer = None
        #: what the open-time recovery sweep reclaimed, per type — folded
        #: into the next explicit recover() so fsck reports the crash
        #: cleanup its own store open already performed
        self._open_recovery: dict = {}
        #: (type_name, snapshot_id) pins THIS process's snapshot streams
        #: hold: exempt from the on-disk pin TTL so a slow-but-live
        #: local stream is never torn by its own store's sweep
        self._active_pins: "set[tuple[str, str]]" = set()
        if audit:  # the <catalog>_queries table analog
            from geomesa_tpu.audit import FileAuditWriter

            self.audit_writer = FileAuditWriter(
                os.path.join(root, "_queries.jsonl")
            )
        for name in sorted(os.listdir(root)):
            meta_path = os.path.join(root, name, "schema.json")
            if os.path.exists(meta_path):
                self._load_type(name)
        self._recover_on_open()

    def _recover_on_open(self) -> None:
        """Crash recovery at open: under the exclusive lock (no flush can
        be mid-write), reclaim interrupted-flush leftovers and repair a
        lagging generation sidecar; ``store.verify=open`` additionally
        checksums every partition file, quarantining failures. A held
        lock elsewhere must not brick opening — the sweep is skipped
        with a warning and runs on the next open/fsck instead."""
        if not self._types:
            return
        import logging

        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.locking import LockTimeout

        verify_open = sys_prop("store.verify") == "open"
        for name in list(self._types):
            try:
                with self._exclusive():
                    self._refresh_from_disk(name)
                    self._open_recovery[name] = self._recover_locked(name)
                    if verify_open:
                        self._verify_type(name)
            except LockTimeout as e:
                logging.getLogger(__name__).warning(
                    "dataset %r: recovery sweep skipped at open (%s)",
                    name, e,
                )

    # -- inter-process locking ---------------------------------------------

    @contextmanager
    def _exclusive(self):
        """Exclusive store lock, re-entrant per thread (a locked rewrite
        reads existing files through _read_partition)."""
        from geomesa_tpu.locking import file_lock

        depth = getattr(self._lock_tl, "depth", 0)
        if depth > 0:
            self._lock_tl.depth = depth + 1
            try:
                yield
            finally:
                self._lock_tl.depth -= 1
            return
        with self._mem_lock, file_lock(self._lock_path):
            self._lock_tl.depth = 1
            try:
                yield
            finally:
                self._lock_tl.depth = 0

    @contextmanager
    def _shared(self):
        from geomesa_tpu.locking import file_lock

        if getattr(self._lock_tl, "depth", 0) > 0:
            yield  # already under this thread's exclusive lock
            return
        with self._mem_lock, file_lock(self._lock_path, shared=True):
            yield

    # -- schema / persistence ---------------------------------------------

    def _dir(self, type_name: str) -> str:
        return os.path.join(self.root, type_name)

    def _load_type(self, name: str) -> None:
        self._types[name] = self._read_state(name)

    def _read_state(self, name: str) -> "_FsTypeState":
        # shared lock: never read the manifest mid-rewrite (writers hold
        # the exclusive lock across the atomic os.replace of schema.json)
        with self._shared():
            with open(os.path.join(self._dir(name), "schema.json")) as fh:
                meta = json.load(fh)
        sft = SimpleFeatureType.create(name, meta["spec"])
        from geomesa_tpu.store.chunkstats import FORMAT_V1, chunkset_from_json

        parts = [
            PartitionMeta(
                pid=p["pid"],
                start=p["start"],
                stop=p["stop"],
                key_lo=tuple(p["key_lo"]),
                key_hi=tuple(p["key_hi"]),
                count=p["count"],
                bbox=tuple(p["bbox"]) if p.get("bbox") else None,
                time_range=tuple(p["time_range"]) if p.get("time_range") else None,
                leaf=p.get("leaf"),
                checksum=p.get("checksum"),
                chunks=self._load_chunks(chunkset_from_json, p.get("chunks")),
                gen=meta.get("file_gen"),
            )
            for p in meta["partitions"]
        ]
        return _FsTypeState(
            sft,
            meta["primary"],
            parts,
            data_interval=tuple(meta["data_interval"])
            if meta.get("data_interval")
            else None,
            encoding=meta.get("encoding", "parquet"),
            scheme=self._scheme_of(sft, strict=False),
            stats=self._load_stats(meta.get("stats")),
            generation=meta.get("generation"),
            file_gen=meta.get("file_gen"),
            format_version=int(meta.get("format", FORMAT_V1)),
            dirty=bool(meta.get("dirty", False)),
            wal_watermark=int(meta.get("wal_watermark", -1)),
        )

    @staticmethod
    def _load_chunks(parse, raw):
        if not raw:
            return None
        try:
            return parse(raw)
        except Exception:  # lint: disable=GT011(sidecar stats are advisory: corrupt chunk metadata degrades to a full scan, never a failed open)
            return None  # chunk stats are advisory; never block opening

    @staticmethod
    def _load_stats(raw):
        if not raw:
            return None
        from geomesa_tpu.stats.sketches import seq_from_json

        try:
            return seq_from_json(raw)
        except Exception:  # lint: disable=GT011(sidecar stats are advisory: corrupt sketches degrade estimates, never a failed open)
            return None  # stats are advisory; never block opening

    @staticmethod
    def _scheme_of(sft: SimpleFeatureType, strict: bool = True):
        from geomesa_tpu.store.partitions import USER_DATA_KEY, scheme_for

        spec = sft.user_data.get(USER_DATA_KEY)
        if not spec:
            return None
        try:
            scheme = scheme_for(str(spec))
            scheme.validate(sft)
        except ValueError:
            if strict:  # create_schema: fail fast, before any writes
                raise
            # loading persisted state: an invalid scheme must not brick
            # the whole catalog -- files stay readable via their recorded
            # leaf paths, only leaf pruning is lost
            import logging

            logging.getLogger(__name__).warning(
                "type %r: invalid partition scheme %r ignored on load",
                sft.type_name,
                spec,
            )
            return None
        return scheme

    def _save_meta(self, name: str) -> None:
        import uuid

        from geomesa_tpu.store.chunkstats import chunkset_to_json

        st = self._types[name]
        st.generation = uuid.uuid4().hex  # new manifest token
        meta = {
            "generation": st.generation,
            "file_gen": st.file_gen,
            "format": st.format_version,
            "dirty": st.dirty,
            "wal_watermark": st.wal_watermark,
            "spec": st.sft.spec,
            "primary": st.primary,
            "encoding": st.encoding,
            "data_interval": st.data_interval,
            "stats": st.stats.to_json() if st.stats is not None else None,
            "partitions": [
                {
                    "pid": p.pid,
                    "start": p.start,
                    "stop": p.stop,
                    "key_lo": list(p.key_lo),
                    "key_hi": list(p.key_hi),
                    "count": p.count,
                    "bbox": list(p.bbox) if p.bbox else None,
                    "time_range": list(p.time_range) if p.time_range else None,
                    "leaf": p.leaf,
                    "checksum": p.checksum,
                    "chunks": chunkset_to_json(p.chunks),
                }
                for p in st.partitions
            ],
        }
        self._publish_manifest(
            os.path.join(self._dir(name), "schema.json"),
            json.dumps(meta),
            st.generation,
        )

    @staticmethod
    def _publish_manifest(path: str, body: str, generation: str) -> None:
        """Atomically publish ``schema.json`` AND its ``.gen`` staleness
        sidecar, fsyncing file contents and the directory: a crash at
        any instant leaves either the old or the new manifest, never a
        truncated one. The sidecar derives FROM this manifest write (one
        source of truth); a crash between the two replaces leaves it
        lagging by exactly one generation, which the recovery sweep
        repairs from the manifest on the next open."""
        from geomesa_tpu.conf import sys_prop

        fsync = bool(sys_prop("store.fsync"))
        tmp = path + ".tmp"
        _write_file(tmp, body.encode("utf-8"), fsync)
        os.replace(tmp, path)
        # tiny sidecar: staleness checks read ONLY this, not the whole
        # manifest (which carries the full partition list)
        gen_tmp = path + ".gen.tmp"
        _write_file(gen_tmp, generation.encode("utf-8"), fsync)
        os.replace(gen_tmp, path + ".gen")
        if fsync:
            _fsync_dir(os.path.dirname(path))

    def create_schema(self, sft: "SimpleFeatureType | str", spec: "str | None" = None):
        if isinstance(sft, str):
            sft = SimpleFeatureType.create(sft, spec)
        if sft.type_name in self._types:
            raise ValueError(f"schema {sft.type_name!r} exists")
        primary = default_indices(sft)[0]
        os.makedirs(self._dir(sft.type_name), exist_ok=True)
        self._types[sft.type_name] = _FsTypeState(
            sft, primary, encoding=self.encoding, scheme=self._scheme_of(sft)
        )
        self._save_meta(sft.type_name)
        return sft

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._types[type_name].sft

    @property
    def type_names(self) -> list:
        return list(self._types)

    # -- writes ------------------------------------------------------------

    def write(self, type_name: str, columns_or_batch, fids=None) -> int:
        st = self._types[type_name]
        if isinstance(columns_or_batch, FeatureBatch):
            batch = columns_or_batch
        else:
            batch = FeatureBatch.from_columns(st.sft, columns_or_batch, fids)
        st.pending.append(batch)
        return len(batch)

    def flush(self, type_name: str) -> None:
        """Merge pending + existing into freshly sorted partition files (the
        compaction step; ref geomesa-fs CompactCommand semantics)."""
        st = self._types[type_name]
        if not st.pending:  # checked before locking: queries flush eagerly
            return
        with self._exclusive():
            self._refresh_from_disk(type_name)
            self._flush_locked(type_name)

    def _refresh_from_disk(self, type_name: str) -> None:
        """Re-read the on-disk manifest under the HELD exclusive lock:
        another process may have rewritten the directory since this
        process snapshotted it, and merging from the stale view would
        read deleted part files. Buffered pending rows survive; the disk
        wins on everything else (partitions, primary, scheme, stats)."""
        meta_path = os.path.join(self._dir(type_name), "schema.json")
        if not os.path.exists(meta_path):
            return
        st = self._types.get(type_name)
        try:
            gen_path = meta_path + ".gen"
            if os.path.exists(gen_path):
                with open(gen_path) as fh:
                    disk_gen = fh.read().strip() or None
            else:  # pre-sidecar manifest: full parse fallback
                with open(meta_path) as fh:
                    disk_gen = json.load(fh).get("generation")
        except (OSError, json.JSONDecodeError):
            return  # unreadable manifest: keep our view
        if st is not None and disk_gen == st.generation:
            # nobody else wrote since we last read/wrote: our in-memory
            # state may be deliberately AHEAD of disk (failed-flush
            # recovery holds everything in pending; deletions may not be
            # persisted yet) and must win
            return
        new = self._read_state(type_name)
        if st is None:
            self._types[type_name] = new
            return
        # in-place: callers (delete, plan, query) hold references to the
        # state object across flushes -- rebinding would strand them on a
        # dead object. Buffered pending rows survive; disk wins on the
        # rest.
        st.sft = new.sft
        st.primary = new.primary
        st.partitions = new.partitions
        st.data_interval = new.data_interval
        st.encoding = new.encoding
        st.scheme = new.scheme
        st.stats = new.stats
        st.generation = new.generation
        st.file_gen = new.file_gen
        st.format_version = new.format_version
        st.dirty = new.dirty
        st.wal_watermark = new.wal_watermark
        st.cache = {}
        # a new generation means new files: stale per-partition
        # quarantines must not outlive the files they indicted
        self._clear_quarantine(st)
        if getattr(self._lock_tl, "depth", 0) > 0:
            # already under the exclusive lock (a maintenance op noticed
            # another process's rewrite): reclaim anything a crashed
            # writer left behind while it is safe to do so
            return self._recover_locked(type_name)

    def _flush_locked(self, type_name: str) -> None:
        st = self._types[type_name]
        if st.dirty and not st.quarantine_owner:
            # a LEGACY (pre-generation) manifest recording a flush that
            # failed after unlinking its files: that process alone holds
            # the lost rows in memory. Flushing our own pending here
            # would publish a clean manifest with only OUR rows --
            # turning the loud failure back into silent loss.
            raise RuntimeError(
                f"dataset {type_name!r} is quarantined: a flush failed "
                "mid-rewrite in another process; retry there or restore "
                "the files"
            )
        if not st.pending:
            return
        orig_pending = list(st.pending)
        batches = orig_pending
        if st.partitions:
            batches = [self._read_all(type_name)] + batches
        data = batches[0] if len(batches) == 1 else FeatureBatch.concat(batches)
        # resolve the keyspace BEFORE clearing pending: a bad primary must
        # not drop the buffered writes
        ks = keyspace_for(st.sft, st.primary)
        st.pending = []
        gen0 = st.generation
        try:
            self._write_sorted(type_name, st, ks, data)
        except BaseException:
            # write-new-then-publish: the PREVIOUS generation is still
            # published and intact, so readers (this process and others)
            # lose nothing; _write_sorted already restored the manifest
            # view and swept its partial files. Restore the buffered
            # batches so a corrected retry merges exactly the same rows
            # -- unless the manifest actually advanced (a post-publish
            # failpoint/GC error), where a restore would duplicate them.
            # Prepended, not assigned: concurrent write() calls may have
            # buffered new batches while the flush ran.
            if st.generation == gen0:
                st.pending = orig_pending + st.pending
            raise

    def _write_sorted(self, type_name, st, ks, data) -> None:
        """Crash-consistent rewrite (write-new-then-publish): the new
        generation's ``part-<gen>-*`` files land NEXT TO the previous
        generation, are fsynced (contents, then directories), and only
        then does the manifest atomically flip — after which the old
        generation is garbage-collected. A crash at ANY instant leaves a
        store that reopens to exactly the previous or the new state;
        leftovers of an interrupted flush are unpublished and reclaimed
        by the recovery sweep. The ``fail.flush.*`` failpoints bracket
        each step for the chaos suite."""
        import dataclasses
        import uuid

        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.failpoints import fail_point
        from geomesa_tpu.pyarrow_compat import preload_pyarrow

        # the writer threads import pyarrow.parquet/orc: the FIRST pyarrow
        # import must happen on this (spawning) thread or a later
        # main-thread read segfaults (pyarrow_compat contract)
        preload_pyarrow()
        d = self._dir(type_name)
        fsync = bool(sys_prop("store.fsync"))
        new_gen = uuid.uuid4().hex[:8]
        # partition format v2: fixed-size chunks with manifest statistics
        # (store/chunkstats.py); parquet row groups align to the chunk
        # boundaries so chunk-pruned reads skip real bytes. v1 keeps the
        # legacy single-row-group layout bit-for-bit.
        from geomesa_tpu.store.chunkstats import FORMAT_V2, build_chunk_set

        fmt = int(sys_prop("store.format.version"))
        chunk_rows = max(int(sys_prop("store.chunk.rows")), 1)
        chunk_grid = max(int(sys_prop("store.chunk.grid")), 1)
        v2 = fmt == FORMAT_V2
        prev = (
            st.partitions, st.file_gen, st.stats, st.data_interval,
            st.generation, st.dirty, st.quarantine_owner, st.format_version,
        )
        # partition files stream out on writer threads (pyarrow releases
        # the GIL; at GB scale the writes are disk-writeback-bound) while
        # the main thread computes stats/manifest — joined BEFORE the
        # manifest publishes, so readers never see it ahead of the files
        writes: "list[tuple]" = []  # (PartitionMeta, Future[checksum])
        dirs = {d}  # every directory holding a new file gets fsynced
        publishing = False
        from geomesa_tpu.spawn import ContextPool

        # blessed pool: the writer threads charge write I/O to the
        # flushing request's collector (carried by submit-time capture)
        ex = ContextPool(2, thread_name_prefix="fs-flush")
        try:
            if st.scheme is not None and len(data):
                # group rows by directory leaf; each leaf is sorted +
                # manifested independently (the partition-scheme layout)
                leaves = st.scheme.leaves(data)
                pid = 0
                for leaf in sorted(set(leaves)):
                    sub = data.take(np.nonzero(leaves == leaf)[0])
                    built = self._build(ks, sub)
                    leaf_dir = d
                    for seg in leaf.split("/"):
                        leaf_dir = os.path.join(leaf_dir, seg)
                        dirs.add(leaf_dir)
                    os.makedirs(leaf_dir, exist_ok=True)
                    # ONE arrow conversion per leaf; partition files are
                    # zero-copy slices (a per-partition take + to_arrow
                    # paid a full column conversion for every file)
                    table = built.batch.to_arrow()
                    for p in built.partitions:
                        part = dataclasses.replace(
                            p,
                            pid=pid,
                            leaf=leaf,
                            chunks=build_chunk_set(
                                ks, built.batch, built.keys,
                                p.start, p.stop, chunk_rows, chunk_grid,
                            ) if v2 else None,
                        )
                        writes.append((part, ex.submit(
                            _write_part_file,
                            table.slice(p.start, p.stop - p.start),
                            self._part_path(type_name, part, gen=new_gen),
                            st.encoding,
                            fsync,
                            chunk_rows if v2 else None,
                        )))
                        pid += 1
                full = data
                z3_keys = None
            else:
                built = self._build(ks, data)
                table = built.batch.to_arrow()
                for p in built.partitions:
                    part = dataclasses.replace(
                        p,
                        chunks=build_chunk_set(
                            ks, built.batch, built.keys,
                            p.start, p.stop, chunk_rows, chunk_grid,
                        ) if v2 else None,
                    )
                    writes.append((part, ex.submit(
                        _write_part_file,
                        table.slice(p.start, p.stop - p.start),
                        self._part_path(type_name, part, gen=new_gen),
                        st.encoding,
                        fsync,
                        chunk_rows if v2 else None,
                    )))
                full = built.batch
                # the build already encoded every row's (bin, z): reuse
                # for the Z3 histogram instead of a second full encode
                z3_keys = (
                    (built.keys["bin"], built.keys["z"])
                    if getattr(ks, "name", None) == "z3"
                    else None
                )
            dtg = st.sft.dtg_field
            interval = st.data_interval
            if dtg is not None and len(full):
                col = full.column(dtg)
                interval = (int(col.min()), int(col.max()))
            from geomesa_tpu.store.memory import build_default_stats

            stats = build_default_stats(st.sft, full, z3_keys=z3_keys)
            # join: a failed write must fail the flush loudly, BEFORE
            # anything publishes; the checksums (and v2 per-chunk
            # row-group byte sizes) ride back with the joins
            parts = []
            for p, w in writes:
                checksum, chunk_nbytes = w.result()
                if (
                    p.chunks is not None
                    and chunk_nbytes is not None
                    and len(chunk_nbytes) == len(p.chunks)
                ):
                    p.chunks.nbytes = np.asarray(chunk_nbytes, dtype=np.int64)
                parts.append(
                dataclasses.replace(p, checksum=checksum, gen=new_gen)
            )
            fail_point("fail.flush.after_write")
            if fsync:
                for dd in sorted(dirs):
                    _fsync_dir(dd)
            st.partitions = parts
            st.file_gen = new_gen
            st.format_version = fmt
            st.data_interval = interval
            st.stats = stats
            st.cache = {}
            self._clear_quarantine(st)
            st.dirty = False
            st.quarantine_owner = False
            fail_point("fail.flush.before_publish")
            publishing = True
            self._save_meta(type_name)
        except BaseException:
            # abort: the previous generation is still the published one.
            # Restore the in-memory view to it and remove our partial
            # files — unless the manifest write itself was interrupted
            # (it may or may not have flipped); then the files stay and
            # the recovery sweep reconciles against the REAL manifest.
            # Queued writes are cancelled (their slices would only be
            # unlinked below); in-flight ones must land before unlinking.
            ex.shutdown(wait=True, cancel_futures=True)
            published_gen = st.generation if publishing else None
            (st.partitions, st.file_gen, st.stats, st.data_interval,
             st.generation, st.dirty, st.quarantine_owner,
             st.format_version) = prev
            st.cache = {}
            if publishing:
                # the manifest replace may have landed before the
                # failure (e.g. the SIDECAR write raised): the disk
                # decides which generation this process is on now. If
                # it flipped, adopt the new state — restoring the old
                # view would defeat _flush_locked's duplicate guard and
                # re-queue rows the manifest already owns. The lagging
                # sidecar is repaired by the next sweep/open.
                try:
                    with open(os.path.join(d, "schema.json")) as fh:
                        disk_gen = json.load(fh).get("generation")
                except (OSError, json.JSONDecodeError):
                    disk_gen = None
                if disk_gen == published_gen:
                    st.partitions, st.file_gen = parts, new_gen
                    st.data_interval, st.stats = interval, stats
                    st.generation = published_gen
                    st.format_version = fmt
                    st.dirty = False
                    st.quarantine_owner = False
            else:
                import logging

                for p, _ in writes:
                    path = self._part_path(type_name, p, gen=new_gen)
                    try:
                        os.unlink(path)
                    except FileNotFoundError:
                        pass
                    except OSError as e:
                        # the file is merely an unpublished orphan now --
                        # but operators should know the sweep owes work
                        logging.getLogger(__name__).warning(
                            "dataset %r: could not remove aborted flush "
                            "file %r: %s", type_name, path, e,
                        )
            raise
        finally:
            ex.shutdown(wait=True)
        from geomesa_tpu import metrics

        metrics.store_generations.inc()
        fail_point("fail.flush.after_publish")
        # the new generation is durable and published: the old one is
        # garbage — GC failures leave harmless orphans for the sweep
        self._gc_stale_parts(type_name)

    #: below this row count a mesh build is routed to the host lexsort
    #: anyway: per-shape jit traces + host->device transfer of tiny (e.g.
    #: per-leaf) batches cost more than the sort they accelerate
    MESH_BUILD_MIN_ROWS = 1 << 16

    def _build(self, ks, data) -> BuiltIndex:
        """Sorted-index build for a flush: on the device mesh when one was
        supplied, the key space has a device encode, and the batch is big
        enough to amortize the dispatch; host lexsort otherwise. Both
        produce bit-identical BuiltIndexes (proven by the parity suite),
        so the manifest/files do not depend on the path."""
        from geomesa_tpu.index.build import DEVICE_BUILD_KINDS

        if (
            self.mesh is not None
            and self.mesh.size > 1
            and getattr(ks, "name", None) in DEVICE_BUILD_KINDS
            and len(data) >= self.MESH_BUILD_MIN_ROWS
        ):
            # the mesh path earns its keep by PARALLELISM (the exchange
            # sort scales across shards); a single-device mesh pays the
            # host->device->host round trip of every lane for none, and
            # through a remote-tunnel chip that round trip alone is ~10x
            # the host build. Bit-identical either way (parity suite).
            return build_index(ks, data, self.partition_size, mesh=self.mesh)
        return build_index(ks, data, self.partition_size)

    #: sentinel: "use the type's published file generation"
    _GEN_CURRENT = object()

    def _part_path(
        self, type_name: str, p: PartitionMeta, gen=_GEN_CURRENT
    ) -> str:
        """Path of a partition file. ``gen`` defaults to the generation
        stamped on the META (falling back to the type's published file
        generation for unstamped metas; None = legacy un-scoped names);
        a flush mid-rewrite passes its NEW generation explicitly. Meta-
        faithful resolution is what keeps a scan over a pre-flush
        partition snapshot on ITS generation's files — it must never
        silently read a newer generation's file for the same pid."""
        from geomesa_tpu.store.partitions import part_file_name

        st = self._types[type_name]
        d = self._dir(type_name)
        if p.leaf:
            d = os.path.join(d, p.leaf)
        if gen is self._GEN_CURRENT:
            gen = p.gen if p.gen is not None else st.file_gen
        return os.path.join(d, part_file_name(p.pid, st.encoding, gen))

    # -- crash recovery / integrity ----------------------------------------

    @staticmethod
    def _clear_quarantine(st: "_FsTypeState") -> None:
        if st.quarantined:
            from geomesa_tpu import metrics

            metrics.store_quarantined.dec(len(st.quarantined))
            st.quarantined = {}

    def _quarantine(self, type_name: str, st, p, path: str, err: str) -> None:
        """Quarantine ONE partition after a checksum failure: loud
        per-partition error, the rest of the dataset keeps serving."""
        import logging

        from geomesa_tpu import metrics

        if p.pid not in st.quarantined:
            st.quarantined[p.pid] = err
            metrics.store_checksum_failures.inc()
            metrics.store_quarantined.inc()
            logging.getLogger(__name__).error(
                "dataset %r partition %d (%s): checksum verification "
                "failed (%s) -- partition quarantined; queries not "
                "touching it keep serving",
                type_name, p.pid, path, err,
            )

    def recover(self, type_name: str) -> dict:
        """Recovery sweep: under the exclusive lock (no flush can be
        mid-write), re-sync with the on-disk manifest, repair a lagging
        ``.gen`` sidecar, and reclaim files left by interrupted flushes
        (unpublished generations, ``*.tmp``). Idempotent; runs
        automatically at store open and from the CLI ``fsck``. Returns
        ``{"files": n, "bytes": b, "gen_repaired": bool}``."""
        with self._exclusive():
            # the refresh itself sweeps when it notices a newer on-disk
            # generation: fold that report in rather than dropping it
            pre = self._refresh_from_disk(type_name)
            rep = self._recover_locked(type_name)
            # fold in sweeps this call didn't run itself but whose work
            # would otherwise go unreported: the open-time sweep (fsck
            # opens the store, which already reclaimed the orphans) and
            # a refresh-triggered one
            for extra in (pre, self._open_recovery.pop(type_name, None)):
                if extra:
                    rep = {
                        "files": rep["files"] + extra["files"],
                        "bytes": rep["bytes"] + extra["bytes"],
                        "gen_repaired": rep["gen_repaired"]
                        or extra["gen_repaired"],
                    }
            return rep

    def _recover_locked(self, type_name: str) -> dict:
        import logging

        from geomesa_tpu import metrics

        repaired = self._repair_gen_sidecar(type_name)
        files, nbytes = self._gc_stale_parts(type_name)
        if files:
            metrics.store_orphan_files.inc(files)
            metrics.store_orphan_bytes.inc(nbytes)
            logging.getLogger(__name__).warning(
                "dataset %r: recovery sweep reclaimed %d orphan file(s), "
                "%d bytes, from an interrupted flush",
                type_name, files, nbytes,
            )
        return {"files": files, "bytes": nbytes, "gen_repaired": repaired}

    def _repair_gen_sidecar(self, type_name: str) -> bool:
        """A crash between the manifest replace and the sidecar replace
        leaves ``.gen`` one generation behind ``schema.json`` (whose
        value is the truth): republish the sidecar from the manifest."""
        st = self._types[type_name]
        if not st.generation:
            return False
        from geomesa_tpu.conf import sys_prop

        gen_path = os.path.join(self._dir(type_name), "schema.json.gen")
        disk = None
        try:
            with open(gen_path) as fh:
                disk = fh.read().strip() or None
        except OSError:
            pass
        if disk == st.generation:
            return False
        _write_file(
            gen_path + ".tmp",
            st.generation.encode("utf-8"),
            bool(sys_prop("store.fsync")),
        )
        os.replace(gen_path + ".tmp", gen_path)
        return True

    def _gc_stale_parts(self, type_name: str) -> "tuple[int, int]":
        """Remove part/tmp files not referenced by the current manifest
        (the previous generation right after a publish; interrupted-flush
        leftovers during a recovery sweep). Caller holds the exclusive
        lock. Returns (files, bytes) removed.

        Snapshot pins (store/snapshot.py) extend the keep-set: a pinned
        generation's files survive even after a newer manifest
        supersedes them, so an in-flight ``GET /snapshot`` stream never
        has a file reclaimed from under it; the pin helper also ages
        out orphaned pins (``snapshot.pin.ttl.s``) so a SIGKILLed
        stream delays GC boundedly instead of wedging it. Underscore
        directories (``_wal``, ``_pins``, ``_snapstage``) are never
        descended into — the WAL/pin/stage planes manage their own
        files."""
        import logging

        from geomesa_tpu.store import snapshot

        st = self._types[type_name]
        expected = {
            os.path.abspath(self._part_path(type_name, p))
            for p in st.partitions
        }
        expected |= snapshot.pinned_paths(self, type_name)
        files = nbytes = 0
        for dirpath, dirnames, names in os.walk(self._dir(type_name)):
            dirnames[:] = [d for d in dirnames if not d.startswith("_")]
            for f in names:
                if not (f.startswith("part-") or f.endswith(".tmp")):
                    continue
                path = os.path.join(dirpath, f)
                if os.path.abspath(path) in expected:
                    continue
                try:
                    sz = os.path.getsize(path)
                    os.unlink(path)
                except FileNotFoundError:
                    continue
                except OSError as e:
                    logging.getLogger(__name__).warning(
                        "dataset %r: could not reclaim %r: %s",
                        type_name, path, e,
                    )
                    continue
                files += 1
                nbytes += sz
        return files, nbytes

    def verify_partitions(self, type_name: str) -> "list[tuple]":
        """Full checksum verification of every partition file (the
        ``fsck`` pass, and what ``store.verify=open`` runs at store
        open): returns ``[(pid, path, error)]`` for the failures, each
        of which is quarantined."""
        with self._shared():
            self._refresh_from_disk(type_name)
            return self._verify_type(type_name)

    def _verify_type(self, type_name: str) -> "list[tuple]":
        st = self._types[type_name]
        errors = []
        for p in st.partitions:
            path = self._part_path(type_name, p)
            err = None
            try:
                with open(path, "rb") as fh:
                    data = fh.read()
            except OSError as e:
                err = f"unreadable: {e}"
            else:
                if p.checksum is not None:
                    err = verify_bytes(data, p.checksum)
            if err:
                self._quarantine(type_name, st, p, path, err)
                errors.append((p.pid, path, err))
        return errors

    def store_stats(self) -> dict:
        """Durability/integrity snapshot (the ``/stats/store`` endpoint
        and the ``fsck`` report): per-type generations, partition and
        quarantine state, plus the process-wide ``geomesa_store_*``
        counters."""
        from geomesa_tpu import metrics
        from geomesa_tpu.conf import sys_prop

        types = {}
        for name, st in self._types.items():
            chunked = [p for p in st.partitions if p.chunks is not None]
            types[name] = {
                "generation": st.generation,
                "file_generation": st.file_gen,
                "encoding": st.encoding,
                "format": int(st.format_version),
                "partitions": len(st.partitions),
                "rows": int(sum(p.count for p in st.partitions)),
                "dirty": bool(st.dirty),
                "wal_watermark": int(st.wal_watermark),
                # format-mix / chunk-stats coverage: how much of the
                # type the pruning + pushdown machinery can serve (v1
                # partitions linger until a compact lazily upgrades)
                "chunked_partitions": len(chunked),
                "chunks": int(sum(len(p.chunks) for p in chunked)),
                "chunk_rows_covered": int(
                    sum(p.count for p in chunked)
                ),
                "quarantined": {
                    int(pid): err for pid, err in st.quarantined.items()
                },
            }
        return {
            "root": self.root,
            "verify": sys_prop("store.verify"),
            "types": types,
            "counters": {
                "generations_published": metrics.store_generations.value(),
                "orphan_files_reclaimed": metrics.store_orphan_files.value(),
                "orphan_bytes_reclaimed": metrics.store_orphan_bytes.value(),
                "checksum_failures": metrics.store_checksum_failures.value(),
                "partitions_quarantined": metrics.store_quarantined.value(),
                "read_retries": metrics.store_read_retries.value(),
                "chunks_read": metrics.store_chunks_read.value(),
                "chunks_skipped": metrics.store_chunks_skipped.value(),
                "chunk_bytes_skipped":
                    metrics.store_chunk_bytes_skipped.value(),
                "chunk_stat_drift": metrics.store_chunk_stat_drift.value(),
                "pushdown_queries": {
                    k: metrics.agg_pushdown_queries.value(kind=k)
                    for k in ("count", "density", "stats")
                },
                "pushdown_fallbacks": {
                    k: metrics.agg_pushdown_fallbacks.value(kind=k)
                    for k in ("count", "density", "stats")
                },
                "pushdown_rows_preaggregated":
                    metrics.agg_pushdown_rows.value(),
            },
        }

    def delete(self, type_name: str, fids) -> int:
        """Drop features by id and compact the partition files. One
        exclusive section end to end: a writer slipping between the read
        and the rewrite would have its rows resurrected or duplicated."""
        with self._exclusive():
            self._refresh_from_disk(type_name)
            st = self._types[type_name]
            self._flush_locked(type_name)
            if not st.partitions:
                return 0
            data = self._read_all(type_name)
            # object dtype: a mixed int/str id list must not collapse to
            # all-str
            keep = ~np.isin(
                data.fids, np.asarray(list(fids), dtype=object)
            )
            removed = int((~keep).sum())
            if removed:
                st.pending = [data.take(np.nonzero(keep)[0])]
                st.partitions = []
                self._flush_locked(type_name)
            return removed

    def age_off(self, type_name: str, before_ms: int) -> int:
        from geomesa_tpu.store.ageoff import age_off

        return age_off(self, type_name, self._types[type_name].sft, before_ms)

    def update_user_data(self, type_name: str, updates: dict) -> None:
        """Set (or, with None values, remove) schema user-data entries and
        persist the manifest (ref: UpdateSftCommand / KeywordsCommand).
        Exclusive + refresh: _save_meta serializes the full partition
        list, and writing it from a stale view would clobber another
        process's flushed manifest."""
        with self._exclusive():
            self._refresh_from_disk(type_name)
            st = self._types[type_name]
            for k, v in updates.items():
                if v is None:
                    st.sft.user_data.pop(k, None)
                else:
                    st.sft.user_data[k] = v
            self._save_meta(type_name)

    def compact(self, type_name: str) -> None:
        """Rewrite all partition files merged + freshly sorted (ref:
        geomesa-fs CompactCommand)."""
        self._rebuild_files(type_name)

    # -- maintenance jobs (ref geomesa-jobs index back-population) ---------

    def _rebuild_files(self, type_name: str) -> None:
        """Re-sort + re-write every partition file under the current
        primary/scheme (pending data included)."""
        with self._exclusive():
            self._refresh_from_disk(type_name)
            self._rebuild_locked(type_name)

    def _rebuild_locked(self, type_name: str) -> None:
        st = self._types[type_name]
        if st.partitions:
            st.pending = [self._read_all(type_name)] + st.pending
            st.partitions = []
        self._flush_locked(type_name)
        # persists primary/scheme even when empty
        self._save_meta(type_name)

    def reindex(self, type_name: str, primary: str) -> None:
        """Switch the primary index and rebuild the sorted files (ref:
        geomesa-jobs attribute re-index / index back-population; here the
        sort order IS the index, so re-indexing is a rewrite)."""
        with self._exclusive():
            self._refresh_from_disk(type_name)  # BEFORE the mutation
            st = self._types[type_name]
            keyspace_for(st.sft, primary)  # validate against the schema
            st.primary = primary
            self._rebuild_locked(type_name)

    def repartition(self, type_name: str, scheme_spec: "str | None") -> None:
        """Change (or drop) the directory partition scheme and rewrite the
        layout."""
        from geomesa_tpu.store.partitions import USER_DATA_KEY, scheme_for

        with self._exclusive():
            self._refresh_from_disk(type_name)  # BEFORE the mutation
            st = self._types[type_name]
            if scheme_spec:
                scheme = scheme_for(scheme_spec)
                scheme.validate(st.sft)
                st.sft.user_data[USER_DATA_KEY] = scheme.spec
            else:
                scheme = None
                st.sft.user_data.pop(USER_DATA_KEY, None)
            st.scheme = scheme
            self._rebuild_locked(type_name)

    def _cache_slice(
        self, st, p: PartitionMeta, chunk_sel
    ) -> "FeatureBatch | None":
        """Serve a chunk-selective read from an already-cached FULL
        partition batch (chunk row offsets are partition-relative slices
        of the file order), or None on a cache miss. Chunk-selective
        results are never themselves pinned -- a partial batch in the
        cache would silently truncate later full reads. Cache keys are
        (generation, pid): a pid recurs across generations with
        different contents, so a stale-snapshot scan must neither hit a
        newer generation's bytes nor publish its own where a
        current-generation reader would find them."""
        full = st.cache.get((p.gen, p.pid))
        if full is None:
            return None
        cs = p.chunks
        idx = np.concatenate(
            [
                np.arange(int(cs.starts[i]), int(cs.stops[i]), dtype=np.int64)
                for i in chunk_sel
            ]
        ) if len(chunk_sel) else np.array([], dtype=np.int64)
        return full.take(idx)

    def _read_partition(
        self,
        type_name: str,
        p: PartitionMeta,
        cache: bool = True,
        chunk_sel=None,
    ) -> FeatureBatch:
        """``cache=False`` reads without pinning the batch in the
        per-type partition cache — the out-of-core streaming scan reads
        every partition exactly once, and pinning them would accumulate
        the whole dataset in host RAM (the thing that scan exists to
        avoid). ``chunk_sel`` reads only those chunks of a v2 partition
        (pruned row groups; never cached)."""
        st = self._types[type_name]
        if chunk_sel is not None:
            hit = self._cache_slice(st, p, chunk_sel)
            if hit is not None:
                return hit
        elif (p.gen, p.pid) in st.cache:
            return st.cache[(p.gen, p.pid)]
        with self._shared():  # never read a half-rewritten directory
            # chunk_sel only rides when set: monkeypatch/test doubles of
            # _read_part_table keep the legacy 3-arg call shape
            t = (
                self._read_part_table(type_name, p, chunk_sel=chunk_sel)
                if chunk_sel is not None
                else self._read_part_table(type_name, p)
            )
        # decode OUTSIDE the lock: _shared() is thread-exclusive
        # in-process (_mem_lock), and the Arrow->FeatureBatch conversion
        # is the heavy half — concurrent readers must overlap it
        return self._decode_part_table(
            type_name, p, t, cache and chunk_sel is None
        )

    def _read_partition_unlocked(
        self,
        type_name: str,
        p: PartitionMeta,
        cache: bool = False,
        chunk_sel=None,
    ) -> FeatureBatch:
        """Read + decode one partition file with NO locking — the caller
        must already hold the store lock (shared or exclusive) for the
        read's whole enclosing scan. This is the worker-thread read of
        the prefetch pipeline under a consumer-held lock (_query_locked,
        _read_all): workers beneath it must not touch the
        (thread-serializing) lock themselves, or the pipeline deadlocks
        against its own consumer."""
        st = self._types[type_name]
        if chunk_sel is not None:
            hit = self._cache_slice(st, p, chunk_sel)
            if hit is not None:
                return hit
        elif (p.gen, p.pid) in st.cache:
            return st.cache[(p.gen, p.pid)]
        t = (
            self._read_part_table(type_name, p, chunk_sel=chunk_sel)
            if chunk_sel is not None
            else self._read_part_table(type_name, p)
        )
        return self._decode_part_table(
            type_name, p, t, cache and chunk_sel is None
        )

    def _read_partition_prefetch(
        self, type_name: str, p: PartitionMeta, chunk_sel=None
    ) -> FeatureBatch:
        """Worker-thread partition read for the out-of-core stream.
        Guards against a mid-rewrite directory with the file lock ALONE:
        shared flock is concurrent across threads (each acquisition is
        its own fd, see locking.py), while _mem_lock — whose job is
        in-memory state, not files — would serialize the workers AND
        block every other thread's store use for the read's duration.
        Writers still exclude these reads via the exclusive flock. Never
        pins the partition cache (the streaming scan reads each
        partition exactly once)."""
        from geomesa_tpu.locking import file_lock

        st = self._types[type_name]
        if chunk_sel is not None:
            hit = self._cache_slice(st, p, chunk_sel)
            if hit is not None:
                return hit
        elif (p.gen, p.pid) in st.cache:
            return st.cache[(p.gen, p.pid)]
        # writer fence: touch (acquire+release) _mem_lock BEFORE taking
        # the shared flock. A same-process writer holds _mem_lock while
        # it polls for the exclusive flock; without the fence, N workers'
        # overlapping SH flocks give near-continuous coverage and the
        # non-blocking EX poll can starve into LockTimeout. With it, new
        # readers queue behind the writer, in-flight reads drain (each
        # bounded by one file), and the writer wins within ~one read.
        # (A writer in ANOTHER process has no such fence — it may wait
        # out in-flight reads up to its lock timeout, same flock
        # semantics as any concurrent reader fleet.)
        with self._mem_lock:
            pass
        with file_lock(self._lock_path, shared=True):
            t = (
                self._read_part_table(type_name, p, chunk_sel=chunk_sel)
                if chunk_sel is not None
                else self._read_part_table(type_name, p)
            )
        return self._decode_part_table(type_name, p, t, cache=False)

    def _read_partition_degradable(
        self, type_name: str, p: PartitionMeta, cache: bool = False,
        locked: bool = False,
    ):
        """Breaker-guarded partition read for the SERVING scan paths:
        transient errors retry on the worker (the ``io.*`` jittered,
        cumulative-capped budget), retries-exhausted and corrupt reads
        record a failure on THIS partition's circuit breaker and return
        a :class:`_PartFailure` sentinel (partition-scoped — the scan's
        pipeline and sibling partitions are untouched), and an OPEN
        breaker short-circuits the read entirely until its half-open
        probe. With ``resilience.degrade`` off this is exactly the
        plain read (errors propagate and fail the query loudly).
        ``locked`` selects the per-read-locking flavor
        (query_partitions holds no lock across its yields)."""
        from geomesa_tpu import resilience

        plain = (
            self._read_partition if locked else self._read_partition_unlocked
        )
        if not resilience.degrade_allowed():
            return plain(type_name, p, cache=cache)
        # breaker scope includes the store root: two stores (or a test
        # and its tmp sibling) with the same type name must not share
        # failure state
        br = resilience.partition_breaker(
            f"{self.root}:{type_name}", p.pid
        )
        if not br.allow():
            return _PartFailure(
                p,
                resilience.PartitionUnavailableError(
                    type_name, p.pid, "circuit breaker open"
                ),
            )
        from geomesa_tpu.store.prefetch import _with_retries

        read = _with_retries(lambda pp: plain(type_name, pp, cache=cache))
        try:
            batch = read(p)
        except FileNotFoundError:
            raise  # a real state (GC'd generation): refresh, not degrade
        except (OSError, PartitionCorruptError) as e:
            br.record_failure()
            return _PartFailure(p, e)
        br.record_success()
        return batch

    @staticmethod
    def _skip_part_failure(type_name: str, failure: "_PartFailure"):
        """Consumer half of the degradable read: note the degradation
        (header/audit stamping + metric) and log the skipped partition.
        Callers ``continue`` past the partition afterwards."""
        import logging

        from geomesa_tpu import resilience

        resilience.note_degraded("partition-unavailable")
        logging.getLogger(__name__).warning(
            "dataset %r partition %d unavailable (%s) -- serving "
            "DEGRADED result without it",
            type_name, failure.p.pid, failure.error,
        )

    def scan_lock_held(self) -> bool:
        """True when THIS thread holds the store's exclusive lock —
        prefetch consumers must then run their reads in-line (a worker
        thread's SH flock on a fresh fd conflicts with this process's
        held EX flock, and the worker cannot see the holder's
        thread-local depth)."""
        return getattr(self._lock_tl, "depth", 0) > 0

    def _row_groups_for(self, st, p: PartitionMeta, chunk_sel):
        """Row-group indices for a chunk-selective read, or None when
        the file cannot serve one (v1, ORC, or chunk stats without the
        write-time row-group record). v2 parquet writes size row groups
        to the chunk boundaries and record their byte sizes, so
        ``chunks align 1:1 with row groups`` holds by construction --
        the fsck chunk cross-check verifies it stays true on disk."""
        if chunk_sel is None:
            return None
        cs = p.chunks
        if (
            st.encoding != "parquet"
            or cs is None
            or cs.nbytes is None
            or len(cs.nbytes) != len(cs)
        ):
            return None
        return [int(i) for i in chunk_sel]

    @staticmethod
    def _slice_table_chunks(t, cs, chunk_sel):
        """Row-slice fallback for chunk-selective reads of files without
        aligned row groups (ORC): the whole table was read, only the
        selected chunks' rows survive to the (heavy) decode."""
        import pyarrow as pa

        slices = [
            t.slice(int(cs.starts[i]), int(cs.stops[i] - cs.starts[i]))
            for i in chunk_sel
        ]
        if not slices:
            return t.slice(0, 0)
        return pa.concat_tables(slices)

    def _read_part_table(
        self, type_name: str, p: PartitionMeta, chunk_sel=None
    ):
        """File -> Arrow table (timed; the prefetch pipeline's 'read'
        stage). Locking is the CALLER's concern. Honors the
        ``fail.read.*`` failpoints; under ``store.verify=always`` the
        raw bytes are checksummed against the manifest BEFORE parsing,
        and a mismatch quarantines this one partition and raises a
        loud :class:`PartitionCorruptError` (siblings keep serving).

        ``chunk_sel`` (v2 partitions) reads only the selected chunks:
        aligned parquet row groups skip the pruned chunks' file bytes
        outright (checksum verification, when on, still reads the whole
        file -- the checksum covers all bytes -- but only surviving row
        groups pay decompress/decode); other encodings read fully and
        row-slice before decode."""
        from geomesa_tpu import metrics
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.failpoints import fail_hit, fail_point

        st = self._types[type_name]
        if p.pid in st.quarantined:
            raise PartitionCorruptError(
                f"dataset {type_name!r} partition {p.pid} is quarantined: "
                f"{st.quarantined[p.pid]}"
            )
        path = self._part_path(type_name, p)
        fail_point("fail.read.io")  # transient: the prefetch retry path
        injected = fail_hit("fail.read.corrupt")
        verify = injected or sys_prop("store.verify") == "always"
        row_groups = self._row_groups_for(st, p, chunk_sel)
        import time as _time

        from geomesa_tpu import ledger
        from geomesa_tpu.tracing import span

        t_read = _time.perf_counter()
        with span("store.read", pid=p.pid, rows=int(p.count)) as sp, \
                metrics.io_read_seconds.time():
            if not verify:
                t = _read_table(path, st.encoding, row_groups=row_groups)
            else:
                with open(path, "rb") as fh:
                    data = fh.read()
                err = (
                    "injected corruption (failpoint fail.read.corrupt)"
                    if injected
                    else verify_bytes(data, p.checksum)
                    if p.checksum is not None
                    else None
                )
                if err:
                    self._quarantine(type_name, st, p, path, err)
                    raise PartitionCorruptError(
                        f"dataset {type_name!r} partition {p.pid} "
                        f"({path}): {err}"
                    )
                t = _parse_table(data, st.encoding, row_groups=row_groups)
            if chunk_sel is not None and row_groups is None:
                t = self._slice_table_chunks(t, p.chunks, chunk_sel)
        ledger.charge("read_seconds", _time.perf_counter() - t_read)
        try:
            if row_groups is not None and not verify:
                # pruned read: account the bytes actually fetched (the
                # selected row groups' manifest-recorded sizes), not the
                # file size -- the skipped remainder is the pruning win
                size = int(p.chunks.nbytes[list(chunk_sel)].sum())
            else:
                size = os.path.getsize(path)
            metrics.io_bytes_read.inc(size)
            ledger.charge("read_bytes", size)
            sp.set(bytes=int(size))
            if chunk_sel is not None:
                sp.set(chunks=len(chunk_sel), chunk_total=len(p.chunks))
                ledger.charge("chunks_read", len(chunk_sel))
                ledger.charge(
                    "chunks_pruned", len(p.chunks) - len(chunk_sel)
                )
        except OSError:
            pass
        return t

    def _decode_part_table(
        self, type_name: str, p: PartitionMeta, t, cache: bool
    ) -> FeatureBatch:
        """Arrow table -> FeatureBatch (timed; the pipeline's 'decode'
        stage), optionally pinning the partition cache."""
        from geomesa_tpu import metrics

        from geomesa_tpu.tracing import span

        import time as _time

        from geomesa_tpu import ledger

        st = self._types[type_name]
        t_dec = _time.perf_counter()
        with span("store.decode", pid=p.pid) as sp, \
                metrics.io_decode_seconds.time():
            batch = FeatureBatch.from_arrow(t, st.sft)
        ledger.charge("decode_seconds", _time.perf_counter() - t_dec)
        sp.set(rows=len(batch))
        if cache:
            st.cache[(p.gen, p.pid)] = batch
        return batch

    def _read_all(self, type_name: str) -> FeatureBatch:
        """Merge-read every partition through the prefetch pipeline
        (reads + Arrow decode on worker threads, concat in partition
        order). Callers hold the exclusive lock (flush/delete/rebuild),
        so the lock-free worker reads are safe."""
        from geomesa_tpu.store.prefetch import (
            batch_nbytes,
            prefetch_map,
        )

        st = self._types[type_name]
        return FeatureBatch.concat(
            list(
                prefetch_map(
                    lambda p: self._read_partition_unlocked(type_name, p),
                    st.partitions,
                    self.io,
                    size_of=batch_nbytes,
                )
            )
        )

    # -- queries -----------------------------------------------------------

    def plan(self, type_name: str, query: "Query | str | ast.Filter") -> QueryPlan:
        self.flush(type_name)
        with self._shared():
            self._refresh_from_disk(type_name)  # another process may have written
            return self._plan_locked(type_name, query)

    def _plan_locked(self, type_name: str, query) -> QueryPlan:
        st = self._types[type_name]
        if st.dirty and not st.pending:
            # another process's flush failed after unlinking the old files;
            # the data exists only in THAT process's memory. An empty
            # result here would be silent data loss -- fail loudly. (The
            # quarantined writer itself still has `pending` and may serve
            # and retry.)
            raise RuntimeError(
                f"dataset {type_name!r} is quarantined: a flush failed "
                "mid-rewrite in another process; retry there or restore "
                "the files"
            )
        ks = keyspace_for(st.sft, st.primary)
        return plan_query(
            st.sft,
            {st.primary: ks},
            as_query(query),
            data_interval=st.data_interval,
            stats=st.stats,
        )

    def _pruned_parts(self, type_name: str, plan: QueryPlan) -> list:
        """Partition-scheme leaf prune, then manifest key-range prune."""
        st = self._types[type_name]
        parts = st.partitions
        if st.scheme is not None:
            from geomesa_tpu.store.partitions import scheme_matches

            parts = [
                p
                for p in parts
                if p.leaf is None or scheme_matches(st.scheme, p.leaf, plan)
            ]
        if plan.ranges is not None:
            parts = [
                p for p in parts if any(p.overlaps(r) for r in plan.ranges)
            ]
        return parts

    def query_partitions(self, type_name: str, query=ast.Include):
        """Yield one filtered FeatureBatch per surviving partition (the
        Spark SpatialRDDProvider analog: 1 partition per range group, so
        callers can process partitions in parallel).

        Row-local post-processing (visibility filtering, projection)
        applies per partition; global sort / max-features do NOT -- they
        have cross-partition semantics, same as Spark RDD partitions.
        """
        import dataclasses

        st = self._types[type_name]
        plan = self.plan(type_name, query)
        ks = keyspace_for(st.sft, st.primary)
        inner_plan = dataclasses.replace(
            plan,
            query=Query(filter=plan.filter, hints={"internal_scan": True}),
        )
        # per-partition outer pass: visibility + projection, no sort/limit
        outer_plan = dataclasses.replace(
            plan,
            query=dataclasses.replace(
                plan.query, sort_by=None, max_features=None
            ),
        )
        from geomesa_tpu.query.runner import _post_process
        from geomesa_tpu.store.prefetch import batch_nbytes, prefetch_map

        parts = self._pruned_parts(type_name, plan)
        # read-ahead while the CALLER processes each yielded batch. No
        # lock is held across the yields (callers may write/flush between
        # partitions), so the workers go through the store's own LOCKED
        # per-read path — reads serialize briefly on the store lock,
        # decodes still overlap. If THIS thread holds the exclusive lock
        # (a maintenance job iterating partitions in-place), workers
        # would block forever on the consumer-held _mem_lock — degrade
        # to the in-line serial reads, whose _shared() short-circuits on
        # the re-entrant thread-local depth.
        batches = prefetch_map(
            lambda p: self._read_partition_degradable(
                type_name, p, cache=True, locked=True
            ),
            parts,
            0 if self.scan_lock_held() else self.io,
            size_of=batch_nbytes,
        )
        try:
            for p, batch in zip(parts, batches):
                if isinstance(batch, _PartFailure):
                    # bulk/export consumers must never silently lose a
                    # partition: the fault surfaces as a TYPED,
                    # partition-scoped error naming exactly what is
                    # unreachable (retries already exhausted on the
                    # worker) — not an anonymous pipeline teardown
                    from geomesa_tpu import resilience

                    raise resilience.PartitionUnavailableError(
                        type_name, batch.p.pid, str(batch.error)
                    ) from batch.error
                local = BuiltIndex(
                    ks,
                    batch,
                    {},
                    [PartitionMeta(0, 0, len(batch), p.key_lo, p.key_hi, len(batch))],
                )
                sub = run_query(local, inner_plan)
                if len(sub.batch):
                    out = _post_process(sub.batch, outer_plan)
                    if len(out):
                        if out is batch:
                            # the internal_scan alias fast path can surface
                            # the partition's (cache-pinned) batch itself
                            # when the outer post-process is a no-op — copy
                            # before yielding (same guard as _query_locked;
                            # `is batch` rather than scanning st.cache,
                            # which prefetch workers mutate concurrently)
                            out = out.take(np.arange(len(out)))
                        yield out
        finally:
            batches.close()

    def query(self, type_name: str, query: "Query | str | ast.Filter" = ast.Include) -> QueryResult:
        """Partition-pruned scan over parquet files. The SHARED lock is
        held across plan + every partition read, so a concurrent writer's
        in-place rewrite can neither unlink files mid-scan nor mix rows
        from two manifest generations into one result."""
        import time as _time

        from geomesa_tpu.tracing import span

        t0 = _time.perf_counter()
        with span("store.query", store="fs", type=type_name) as sp:
            # flush BEFORE the shared lock: exclusive if pending
            self.flush(type_name)
            with self._shared():
                res = self._query_locked(type_name, query, t0)
            sp.set(hits=len(res), scanned=res.scanned)
            return res

    def _query_locked(self, type_name: str, query, t0) -> QueryResult:
        import time as _time

        self._refresh_from_disk(type_name)
        st = self._types[type_name]
        plan = self._plan_locked(type_name, query)
        t1 = _time.perf_counter()
        parts = self._pruned_parts(type_name, plan)
        # scan each surviving file through the shared runner by wrapping it
        # as a single-partition BuiltIndex
        ks = keyspace_for(st.sft, st.primary)
        chunks = []
        scanned = 0
        # per-partition scans must not apply projection/sort/limit -- that
        # happens once, globally, after the merge
        import dataclasses

        inner_plan = dataclasses.replace(
            plan,
            query=Query(filter=plan.filter, hints={"internal_scan": True}),
        )
        from geomesa_tpu.conf import QueryTimeout, sys_prop
        from geomesa_tpu.store.prefetch import batch_nbytes, prefetch_map

        timeout_ms = sys_prop("query.timeout")
        deadline = t0 + timeout_ms / 1000.0 if timeout_ms else None
        # partition reads + Arrow decode run ahead on the prefetch
        # pipeline (this method executes under the held shared lock, so
        # the workers' lock-free reads are safe) while this thread scans;
        # cache=True keeps the partition-cache semantics of the serial
        # path. A deadline abort closes the pipeline (workers drained)
        # via the generator's finally.
        batches = prefetch_map(
            lambda p: self._read_partition_degradable(
                type_name, p, cache=True
            ),
            parts,
            self.io,
            size_of=batch_nbytes,
        )
        # FULL scans (Include, no ranges — notably the resident
        # DeviceIndex staging query) stream into buffers pre-sized from
        # the manifest's chunk/partition row counts instead of the
        # collect-then-concat path: one dataset copy instead of two at
        # peak, and zero-row partitions never touch the buffers
        sink = (
            _PresizedSink(st.sft, sum(int(q.count) for q in parts))
            if plan.filter is ast.Include
            and plan.ranges is None
            and len(parts) > 1
            else None
        )
        sources = []  # the read batch behind each chunk (alias guard)
        try:
            for p, batch in zip(parts, batches):
                if deadline and _time.perf_counter() > deadline:
                    raise QueryTimeout(
                        f"query on {type_name!r} exceeded {timeout_ms}ms"
                    )
                if isinstance(batch, _PartFailure):
                    from geomesa_tpu import resilience

                    if resilience.capture_degraded() is None:
                        # no request collector to stamp: a library/CLI
                        # caller would get a SILENT partial — fail with
                        # the typed partition-scoped error instead (the
                        # serving path installs collect_degraded and
                        # rides the branch below)
                        raise resilience.PartitionUnavailableError(
                            type_name, batch.p.pid, str(batch.error)
                        ) from batch.error
                    # partition-scoped fault: serve the siblings, stamp
                    # the result degraded (never a silent partial)
                    self._skip_part_failure(type_name, batch)
                    continue
                scanned += len(batch)
                local = BuiltIndex(
                    ks,
                    batch,
                    {},
                    [
                        PartitionMeta(
                            0, 0, len(batch), p.key_lo, p.key_hi, len(batch)
                        )
                    ],
                )
                sub = run_query(local, inner_plan)
                if len(sub.batch):
                    if sink is not None:
                        sink.add(sub.batch)  # copies; batch drops now
                    else:
                        chunks.append(sub.batch)
                        sources.append(batch)
        finally:
            batches.close()
        total = sum(p.count for p in st.partitions)
        if sink is not None and sink.filled:
            out = sink.finish()
        elif chunks:
            if len(chunks) == 1:
                out = chunks[0]
                if out is sources[0]:
                    # the aliasing fast path above only holds WITHIN this
                    # scan: a single-chunk full match would hand the
                    # (cache-pinned) partition batch to the caller — copy.
                    # Checked against the scan's OWN source list: another
                    # thread's prefetch workers mutate st.cache lock-free,
                    # so iterating st.cache.values() here would race.
                    out = out.take(np.arange(len(out)))
            else:
                out = FeatureBatch.concat(chunks)
        else:
            empty = self._read_partition(type_name, st.partitions[0]).take(
                np.array([], dtype=np.int64)
            ) if st.partitions else FeatureBatch.from_columns(
                st.sft, {a.name: [] for a in st.sft.attributes}
            )
            out = empty
        from geomesa_tpu.query.runner import _post_process
        from geomesa_tpu.audit import observe_query

        out = _post_process(out, plan)
        result = QueryResult(out, plan, scanned, total)
        observe_query(
            "fs", type_name, plan, t0, t1, _time.perf_counter(), result,
            self.audit_writer,
        )
        return result

    def explain(self, type_name: str, query) -> str:
        return self.plan(type_name, query).explain()

    # -- aggregation pushdown (partition format v2) ------------------------

    def manifest_rows(self, type_name: str) -> int:
        """Total rows recorded by the manifest (== file rows by the
        manifest contract) — the pre-size hint resident staging and the
        pushdown paths consume without reading any file."""
        return int(sum(p.count for p in self._types[type_name].partitions))

    def has_chunk_stats(self, type_name: str) -> bool:
        """True when every partition of ``type_name`` carries v2 chunk
        statistics, i.e. aggregate pushdown can answer bbox+time shapes
        without row scans. The server's brownout rung consults this —
        over a v1/legacy dataset the 'pre-aggregate' path would quietly
        row-scan, the opposite of a brownout."""
        st = self._types.get(type_name)
        if st is None:
            return False
        # snapshot: flush replaces st.partitions wholesale, never mutates
        return all(p.chunks is not None for p in list(st.partitions))

    def count(self, type_name: str, query=ast.Include) -> int:
        """Filtered count; bbox+time-shaped filters on a v2 store are
        answered from chunk pre-aggregates (interior chunks from the
        manifest, boundary chunks row-refined — bit-identical to the
        row scan, proven by the parity tests) without reading interior
        rows. Anything the chunk stats cannot decide exactly falls back
        to the full query path. Pushdown-served counts are audited and
        counted exactly like scanned ones."""
        import time as _time

        from geomesa_tpu.audit import observe_query
        from geomesa_tpu.store.pushdown import count_pushdown

        t0 = _time.perf_counter()
        self.flush(type_name)
        with self._shared():
            self._refresh_from_disk(type_name)
            t1 = _time.perf_counter()
            out = count_pushdown(self, type_name, query)
        if out is not None:
            n, plan = out
            observe_query(
                "fs", type_name, plan, t0, t1, _time.perf_counter(),
                _Sized(n), self.audit_writer,
            )
            return n
        return len(self.query(type_name, query))

    def density_pushdown(
        self, type_name: str, query, envelope, width: int, height: int
    ):
        """Chunk-granular density grid (see store/pushdown.py), or None
        when the query needs the row-scan path. Interior chunks prorate
        their coarse world-grid histograms onto the raster; boundary
        chunks read + rasterize exactly — total mass matches the row
        scan, per-pixel placement is within coarse-cell tolerance."""
        from geomesa_tpu.store.pushdown import density_pushdown

        self.flush(type_name)
        with self._shared():
            self._refresh_from_disk(type_name)
            return density_pushdown(
                self, type_name, query, envelope, width, height
            )

    def stats_pushdown(self, type_name: str, query, stat_spec: str):
        """Stat-DSL aggregation from chunk partials (Count/MinMax specs
        with bbox+time filters; exact — interior chunks merge their
        manifest sketches, boundary chunks observe their rows), or None
        for the row-scan path."""
        from geomesa_tpu.store.pushdown import stats_pushdown

        self.flush(type_name)
        with self._shared():
            self._refresh_from_disk(type_name)
            return stats_pushdown(self, type_name, query, stat_spec)

    def verify_chunk_stats(self, type_name: str) -> "list[tuple]":
        """fsck's chunk-stat cross-check: decode every v2 partition and
        recompute per-chunk row counts, key min/max, bbox, time range,
        density-cell mass and MinMax partials against the manifest (plus
        parquet row-group alignment). Returns ``[(pid, chunk, error)]``
        for every drifted record — drift means pruning/pushdown could
        return wrong answers, so fsck fails nonzero on it."""
        from geomesa_tpu.store.pushdown import verify_chunk_stats

        with self._shared():
            self._refresh_from_disk(type_name)
            return verify_chunk_stats(self, type_name)



