"""BatchStore: a minimal read-only store over one in-memory FeatureBatch.

The resident-cache-first deployment shape: when a DeviceIndex serves every
query from HBM, the host-side sorted indexes a MemoryDataStore builds at
flush are pure overhead — this store holds ONLY the batch and the schema,
so ``DeviceIndex(BatchStore(batch))`` stages directly with no host index
build. (Ref role: the reference's in-memory/lambda layers keep a backing
collection the iterators scan; here the "iterator" is the resident cache
itself — SURVEY section 2.3 in-memory store row [UNVERIFIED - empty
reference mount].) bench.py uses it to measure the serving path without
paying for host structures the measured path never touches.

Only full scans (Include) are served; anything else raises — filtered
queries belong to the DeviceIndex staged on top (or a real store).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.query.plan import as_query
from geomesa_tpu.query.runner import QueryResult


class BatchStore:
    """Read-only single-type store over a FeatureBatch (no host indexes)."""

    def __init__(self, batch: FeatureBatch, type_name: "str | None" = None):
        self.batch = batch
        self.sft: SimpleFeatureType = batch.sft
        self.type_name = type_name or self.sft.type_name

    @property
    def type_names(self) -> list:
        return [self.type_name]

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        if type_name != self.type_name:
            raise KeyError(type_name)
        return self.sft

    def query(self, type_name: str, query=ast.Include) -> QueryResult:
        if type_name != self.type_name:
            raise KeyError(type_name)
        q = as_query(query)
        f = q.filter if q.filter is not None else ast.Include
        if f is not ast.Include:
            raise NotImplementedError(
                "BatchStore serves full scans only; stage a DeviceIndex on "
                "top (or use a real store) for filtered queries"
            )
        batch = self.batch
        if not q.hints.get("raw_visibility"):
            from geomesa_tpu.security import filter_by_visibility

            keep = filter_by_visibility(batch, q.hints.get("auths"))
            if keep is not None:
                batch = batch.take(np.nonzero(keep)[0])
        # no planner ran: there is nothing to explain on a full scan
        return QueryResult(
            batch=batch, plan=None, scanned=len(batch), total=len(self.batch)
        )
