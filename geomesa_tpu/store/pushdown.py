"""Aggregation pushdown over chunk statistics (partition format v2).

The reference system answers density/count/stats queries SERVER-SIDE
(geomesa-accumulo DensityIterator / StatsIterator: aggregates computed
next to the data, features never shipped [UNVERIFIED - empty reference
mount]). The rebuild's equivalent of "next to the data" is the manifest:
v2 partitions carry per-chunk pre-aggregates (store/chunkstats.py), so a
bbox+time aggregate decomposes as

- **interior** chunks (bbox inside one query envelope, time range inside
  one interval): answered from the manifest summary -- rows never read,
- **boundary** chunks: read (chunk-selective, pruned row groups) and
  refined at row level with the exact filter,
- **disjoint** chunks: skipped.

Count and stats (Count/MinMax specs) are EXACT under this split -- an
interior chunk's row count and MinMax partial are the truth for its
rows, and the boundary refinement applies the same filter the row scan
would. Density is exact in total mass and within coarse-cell tolerance
in placement (interior cells prorate uniformly within a world-grid
cell); the parity tests pin both properties.

Routing: the planner computes :func:`query.plan.aggregate_bounds`
(``QueryPlan.agg_bounds``) -- None means the filter has structure chunk
stats cannot decide and everything falls back to the row scan. The
``store.chunk.pushdown`` property and a per-query
``hints={"agg.pushdown": False}`` veto complete the three knobs.

All entry points REQUIRE the store's shared lock to be held by the
caller (they read partition files mid-plan); the FileSystemDataStore
methods (``count``/``density_pushdown``/``stats_pushdown``) wrap them
accordingly.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.index.api import BuiltIndex, PartitionMeta
from geomesa_tpu.index.keyspaces import keyspace_for
from geomesa_tpu.store import chunkstats as cks

#: query hints that cannot change a pushdown answer -- anything else
#: (sampling, projection hooks, unknown extensions) forces the row scan
_INERT_HINTS = frozenset({"auths", "internal", "agg.pushdown"})


def _plan_for(store, type_name: str, query):
    from geomesa_tpu.query.plan import as_query, is_aggregate_shape

    q = as_query(query)
    if q.hints.get("agg.pushdown") is False:
        return None, q
    if q.max_features is not None or q.properties:
        return None, q  # caps/projections have row-level semantics
    if any(k not in _INERT_HINTS for k in q.hints):
        return None, q
    from geomesa_tpu.conf import sys_prop

    if not sys_prop("store.chunk.pushdown"):
        return None, q
    # structural pre-screen BEFORE planning: an attribute/OR/NOT filter
    # can never push down, and planning it here just to discard the
    # plan would double the fallback's planning cost (the row-scan
    # path plans again). Interceptors may rewrite the query during
    # planning, but only ever toward MORE structure (caps, rewrites),
    # which the post-plan agg_bounds/max_features checks still catch.
    if not is_aggregate_shape(q.parsed(), store.get_schema(type_name)):
        return None, q
    plan = store._plan_locked(type_name, q)
    if plan.agg_bounds is None:
        return None, q
    if plan.query.max_features is not None:
        # an interceptor (global query.max.features) capped the query
        # during planning: caps have row-level semantics
        return None, q
    return plan, q


def _eligible_parts(store, type_name: str, plan):
    """The pruned partition list when EVERY surviving partition carries
    chunk stats and none holds visibility-labeled rows (pushdown cannot
    see labels, so it must not skip rows a visibility filter would
    hide). None = fall back."""
    parts = store._pruned_parts(type_name, plan)
    for p in parts:
        if p.chunks is None or p.chunks.has_vis:
            return None
    return parts


def _classify(plan, cs):
    envs, ivals = plan.agg_bounds
    return cks.classify(cs, envs, ivals)


def _refine_batch(store, type_name: str, p, sel, plan, ks):
    """Read the boundary chunks of one partition (chunk-selective) and
    return the rows surviving the EXACT filter -- the same single-
    partition runner wrap the row-scan query path uses."""
    import dataclasses

    from geomesa_tpu.query.plan import Query
    from geomesa_tpu.query.runner import run_query

    batch = store._read_partition_unlocked(
        type_name, p, cache=False, chunk_sel=sel
    )
    inner_plan = dataclasses.replace(
        plan,
        query=Query(filter=plan.filter, hints={"internal_scan": True}),
    )
    local = BuiltIndex(
        ks,
        batch,
        {},
        [PartitionMeta(0, 0, len(batch), p.key_lo, p.key_hi, len(batch))],
    )
    return run_query(local, inner_plan).batch


def _boundary_sel(plan, cs, klass) -> list:
    """Boundary-chunk indices, additionally Z-range pruned: a chunk can
    meet the query's bbox without containing any key the planner's
    ranges cover."""
    sel = np.nonzero(klass == cks.BOUNDARY)[0]
    if len(sel) and plan.ranges is not None:
        keep = cks.chunks_overlapping(cs, plan.ranges)
        sel = sel[keep[sel]]
    return [int(i) for i in sel]


def count_pushdown(store, type_name: str, query) -> "tuple | None":
    """Exact filtered count from chunk pre-aggregates as ``(count,
    plan)``, or None for the row-scan fallback. Caller holds the
    store's shared lock (and audits the answer — a pushdown-served
    count must appear in the audit log exactly like a scanned one)."""
    from geomesa_tpu import metrics
    from geomesa_tpu.tracing import span

    plan, q = _plan_for(store, type_name, query)
    if plan is None:
        return None
    parts = _eligible_parts(store, type_name, plan)
    if parts is None:
        metrics.agg_pushdown_fallbacks.inc(kind="count")
        return None
    st = store._types[type_name]
    ks = keyspace_for(st.sft, st.primary)
    total = 0
    pre_rows = 0
    refined_chunks = 0
    with span("agg.pushdown", kind="count", type=type_name) as sp:
        for p in parts:
            cs = p.chunks
            klass = _classify(plan, cs)
            interior = int(cs.rows[klass == cks.INTERIOR].sum())
            total += interior
            pre_rows += interior
            sel = _boundary_sel(plan, cs, klass)
            if sel:
                refined_chunks += len(sel)
                total += len(
                    _refine_batch(store, type_name, p, sel, plan, ks)
                )
        sp.set(rows_preagg=pre_rows, chunks_refined=refined_chunks)
    metrics.agg_pushdown_queries.inc(kind="count")
    metrics.agg_pushdown_rows.inc(pre_rows)
    if refined_chunks:
        metrics.agg_pushdown_chunks_refined.inc(refined_chunks)
    return int(total), plan


def density_pushdown(
    store, type_name: str, query, envelope, width: int, height: int
) -> "np.ndarray | None":
    """(height, width) float32 density grid from chunk pre-aggregates,
    or None for the row-scan fallback. Caller holds the shared lock.

    Density is the tolerant aggregate (the caller asked for a raster,
    not rows), so the read-avoidance bar is lower than count's: a chunk
    whose TIME range is fully inside a query interval is answered
    entirely from its coarse world-grid cells — cells inside the
    envelope count fully (exact), cells straddling the envelope/raster
    edge prorate by area overlap (the uniform-within-cell assumption).
    No read, regardless of the chunk's spatial extent. Only chunks whose
    time range straddles an interval boundary descend to row-level
    refinement (their cells cannot say WHICH rows are in-interval);
    chunks disjoint in space or time are skipped. With an
    envelope/raster aligned to the coarse grid there are no straddling
    cells and the result is mass-exact; otherwise edge cells carry the
    documented grid-cell tolerance."""
    from geomesa_tpu import metrics
    from geomesa_tpu.tracing import span

    plan, q = _plan_for(store, type_name, query)
    if plan is None:
        return None
    sft = store.get_schema(type_name)
    geom = sft.geom_field
    if geom is None or not sft.descriptor(geom).is_point:
        return None  # coarse cells count point locations only
    parts = _eligible_parts(store, type_name, plan)
    if parts is None:
        metrics.agg_pushdown_fallbacks.inc(kind="density")
        return None
    grid_n = None
    for p in parts:
        g = p.chunks.grid
        if grid_n is None:
            grid_n = g
        elif g != grid_n:
            # mixed grids (a store.chunk.grid change mid-history): the
            # proration matrices assume one resolution — row scan
            metrics.agg_pushdown_fallbacks.inc(kind="density")
            return None
    envs, ivals = plan.agg_bounds
    st = store._types[type_name]
    ks = keyspace_for(st.sft, st.primary)
    out = np.zeros((height, width), dtype=np.float32)
    coarse = None
    pre_rows = 0
    refined_chunks = 0
    with span("agg.pushdown", kind="density", type=type_name) as sp:
        for p in parts:
            cs = p.chunks
            klass = _classify(plan, cs)  # spatial+time, for DISJOINT
            t_klass = cks.classify(cs, None, ivals)  # time alone
            for ci in range(len(cs)):
                if klass[ci] == cks.DISJOINT:
                    continue
                if (
                    t_klass[ci] == cks.INTERIOR
                    and (len(cs.cells[ci]) or not cs.rows[ci])
                    # a non-finite bbox means NaN coordinates polluted
                    # the chunk's cell histogram at build time: those
                    # rows must row-refine (the exact path drops NaN
                    # rows from the raster; the cells cannot)
                    and (
                        cs.bbox is None
                        or bool(np.isfinite(cs.bbox[ci]).all())
                    )
                ):
                    if coarse is None:
                        coarse = np.zeros(
                            grid_n * grid_n, dtype=np.float64
                        )
                    coarse[cs.cells[ci]] += cs.cell_counts[ci]
                    pre_rows += int(cs.rows[ci])
                    klass[ci] = cks.INTERIOR  # answered; never refine
                else:
                    # time straddles (or a drifted manifest lost the
                    # histogram): row-level refinement, never mass loss
                    klass[ci] = cks.BOUNDARY
            sel = _boundary_sel(plan, cs, klass)
            if sel:
                refined_chunks += len(sel)
                hits = _refine_batch(store, type_name, p, sel, plan, ks)
                if len(hits):
                    from geomesa_tpu.process.density import _density_host

                    x, y = hits.point_coords()
                    out += _density_host(
                        x, y, np.ones(len(hits)), envelope, width, height
                    )
        if coarse is not None:
            out += _cells_to_raster(
                coarse.reshape(grid_n, grid_n),
                grid_n,
                envs,
                envelope,
                width,
                height,
            )
        sp.set(rows_preagg=pre_rows, chunks_refined=refined_chunks)
    metrics.agg_pushdown_queries.inc(kind="density")
    metrics.agg_pushdown_rows.inc(pre_rows)
    if refined_chunks:
        metrics.agg_pushdown_chunks_refined.inc(refined_chunks)
    return out


def _cells_to_raster(coarse, grid_n, envs, envelope, width, height):
    """Pre-aggregated cells -> raster: restrict the coarse counts to the
    query envelopes (cells fully outside drop, straddling cells keep the
    overlapping area fraction — uniform-within-cell), then prorate onto
    the raster pixels."""
    if envs is not None:
        frac = np.zeros((grid_n, grid_n), dtype=np.float64)
        for e in envs:
            fx = cks._overlap_matrix(
                grid_n, cks.WORLD[0], cks.WORLD[2], e.xmin, e.xmax, 1
            )[:, 0]
            fy = cks._overlap_matrix(
                grid_n, cks.WORLD[1], cks.WORLD[3], e.ymin, e.ymax, 1
            )[:, 0]
            frac = np.maximum(frac, fy[:, None] * fx[None, :])
        coarse = coarse * np.clip(frac, 0.0, 1.0)
    return cks.prorate_coarse(coarse, grid_n, envelope, width, height)


def stats_pushdown(
    store, type_name: str, query, stat_spec: str
):
    """SeqStat from chunk partials for Count/MinMax specs (exact), or
    None for the row-scan fallback. Caller holds the shared lock."""
    from geomesa_tpu import metrics
    from geomesa_tpu.stats.dsl import parse_stat
    from geomesa_tpu.stats.sketches import CountStat, MinMax, stat_from_json
    from geomesa_tpu.tracing import span

    seq = parse_stat(stat_spec)
    if not all(isinstance(s, (CountStat, MinMax)) for s in seq.stats):
        return None  # only the sketches chunk partials carry
    plan, q = _plan_for(store, type_name, query)
    if plan is None:
        return None
    covered = {
        rec["attr"]
        for p in store._types[type_name].partitions
        if p.chunks is not None
        for part in p.chunks.partials[:1]
        for rec in part
    }
    for s in seq.stats:
        if isinstance(s, MinMax) and s.attr not in covered:
            return None  # no partial recorded for this attribute
    parts = _eligible_parts(store, type_name, plan)
    if parts is None:
        metrics.agg_pushdown_fallbacks.inc(kind="stats")
        return None
    st = store._types[type_name]
    ks = keyspace_for(st.sft, st.primary)
    pre_rows = 0
    refined_chunks = 0
    with span("agg.pushdown", kind="stats", type=type_name) as sp:
        for p in parts:
            cs = p.chunks
            klass = _classify(plan, cs)
            for ci in np.nonzero(klass == cks.INTERIOR)[0]:
                rows = int(cs.rows[ci])
                pre_rows += rows
                partial = {
                    rec["attr"]: rec for rec in cs.partials[ci]
                }
                for s in seq.stats:
                    if isinstance(s, CountStat):
                        s.count += rows
                    else:
                        rec = partial.get(s.attr)
                        if rec is not None:
                            s.merge(stat_from_json(rec))
            sel = _boundary_sel(plan, cs, klass)
            if sel:
                refined_chunks += len(sel)
                hits = _refine_batch(store, type_name, p, sel, plan, ks)
                if len(hits):
                    seq.observe_batch(hits)
        sp.set(rows_preagg=pre_rows, chunks_refined=refined_chunks)
    metrics.agg_pushdown_queries.inc(kind="stats")
    metrics.agg_pushdown_rows.inc(pre_rows)
    if refined_chunks:
        metrics.agg_pushdown_chunks_refined.inc(refined_chunks)
    return seq


# -- fsck cross-check --------------------------------------------------------


def verify_chunk_stats(store, type_name: str) -> "list[tuple]":
    """Cross-check every v2 partition's chunk statistics against its
    decoded rows: per-chunk row counts, key min/max (recomputed through
    the key space), bbox, time range, density-cell mass and MinMax
    partials, plus parquet row-group alignment. Returns
    ``[(pid, chunk_index, error)]`` -- drifted stats mean pruning and
    pushdown could silently return wrong answers. Caller holds the
    shared lock (the fs method wraps this)."""
    from geomesa_tpu import metrics

    st = store._types[type_name]
    ks = keyspace_for(st.sft, st.primary)
    errors: list = []

    def drift(pid, ci, msg):
        errors.append((pid, ci, msg))
        metrics.store_chunk_stat_drift.inc()

    for p in st.partitions:
        cs = p.chunks
        if cs is None:
            continue
        if cs.total_rows != int(p.count):
            drift(p.pid, -1, (
                f"chunk rows sum {cs.total_rows} != partition count "
                f"{int(p.count)}"
            ))
            continue
        if st.encoding == "parquet" and cs.nbytes is not None:
            import pyarrow.parquet as pq

            md = pq.ParquetFile(
                store._part_path(type_name, p)
            ).metadata
            if md.num_row_groups != len(cs):
                drift(p.pid, -1, (
                    f"{md.num_row_groups} row groups != {len(cs)} chunks"
                ))
                continue
            for i in range(md.num_row_groups):
                if md.row_group(i).num_rows != int(cs.rows[i]):
                    drift(p.pid, i, (
                        f"row group rows {md.row_group(i).num_rows} != "
                        f"chunk rows {int(cs.rows[i])}"
                    ))
        batch = store._read_partition_unlocked(type_name, p, cache=False)
        if len(batch) != int(p.count):
            drift(p.pid, -1, (
                f"file rows {len(batch)} != partition count {int(p.count)}"
            ))
            continue
        keys = ks.index_keys(batch)
        key_cols = [keys[c] for c in ks.key_columns]
        geom = st.sft.geom_field
        dtg = st.sft.dtg_field
        xy = None
        if geom is not None and len(batch):
            col = batch.columns[geom]
            if col.dtype != object:
                xy = (col[:, 0], col[:, 1])
        for ci in range(len(cs)):
            s, e = int(cs.starts[ci]), int(cs.stops[ci])
            if e <= s:
                continue
            lo = cks._key_tuple(key_cols, s)
            hi = cks._key_tuple(key_cols, e - 1)
            if lo != tuple(cs.key_lo[ci]) or hi != tuple(cs.key_hi[ci]):
                drift(p.pid, ci, (
                    f"key span {lo}..{hi} != manifest "
                    f"{tuple(cs.key_lo[ci])}..{tuple(cs.key_hi[ci])}"
                ))
            if xy is not None and cs.bbox is not None:
                x, y = xy[0][s:e], xy[1][s:e]
                want = cs.bbox[ci]
                got = (x.min(), y.min(), x.max(), y.max())
                # equal_nan: a NaN-coordinate chunk legitimately records
                # a NaN bbox (classified BOUNDARY, never pruned away)
                if not np.allclose(got, want, equal_nan=True):
                    drift(p.pid, ci, f"bbox {got} != manifest {tuple(want)}")
                if len(cs.cells) > ci and len(cs.cells[ci]):
                    mass = int(cs.cell_counts[ci].sum())
                    if mass != e - s:
                        drift(p.pid, ci, (
                            f"density cell mass {mass} != chunk rows {e - s}"
                        ))
            if dtg is not None and cs.time_range is not None:
                d = np.asarray(batch.column(dtg))[s:e]
                t0, t1 = int(d.min()), int(d.max())
                if (t0, t1) != (
                    int(cs.time_range[ci][0]), int(cs.time_range[ci][1])
                ):
                    drift(p.pid, ci, (
                        f"time range ({t0}, {t1}) != manifest "
                        f"{tuple(int(v) for v in cs.time_range[ci])}"
                    ))
            for rec in cs.partials[ci]:
                col = np.asarray(batch.column(rec["attr"]))[s:e]
                if not (
                    np.isclose(float(col.min()), float(rec["min"]))
                    and np.isclose(float(col.max()), float(rec["max"]))
                ):
                    drift(p.pid, ci, (
                        f"minmax({rec['attr']}) "
                        f"({col.min()}, {col.max()}) != manifest "
                        f"({rec['min']}, {rec['max']})"
                    ))
    return errors
