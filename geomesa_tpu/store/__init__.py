"""DataStore API surface (maps reference L6 + L1).

- ``memory``: in-memory columnar store -- the TestGeoMesaDataStore analog
              (ref: geomesa-index-api src/test TestGeoMesaDataStore; SURVEY
              section 4 calls this the most important testing idea)
- ``fs``:     Parquet filesystem store (ref: geomesa-fs)
- ``kv``:     sorted key-value store family -- one IndexAdapter over
              pluggable sorted-KV engines (ref: geomesa-accumulo /
              geomesa-hbase / geomesa-redis / geomesa-cassandra /
              geomesa-bigtable adapters)
- ``oocscan``: out-of-core streamed device scan over a partitioned
              store (datasets larger than HBM; ref: Accumulo iterators
              stream tablets)
- ``prefetch``: the shared host-I/O pipeline feeding it — ordered
              threaded partition read/decode/stage with bounded
              read-ahead (ref: Accumulo BatchScanner readahead)
"""

from geomesa_tpu.store.fs import FileSystemDataStore, PartitionCorruptError
from geomesa_tpu.store.kv import KVDataStore, MemoryKV, SqliteKV
from geomesa_tpu.store.memory import MemoryDataStore
from geomesa_tpu.store.oocscan import SlabStream, StreamedDeviceScan
from geomesa_tpu.store.prefetch import PrefetchConfig, prefetch_map

__all__ = [
    "FileSystemDataStore",
    "KVDataStore",
    "MemoryKV",
    "MemoryDataStore",
    "PartitionCorruptError",
    "PrefetchConfig",
    "SlabStream",
    "SqliteKV",
    "StreamedDeviceScan",
    "prefetch_map",
]
