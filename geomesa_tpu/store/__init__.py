"""DataStore API surface (maps reference L6 + L1).

- ``api``:    store protocol + feature writer
              (ref: geomesa-index-api .../index/geotools/GeoMesaDataStore)
- ``memory``: in-memory columnar store -- the TestGeoMesaDataStore analog
              (ref: geomesa-index-api src/test TestGeoMesaDataStore; SURVEY
              section 4 calls this the most important testing idea)
- ``fs``:     Parquet filesystem store (ref: geomesa-fs)
"""

from geomesa_tpu.store.memory import MemoryDataStore

__all__ = ["MemoryDataStore"]
