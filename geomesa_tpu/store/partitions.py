"""Named filesystem partition schemes.

Ref role: geomesa-fs storage/api/PartitionScheme + the stock schemes in
common/partitions (Z2Scheme, XZ2Scheme, DateTimeScheme, AttributeScheme and
composites like ``hourly,z2-2bit``) [UNVERIFIED - empty reference mount].
A scheme maps each feature to a directory-leaf string and, at query time,
decides whether an existing leaf can contain matching features (the
partition prune). Unlike the reference's eager "filter -> partition list"
enumeration, pruning here is a per-existing-leaf ``matches`` test -- same
outcome, no range-explosion cap needed.

Scheme spec strings (SFT user data ``geomesa.fs.partition-scheme``):

- ``z2-<n>bit[s]``   -- point grid cells, n total z bits (n/2 per dim)
- ``xz2-<n>bit[s]``  -- non-point extent cells at XZ2 precision n
- ``xz3-<n>bit[s]``  -- non-point extent + week-bin time cells (XZ3)
- ``yearly | monthly | weekly | daily | hourly | minute`` -- dtg buckets
- ``attribute:<name>`` -- one leaf per attribute value
- comma-joined composites, e.g. ``daily,z2-2bit`` (leaf paths nest)
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.xz2 import XZ2SFC
from geomesa_tpu.filter import ast
from geomesa_tpu.geom import Envelope

USER_DATA_KEY = "geomesa.fs.partition-scheme"

# -- partition file naming ---------------------------------------------------
#
# Crash-consistent flushes write each rewrite as a fresh GENERATION of
# files next to the previous one (`part-<gen>-NNNNN.<enc>`), publish the
# manifest atomically, then GC the old generation; the legacy un-scoped
# form (`part-NNNNN.<enc>`) is still read from pre-generation stores.
# Names are only ever PRODUCED (here) and matched by prefix in the
# recovery sweep — the sweep deliberately reclaims anything `part-`ish
# that the manifest does not reference, well-formed or not.


def part_file_name(pid: int, encoding: str, gen: "str | None" = None) -> str:
    """Partition file name: generation-scoped when ``gen`` is set, the
    legacy un-scoped form otherwise."""
    if gen:
        return f"part-{gen}-{pid:05d}.{encoding}"
    return f"part-{pid:05d}.{encoding}"


class PartitionScheme:
    """Base: subclasses define spec, depth (leaf path segments), leaves()
    and matches()."""

    spec: str
    depth: int = 1

    def leaves(self, batch) -> np.ndarray:
        raise NotImplementedError

    def matches(self, leaf: str, geom_bounds, time_bounds) -> bool:
        """May this leaf contain features satisfying the extracted bounds?
        Conservative: True when the scheme cannot tell."""
        raise NotImplementedError

    def validate(self, sft) -> None:
        """Fail fast at schema-bind time when the SFT cannot support the
        scheme (checked by create_schema, before any writes)."""


# -- datetime ----------------------------------------------------------------

_STEPS = {
    # step -> (numpy datetime64 unit, leaf path segments)
    "yearly": ("Y", 1),
    "monthly": ("M", 2),
    "daily": ("D", 3),
    "hourly": ("h", 4),
    "minute": ("m", 5),
}

_WEEK_MS = 7 * 86400 * 1000


@dataclass
class DateTimeScheme(PartitionScheme):
    """dtg-bucket leaves: ``2020/01/05`` (daily), ``2020/01/05/13``
    (hourly), ... Weekly uses epoch-week leaves ``W2609`` (the same
    week-binning as the Z3 curve's BinnedTime)."""

    step: str

    def __post_init__(self):
        if self.step != "weekly" and self.step not in _STEPS:
            raise ValueError(f"unknown datetime step {self.step!r}")
        self.spec = self.step
        self.depth = 1 if self.step == "weekly" else _STEPS[self.step][1]

    def validate(self, sft) -> None:
        if sft.dtg_field is None:
            raise ValueError(
                f"datetime partition scheme {self.step!r} needs a Date field"
            )

    def _dtg_col(self, batch) -> np.ndarray:
        dtg = batch.sft.dtg_field
        if dtg is None:
            raise ValueError("datetime partition scheme needs a Date field")
        return np.asarray(batch.column(dtg), dtype=np.int64)

    def leaves(self, batch) -> np.ndarray:
        ms = self._dtg_col(batch)
        if self.step == "weekly":
            weeks = ms // _WEEK_MS
            return np.array([f"W{w}" for w in weeks], dtype=object)
        unit = _STEPS[self.step][0]
        strs = np.datetime_as_string(
            ms.astype("datetime64[ms]").astype(f"datetime64[{unit}]")
        )
        return np.array(
            [
                s.replace("-", "/").replace("T", "/").replace(":", "/")
                for s in strs
            ],
            dtype=object,
        )

    def _bucket_ms(self, leaf: str) -> "tuple[int, int]":
        if self.step == "weekly":
            w = int(leaf[1:])
            return w * _WEEK_MS, (w + 1) * _WEEK_MS
        unit = _STEPS[self.step][0]
        parts = leaf.split("/")
        iso = parts[0]
        if len(parts) > 1:
            iso += "-" + parts[1]
        if len(parts) > 2:
            iso += "-" + parts[2]
        if len(parts) > 3:
            iso += "T" + parts[3]
        if len(parts) > 4:
            iso += ":" + parts[4]
        start = np.datetime64(iso, unit)
        return (
            int(start.astype("datetime64[ms]").astype(np.int64)),
            int((start + 1).astype("datetime64[ms]").astype(np.int64)),
        )

    def matches(self, leaf: str, geom_bounds, time_bounds) -> bool:
        if time_bounds is None or time_bounds.unbounded:
            return True
        lo, hi = self._bucket_ms(leaf)  # [lo, hi)
        for t0, t1 in time_bounds.values:
            if t0 < hi and t1 >= lo:
                return True
        return False


# -- z2 grid -----------------------------------------------------------------


@dataclass
class Z2Scheme(PartitionScheme):
    """Point-grid leaves: the feature's z2 cell at ``bits`` total bits
    (``bits/2`` per dimension), zero-padded decimal."""

    bits: int

    def __post_init__(self):
        if self.bits % 2 or not (2 <= self.bits <= 32):
            raise ValueError("z2 scheme bits must be even, in [2, 32]")
        self.spec = f"z2-{self.bits}bits"
        self.res = self.bits // 2  # bits per dimension
        self.digits = len(str((1 << self.bits) - 1))

    def validate(self, sft) -> None:
        geom = sft.geom_field
        if geom is None or sft.descriptor(geom).type_name != "Point":
            raise ValueError(
                "z2 partition scheme requires a Point geometry field; "
                "use an xz2 scheme for non-point geometries"
            )

    def _cells(self, x, y) -> np.ndarray:
        n = 1 << self.res
        ix = np.clip(((np.asarray(x) + 180.0) / 360.0 * n).astype(np.int64), 0, n - 1)
        iy = np.clip(((np.asarray(y) + 90.0) / 180.0 * n).astype(np.int64), 0, n - 1)
        return zorder.encode_2d_np(ix.astype(np.uint64), iy.astype(np.uint64))

    def leaves(self, batch) -> np.ndarray:
        geom = batch.sft.geom_field
        col = batch.columns[geom]
        if col.dtype == object:
            # a polygon's extent can span many cells, but a feature lives
            # in exactly one leaf -- single-cell pruning would then drop
            # results. Extent-preserving layout is what xz2 is for.
            raise ValueError(
                "z2 partition scheme requires a Point geometry field; "
                "use an xz2 scheme for non-point geometries"
            )
        x, y = col[:, 0], col[:, 1]
        return np.array(
            [f"{int(z):0{self.digits}d}" for z in self._cells(x, y)], dtype=object
        )

    def _cell_env(self, leaf: str) -> Envelope:
        ix, iy = zorder.decode_2d_np(np.array([int(leaf)], dtype=np.uint64))
        n = 1 << self.res
        w, h = 360.0 / n, 180.0 / n
        xmin = -180.0 + float(ix[0]) * w
        ymin = -90.0 + float(iy[0]) * h
        return Envelope(xmin, ymin, xmin + w, ymin + h)

    def matches(self, leaf: str, geom_bounds, time_bounds) -> bool:
        if geom_bounds is None or geom_bounds.unbounded:
            return True
        cell = self._cell_env(leaf)
        return any(env.intersects(cell) for env, _ in geom_bounds.values)


def _geom_envelopes(batch):
    """Per-feature envelope bounds of the default geometry column (point
    fast path; shared by the extent-preserving xz schemes)."""
    geom = batch.sft.geom_field
    col = batch.columns[geom]
    if col.dtype != object:
        return col[:, 0], col[:, 1], col[:, 0], col[:, 1]
    envs = [g.envelope for g in col]
    return (
        np.array([e.xmin for e in envs]),
        np.array([e.ymin for e in envs]),
        np.array([e.xmax for e in envs]),
        np.array([e.ymax for e in envs]),
    )


@dataclass
class XZ2Scheme(PartitionScheme):
    """Non-point extent leaves: the geometry envelope's XZ2 code at
    precision ``bits`` (ref XZ2Scheme; extent-preserving, so a leaf is
    pruned via XZ2 ranges of the query box at the same precision)."""

    bits: int

    def __post_init__(self):
        if not (1 <= self.bits <= 12):
            raise ValueError("xz2 scheme bits must be in [1, 12]")
        self.spec = f"xz2-{self.bits}bits"
        self.sfc = XZ2SFC(self.bits)
        max_code = np.atleast_1d(self.sfc.index(179.0, 89.0, 180.0, 90.0))[0]
        self.digits = len(str(int(max_code)))

    def leaves(self, batch) -> np.ndarray:
        xmin, ymin, xmax, ymax = _geom_envelopes(batch)
        codes = self.sfc.index(xmin, ymin, xmax, ymax)
        return np.array(
            [f"{int(c):0{self.digits}d}" for c in np.atleast_1d(codes)],
            dtype=object,
        )

    def matches(self, leaf: str, geom_bounds, time_bounds) -> bool:
        if geom_bounds is None or geom_bounds.unbounded:
            return True
        code = int(leaf)
        for env, _ in geom_bounds.values:
            for r in self.sfc.ranges(env.xmin, env.ymin, env.xmax, env.ymax):
                if r.lower <= code <= r.upper:
                    return True
        return False


@dataclass
class XZ3Scheme(PartitionScheme):
    """Non-point spatio-temporal leaves: ``W<epoch-bin>/<xz3>`` -- the
    geometry envelope's XZ3 code at precision ``bits`` inside its time
    bin (ref XZ3 storage partitioning; extent-preserving like xz2, with
    the same week-binned time as the Z3 curve)."""

    bits: int
    period: str = "week"
    depth = 2

    def __post_init__(self):
        if not (1 <= self.bits <= 12):
            raise ValueError("xz3 scheme bits must be in [1, 12]")
        from geomesa_tpu.curves import TimePeriod
        from geomesa_tpu.curves.xz3 import XZ3SFC

        self.spec = f"xz3-{self.bits}bits"
        self.sfc = XZ3SFC(TimePeriod.parse(self.period), self.bits)
        # minimal-extent probe at the max corner: a full-extent window
        # stops octree subdivision early and under-reports the code width
        tm = self.sfc.t_max
        probe = np.atleast_1d(
            self.sfc.index(180.0, 90.0, tm, 180.0, 90.0, tm)
        )[0]
        self.digits = len(str(int(probe)))

    def validate(self, sft) -> None:
        if sft.geom_field is None or sft.dtg_field is None:
            raise ValueError(
                "xz3 partition scheme needs a geometry and a Date field"
            )

    def leaves(self, batch) -> np.ndarray:
        from geomesa_tpu.curves.binnedtime import to_binned_time

        xmin, ymin, xmax, ymax = _geom_envelopes(batch)
        ms = np.asarray(batch.column(batch.sft.dtg_field), dtype=np.int64)
        bins, off = to_binned_time(ms, self.period)
        codes = np.atleast_1d(
            self.sfc.index(xmin, ymin, off.astype(np.float64), xmax, ymax,
                           off.astype(np.float64))
        )
        return np.array(
            [
                f"W{int(b)}/{int(c):0{self.digits}d}"
                for b, c in zip(np.atleast_1d(bins), codes)
            ],
            dtype=object,
        )

    def matches(self, leaf: str, geom_bounds, time_bounds) -> bool:
        from geomesa_tpu.curves.binnedtime import max_offset, to_binned_time

        bin_part, code_part = leaf.split("/")
        b = int(bin_part[1:])
        code = int(code_part)
        if time_bounds is not None and not time_bounds.unbounded:
            mx = max_offset(self.period)
            ok_t = False
            windows = []
            for t0, t1 in time_bounds.values:
                b0, o0 = to_binned_time(np.int64(t0), self.period)
                b1, o1 = to_binned_time(np.int64(t1), self.period)
                if not (int(b0) <= b <= int(b1)):
                    continue
                ok_t = True
                lo = float(o0) if b == int(b0) else 0.0
                hi = float(o1) if b == int(b1) else float(mx)
                windows.append((lo, hi))
            if not ok_t:
                return False
        else:
            windows = [(0.0, float(max_offset(self.period)))]
        if geom_bounds is None or geom_bounds.unbounded:
            return True
        for env, _ in geom_bounds.values:
            for lo, hi in windows:
                for r in self._ranges_cached(
                    env.xmin, env.ymin, lo, env.xmax, env.ymax, hi
                ):
                    if r.lower <= code <= r.upper:
                        return True
        return False

    def _ranges_cached(self, xmin, ymin, lo, xmax, ymax, hi):
        """matches() runs once per leaf but the octree decomposition only
        depends on the query window: memoize it per (env, window)."""
        if not hasattr(self, "_range_cache"):
            self._range_cache = {}
        key = (xmin, ymin, lo, xmax, ymax, hi)
        if key not in self._range_cache:
            if len(self._range_cache) > 256:
                self._range_cache.clear()
            self._range_cache[key] = self.sfc.ranges(
                xmin, ymin, lo, xmax, ymax, hi
            )
        return self._range_cache[key]


# -- attribute ---------------------------------------------------------------


def _equality_values(f, attr: str) -> "set | None":
    """Values ``attr`` may take under ``f``; None = unconstrained."""
    if isinstance(f, ast.Compare) and f.attr == attr and f.op == "=":
        return {f.value}
    if isinstance(f, ast.In) and f.attr == attr:
        return set(f.values)
    if isinstance(f, ast.And):
        out = None
        for c in f.children:
            v = _equality_values(c, attr)
            if v is not None:
                out = v if out is None else (out & v)
        return out
    if isinstance(f, ast.Or):
        out: set = set()
        for c in f.children:
            v = _equality_values(c, attr)
            if v is None:
                return None  # one branch unconstrained -> no prune
            out |= v
        return out
    return None


_UNSAFE_LEAF = re.compile(r"[^A-Za-z0-9_.\-]")


def _safe_leaf(v) -> str:
    """Attribute value -> filesystem-safe single path segment (no '/',
    no traversal, never empty)."""
    s = _UNSAFE_LEAF.sub("_", str(v)).lstrip(".")
    return s or "_"


@dataclass
class AttributeScheme(PartitionScheme):
    """One leaf per attribute value (ref AttributeScheme). Pruning uses
    equality / IN constraints extracted from the residual filter. Values
    are sanitized to a single safe path segment."""

    attr: str

    def __post_init__(self):
        self.spec = f"attribute:{self.attr}"

    def validate(self, sft) -> None:
        if self.attr not in sft.attribute_names:
            raise ValueError(
                f"attribute partition scheme: no attribute {self.attr!r}"
            )

    def leaves(self, batch) -> np.ndarray:
        col = batch.column(self.attr)
        return np.array([_safe_leaf(v) for v in col], dtype=object)

    def matches(self, leaf: str, geom_bounds, time_bounds, filter=None) -> bool:
        if filter is None:
            return True
        vals = _equality_values(filter, self.attr)
        return vals is None or leaf in {_safe_leaf(v) for v in vals}


# -- composite ---------------------------------------------------------------


class CompositeScheme(PartitionScheme):
    """Nested leaves, outer scheme first: ``daily,z2-2bit`` gives
    ``2020/01/05/03`` paths."""

    def __init__(self, parts: "list[PartitionScheme]"):
        self.parts = parts
        # ':' join so the spec survives the comma-delimited SFT spec string
        # (scheme_for accepts either separator)
        self.spec = ":".join(p.spec for p in parts)
        self.depth = sum(p.depth for p in parts)

    def validate(self, sft) -> None:
        for p in self.parts:
            p.validate(sft)

    def leaves(self, batch) -> np.ndarray:
        per_part = [p.leaves(batch) for p in self.parts]
        return np.array(
            ["/".join(row) for row in zip(*per_part)], dtype=object
        )

    def matches(self, leaf: str, geom_bounds, time_bounds, filter=None) -> bool:
        segs = leaf.split("/")
        off = 0
        for p in self.parts:
            sub = "/".join(segs[off : off + p.depth])
            off += p.depth
            if isinstance(p, AttributeScheme):
                ok = p.matches(sub, geom_bounds, time_bounds, filter=filter)
            else:
                ok = p.matches(sub, geom_bounds, time_bounds)
            if not ok:
                return False
        return True


# -- parsing -----------------------------------------------------------------

_ZBITS = re.compile(r"^(x?z[23])-(\d+)bits?$")


def scheme_for(spec: str) -> PartitionScheme:
    """Parse a scheme spec string (see module docstring). Composites may
    be ','- or ':'-joined; the ':' form is what persists through the SFT
    spec round-trip."""
    # 'attribute:name' contains ':' legitimately -- protect it, then split
    protected = re.sub(r"\b(attr|attribute):", r"\1=", spec)
    parts = [
        s.strip().replace("=", ":", 1)
        for s in re.split(r"[,:]", protected)
        if s.strip()
    ]
    if not parts:
        raise ValueError("empty partition scheme spec")
    schemes = []
    for part in parts:
        m = _ZBITS.match(part)
        if m:
            kind = m.group(1)
            if kind == "z2":
                schemes.append(Z2Scheme(int(m.group(2))))
            elif kind == "xz2":
                schemes.append(XZ2Scheme(int(m.group(2))))
            elif kind == "xz3":
                schemes.append(XZ3Scheme(int(m.group(2))))
            else:
                raise ValueError(f"unknown partition scheme {part!r}")
        elif part in _STEPS or part == "weekly":
            schemes.append(DateTimeScheme(part))
        elif part.startswith(("attribute:", "attr:")):
            schemes.append(AttributeScheme(part.split(":", 1)[1]))
        elif part == "datetime":
            schemes.append(DateTimeScheme("daily"))
        else:
            raise ValueError(f"unknown partition scheme {part!r}")
    return schemes[0] if len(schemes) == 1 else CompositeScheme(schemes)


def scheme_matches(scheme, leaf, plan) -> bool:
    """Prune test against a QueryPlan's extracted bounds."""
    if isinstance(scheme, (AttributeScheme, CompositeScheme)):
        return scheme.matches(
            leaf, plan.geom_bounds, plan.time_bounds, filter=plan.filter
        )
    return scheme.matches(leaf, plan.geom_bounds, plan.time_bounds)
