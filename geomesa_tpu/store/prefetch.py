"""Pipelined host I/O: bounded read-ahead over partition files.

Ref role: Accumulo tablet servers stream ranges to a scan in PARALLEL
(BatchScanner readahead threads); the rebuild's out-of-core scan, FS
staging and bulk ingest paths were reading, decoding and staging
partitions SERIALLY on the consumer thread, so the device slab pump (and
the disk) sat idle behind host decode — BENCH_r05 measured the streamed
scan at 12 MB/s sustained with the device side double-buffered.

This module is the shared host-side half of that overlap: an ordered,
bounded, threaded map. ``prefetch_map(fn, items)`` runs ``fn`` on worker
threads with a bounded number of items in flight and yields the results
IN INPUT ORDER, so host work on item i+k (file read, Arrow decode,
``stage_columns_host``) overlaps both the disk and whatever the consumer
does with item i (typically a device kernel). The heavy per-item work —
pyarrow reads/decompression, numpy copies/astype — releases the GIL, so
worker threads scale on multi-core hosts; on a single core the pipeline
still overlaps the consumer's device dispatches with the next read.

Memory bound: at most ``depth`` results exist at once (completed results
waiting in the queue additionally respect ``byte_budget`` — topping up
stops while completed-but-unconsumed results exceed it, so peak host
memory is roughly ``byte_budget`` + ``workers`` x one item). Ordered
delivery means a slow head item back-pressures the whole pipeline rather
than reordering results — deterministic output is the contract every
caller (scan parity, ingest replay) relies on.

Failure discipline: an ``fn`` exception surfaces to the consumer at that
item's position in the stream; the executor is then drained and shut
down (queued items cancelled, running ones finish and are discarded), so
a decode error mid-stream can neither deadlock the queue nor leak
threads. Closing the generator early (consumer abandons the scan — e.g.
a query deadline expired) runs the same cleanup. TRANSIENT read errors
(OSError — flaky NFS, the ``fail.read.io`` failpoint) are retried on the
worker with bounded exponential backoff BEFORE surfacing (``io.retries``
x ``io.backoff.ms``, doubling; ``geomesa_store_read_retries_total``
counts them); FileNotFoundError and domain failures (e.g. a checksum
quarantine) stay immediate and loud.

Knobs resolve from the ``io.*`` system properties (``io.workers``,
``io.readahead``, ``io.queue.bytes`` — see :mod:`geomesa_tpu.conf`) when
no explicit :class:`PrefetchConfig` is given; ``workers=0`` disables the
threads entirely (the serial baseline, and the right setting for
spinning disks or tiny partitions where thread handoff costs more than
the overlap wins). Observability: ``geomesa_io_*`` metrics (read/decode/
stage seconds observed by the callers, prefetch depth, queue bytes,
chunk counter) ride :mod:`geomesa_tpu.metrics`.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from geomesa_tpu.locking import checked_lock

__all__ = ["PrefetchConfig", "prefetch_map", "batch_nbytes"]

#: thread-name prefix for every prefetch worker (tests assert cleanup)
WORKER_PREFIX = "geomesa-io"


@dataclass(frozen=True)
class PrefetchConfig:
    """Host-I/O pipeline knobs.

    ``workers`` is the decode thread count (0 = serial, no threads);
    ``depth`` bounds items in flight (submitted but not yet consumed;
    0 = auto, ``2 * workers``); ``byte_budget`` bounds the bytes of
    COMPLETED results waiting for the consumer (0 = unbounded) — the
    queue-occupancy half of the memory bound documented above."""

    workers: int = 4
    depth: int = 0
    byte_budget: int = 256 << 20

    @property
    def effective_depth(self) -> int:
        return self.depth if self.depth > 0 else max(2 * self.workers, 2)

    @staticmethod
    def from_props() -> "PrefetchConfig":
        from geomesa_tpu.conf import sys_prop

        return PrefetchConfig(
            workers=int(sys_prop("io.workers")),
            depth=int(sys_prop("io.readahead")),
            byte_budget=int(sys_prop("io.queue.bytes")),
        )

    @staticmethod
    def coerce(io) -> "PrefetchConfig":
        """None -> the ``io.*`` system properties (resolved NOW, so a
        test's ``prop_override`` takes effect per call); an int -> that
        worker count with defaults; a config passes through."""
        if io is None:
            return PrefetchConfig.from_props()
        if isinstance(io, PrefetchConfig):
            return io
        if isinstance(io, int):
            return PrefetchConfig(workers=io)
        raise TypeError(
            f"io must be a PrefetchConfig, int worker count or None, "
            f"not {type(io).__name__}"
        )


def batch_nbytes(batch) -> int:
    """Rough host bytes of a FeatureBatch (numpy columns only; object
    columns count pointer width — good enough for a queue budget)."""
    try:
        return int(
            sum(int(v.nbytes) for v in batch.columns.values())
            + int(batch.fids.nbytes)
        )
    except Exception:  # lint: disable=GT011(queue-budget sizing heuristic: an unsizable batch counts as 0 and the budget stays conservative elsewhere)
        return 0


def _with_retries(fn):
    """Transient-read resilience for the pipeline workers: retry ``fn``
    on OSError with bounded, JITTERED exponential backoff —
    ``io.retries`` extra attempts, ``io.backoff.ms`` base doubling per
    attempt scaled 0.5-1.5x (a fleet of workers hitting the same
    flapping disk de-correlates), the CUMULATIVE sleep capped by
    ``io.backoff.cap.ms`` so a flapping disk can never stall a worker
    for unbounded wall-clock (once the budget is spent the next error
    surfaces immediately). Reads are idempotent, so re-running the
    whole work item is safe. NOT retried: FileNotFoundError (a real
    state — e.g. another writer GC'd the generation mid-scan, which a
    refresh must resolve, not a sleep) and non-OSError domain failures
    (checksum quarantines stay loud)."""
    from geomesa_tpu.conf import sys_prop

    retries = int(sys_prop("io.retries"))
    if retries <= 0:
        return fn

    def call(item):
        import time as _time

        from geomesa_tpu import metrics
        from geomesa_tpu.resilience import backoff_sleeps

        # per-item budget, resolved per call so prop_override applies
        sleeps = backoff_sleeps(
            retries,
            float(sys_prop("io.backoff.ms")),
            float(sys_prop("io.backoff.cap.ms")),
        )
        while True:
            try:
                return fn(item)
            except FileNotFoundError:
                raise
            except OSError:
                delay = next(sleeps, None)
                if delay is None:
                    raise  # retries/budget exhausted: surface the error
                metrics.store_read_retries.inc()
                _time.sleep(delay)

    return call


def prefetch_map(fn, items, config=None, size_of=None):
    """Ordered pipelined map: ``fn(item)`` runs on worker threads with
    bounded read-ahead; results yield in input order (see the module
    docstring for the memory bound and failure discipline). Transient
    OSErrors from ``fn`` are retried per the ``io.retries`` /
    ``io.backoff.ms`` properties (see :func:`_with_retries`).

    ``items`` is only ever advanced on the consumer thread, so plain
    generators are fine as input. ``size_of(result)`` opts results into
    the byte budget. With ``workers <= 0`` this is exactly
    ``map(fn, items)`` — no threads, the serial baseline (retries still
    apply)."""
    cfg = PrefetchConfig.coerce(config)
    fn = _with_retries(fn)
    if cfg.workers <= 0:
        for item in items:
            yield fn(item)
        return
    yield from _prefetch_threads(fn, items, cfg, size_of)


def _prefetch_threads(fn, items, cfg: PrefetchConfig, size_of):
    from geomesa_tpu import metrics
    from geomesa_tpu.spawn import ContextPool

    it = iter(items)
    depth = cfg.effective_depth
    budget = cfg.byte_budget
    lock = checked_lock("prefetch.queued")
    queued = {"bytes": 0}  # completed-but-unconsumed result bytes

    def run(item):
        # request context (trace spans, cost collector, degradation,
        # compile scope) crosses the pool via the blessed ContextPool:
        # contextvars are per-thread, so without the submit-time
        # capture/attach the workers' read/decode/stage spans would
        # silently vanish from the request's trace and bytes read on a
        # worker would charge nobody (tracing.py module docstring)
        out = fn(item)
        b = 0
        if size_of is not None and budget:
            try:
                b = int(size_of(out))
            except Exception:  # lint: disable=GT011(queue-budget sizing heuristic: an unsizable item is uncounted, the pipeline result is untouched)
                b = 0
            with lock:
                queued["bytes"] += b
            if b:
                metrics.io_queue_bytes.inc(b)
        return out, b

    pending: deque = deque()
    ex = ContextPool(cfg.workers, thread_name_prefix=WORKER_PREFIX)
    # gauges are updated by DELTA (inc/dec), never set: several
    # pipelines commonly run at once (concurrent queries on a threaded
    # server) and each must contribute only its own share
    try:
        exhausted = False
        while True:
            while not exhausted and len(pending) < depth:
                if budget and pending and queued["bytes"] >= budget:
                    # queue over budget: stop topping up, but always keep
                    # >= 1 item in flight so the pipeline cannot stall
                    break
                try:
                    item = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(ex.submit(run, item))
                metrics.io_prefetch_depth.inc()
            if not pending:
                break
            # resolve BEFORE popping: if fn raised, the future stays in
            # `pending` so the finally's gauge retraction still counts it
            out, b = pending[0].result()
            pending.popleft()
            metrics.io_prefetch_depth.dec()
            if b:
                with lock:
                    queued["bytes"] -= b
                metrics.io_queue_bytes.dec(b)
            metrics.io_chunks.inc()
            yield out
    finally:
        # error or early close: cancel what never started, let running
        # items finish (fn may hold external resources mid-call), and
        # join the workers — nothing leaks past this frame
        for f in pending:
            f.cancel()
        ex.shutdown(wait=True, cancel_futures=True)
        # after the join, retract this pipeline's leftover contribution
        # (unconsumed completed items and their accounted bytes)
        metrics.io_prefetch_depth.dec(len(pending))
        metrics.io_queue_bytes.dec(queued["bytes"])
        queued["bytes"] = 0
