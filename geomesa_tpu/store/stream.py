"""Streaming live layer: WAL-backed incremental ingest over the FS store.

The geomesa-kafka live-layer tier rebuilt on LSM discipline (ref:
KafkaDataStore's hot in-memory tier in front of the indexed store
[UNVERIFIED - empty reference mount]; PAPER.md L7): the batch write
path pays a full restage before a row is queryable (flush 33s + stage
24s for 4M rows vs 3.5s ingest, BENCH_r04), so streaming writes go to

1. a checksummed, fsync-policied **write-ahead log**
   (:mod:`geomesa_tpu.store.wal`) — the ack point: a returned seq has
   hit the ``store.fsync`` durability bar and survives SIGKILL;
2. a bounded in-memory generation of **Z-sorted memtable runs** that
   serves immediately — :meth:`StreamingStore.query`/``count`` (and
   process density/stats, which route through ``query``) merge memtable
   hits with the resident/on-disk results under the existing planner;
3. background **generational compaction**: a daemon merges the sealed
   runs into the store's crash-consistent partition files
   (write-new-then-publish, PR 3) with the WAL watermark persisted
   ATOMICALLY in the manifest, then truncates the consumed segments.
   Compaction yields to serving load (the brownout/queue-pressure
   signal) but never past the read-amplification bound: at most
   ``wal.max.generations`` live runs before appends backpressure
   429-style instead of growing unboundedly.

Crash recovery replays the WAL at open — torn tails truncated at the
last valid checksum, already-compacted records skipped via the
manifest's ``wal_watermark`` — so a SIGKILL anywhere in
append/rotate/compact/publish loses zero acked rows and invents zero
phantom rows (the chaos kill matrix in tests/test_crash_consistency.py
proves it at every ``fail.wal.*``/``fail.compact.*`` instant).

Consistency of the merge: queries snapshot the memtable and read the
store under ONE shared store lock section, while the compactor removes
compacted runs inside the SAME exclusive section that published them —
a query can never see a row in both (double count) or neither (loss)
mid-compaction.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.filter import ast
from geomesa_tpu.index.build import build_index
from geomesa_tpu.index.keyspaces import keyspace_for
from geomesa_tpu.spawn import spawn_thread
from geomesa_tpu.sched.scheduler import RejectedError
from geomesa_tpu.store.wal import WriteAheadLog

__all__ = [
    "IngestBackpressureError",
    "ReplicationGapError",
    "WalUnavailableError",
    "StreamingStore",
    "streaming_enabled",
]

_retry_rng = random.Random()


def streaming_enabled() -> bool:
    from geomesa_tpu.conf import sys_prop

    return bool(sys_prop("stream.enabled"))


class IngestBackpressureError(RejectedError):
    """The live layer is at its ``wal.max.generations`` read-
    amplification bound: the caller should back off and retry
    (HTTP 429 + Retry-After — a RejectedError so the serving stack's
    flow-control handling applies unchanged, and resilience classifies
    it FATAL: backpressure is the client contract, never retried or
    degraded away server-side)."""

    def __init__(self, retry_after_s: float):
        RuntimeError.__init__(
            self,
            "streaming ingest backpressured: memtable at the "
            f"wal.max.generations bound; retry after {retry_after_s:g}s",
        )
        self.retry_after_s = retry_after_s


class WalUnavailableError(RuntimeError):
    """The ``wal`` failure-domain breaker is open: appends fail fast
    instead of queueing against a log that cannot take them (an ack
    must never be promised by a dead WAL)."""


class ReplicationGapError(RuntimeError):
    """A shipped record would leave a seq hole in this replica's WAL:
    applying it would silently skip acked rows (the follower would
    report lag 0 while missing data forever). The apply path refuses;
    the replicator marks the type ``needs_reprovision`` instead of
    diverging."""


@dataclass
class _MemRun:
    """One Z-sorted in-memory run: an immutable BuiltIndex snapshot
    plus the highest WAL seq it contains. ``sealed`` runs are owned by
    an in-flight compaction — appends stop coalescing into them."""

    built: object  # BuiltIndex
    max_seq: int
    primary: str
    sealed: bool = False

    @property
    def rows(self) -> int:
        return len(self.built.batch)


@dataclass
class _TypeStream:
    wal: WriteAheadLog
    #: serializes append (WAL write + memtable insert must commit in
    #: seq order — a compaction watermark over out-of-order runs would
    #: skip un-compacted records at replay) and the runs-list snapshot.
    #: blocking_ok: the WAL write happens under it BY DESIGN (ordering
    #: blocking appends is the lock's purpose, audit-writer style)
    lock: object = None
    runs: "list[_MemRun]" = field(default_factory=list)
    appended_rows: int = 0
    compactions: int = 0
    last_publish: float = field(default_factory=time.monotonic)
    last_compact_s: float = 0.0
    kicked: bool = False  # explicit compaction request (close/CLI)


class StreamingStore:
    """Streaming facade over a :class:`FileSystemDataStore`: everything
    not overridden delegates to the wrapped store, so the HTTP server,
    resident DeviceIndex staging and the process/* operators treat it
    as a drop-in store whose query surface includes the live layer.

    >>> layer = StreamingStore(store)
    >>> layer.append("t", {...}, fids=[...])   # acked + queryable NOW
    >>> layer.query("t", "BBOX(geom, ...)")    # memtable ∪ store
    """

    def __init__(self, store, scheduler=None):
        self.store = store
        self.scheduler = scheduler
        self._streams: "dict[str, _TypeStream]" = {}
        #: delta listeners: cb(type_name, batch) after each acked
        #: append — the resident-index incremental refresh hook
        from geomesa_tpu.locking import checked_lock

        self._listeners: list = []
        #: seq listeners: cb(type_name, batch, seq) after each durably
        #: landed record — leader appends AND follower applies — the
        #: continuous-query matcher's cursor-exact live feed
        self._seq_listeners: list = []
        #: replication retention hook: ``callable(type_name) -> int |
        #: None`` giving the lowest WAL seq a follower still needs
        #: (Replicator.attach installs it); the compactor never
        #: truncates segments past it, so a lagging-but-live follower
        #: keeps tailing instead of hitting the 410 re-provision cliff
        self.retention_floor = None
        #: additional retention floors (``add_retention_floor``): the
        #: push tier pins segments live subscriber cursors still need
        #: to replay — the effective truncation bound is the min over
        #: every installed floor
        self._retention_floors: list = []
        # blocking_ok: first-touch _TypeStream construction opens the
        # WAL (segment scan + torn-tail truncation) under it BY DESIGN
        # — two appenders racing the open would double-append one
        # segment through two fds (the server.resident discipline)
        self._streams_lock = checked_lock(
            "store.stream.types", blocking_ok=True
        )
        self._cv = threading.Condition()
        self._stop = False
        self._recover_all()
        self._compactor = spawn_thread(
            self._compact_loop, name="stream-compactor", context=False
        )
        self._compactor.start()

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.store, name)

    # -- per-type state ----------------------------------------------------

    def _wal_dir(self, type_name: str) -> str:
        return os.path.join(self.store.root, type_name, "_wal")

    def _ts(self, type_name: str) -> _TypeStream:
        ts = self._streams.get(type_name)
        if ts is not None:
            return ts
        if type_name not in self.store._types:
            raise KeyError(type_name)
        with self._streams_lock:
            ts = self._streams.get(type_name)
            if ts is None:
                from geomesa_tpu.locking import checked_lock

                ts = _TypeStream(
                    wal=WriteAheadLog(self._wal_dir(type_name)),
                    lock=checked_lock(
                        "store.stream.mem", blocking_ok=True
                    ),
                )
                self._streams[type_name] = ts
        return ts

    # -- ingest ------------------------------------------------------------

    def append(self, type_name: str, columns_or_batch, fids=None) -> dict:
        """Durable streaming append: WAL (ack point) then the live
        memtable; returns ``{"seq", "rows"}``. The rows are queryable
        through this layer — and any attached resident index — before
        this method returns; no flush or restage happens on this path.
        Raises :class:`IngestBackpressureError` at the
        ``wal.max.generations`` read-amplification bound."""
        from geomesa_tpu import ledger, metrics, resilience
        from geomesa_tpu.conf import sys_prop
        from geomesa_tpu.tracing import span

        st = self.store._types[type_name]
        if isinstance(columns_or_batch, FeatureBatch):
            batch = columns_or_batch
        else:
            batch = FeatureBatch.from_columns(
                st.sft, columns_or_batch, fids
            )
        if len(batch) == 0:
            return {"seq": -1, "rows": 0}
        ts = self._ts(type_name)
        max_gens = max(int(sys_prop("wal.max.generations")), 1)
        br = resilience.wal_breaker()
        with span("stream.append", type=type_name, rows=len(batch)):
            shed_detail = None
            with ts.lock:
                if len(ts.runs) >= max_gens and not self._can_coalesce(
                    type_name, ts, batch
                ):
                    # at the bound AND a new run would be needed:
                    # 429-style shed — the WAL write is refused BEFORE
                    # any byte lands, so nothing is acked. Detail is
                    # gathered HERE; the flight trigger fires after
                    # the lock releases (its providers re-take it)
                    metrics.stream_backpressure.inc()
                    shed_detail = {
                        "type": type_name,
                        "runs": len(ts.runs),
                        "memtable_rows": sum(r.rows for r in ts.runs),
                    }
                if shed_detail is None:
                    if not br.allow():
                        raise WalUnavailableError(
                            "streaming ingest unavailable: the wal "
                            "failure-domain breaker is open"
                        )
                    # the FALLIBLE work (sort + encode) happens before
                    # the WAL write: after the record is durable, only
                    # infallible list commits remain — an error after
                    # the ack point would leave a record that replays
                    # rows the client was told failed (phantoms)
                    coalesce, built, primary = self._prepare_run_locked(
                        type_name, ts, batch
                    )
                    payload = self._encode(batch)
                    try:
                        seq = ts.wal.append(payload)
                    except Exception:
                        br.record_failure()
                        raise
                    br.record_success()
                    self._commit_run_locked(
                        ts, built, coalesce, primary, seq
                    )
                    ts.appended_rows += len(batch)
                    mem_rows = sum(r.rows for r in ts.runs)
                    nruns = len(ts.runs)
            if shed_detail is not None:
                stalled = self._note_stall(type_name, ts, shed_detail)
                self._kick()
                raise IngestBackpressureError(
                    self._retry_after(ts, stalled)
                )
            metrics.stream_appends.inc()
            metrics.stream_rows.inc(len(batch))
            metrics.stream_memtable_rows.set(mem_rows, type=type_name)
            metrics.stream_memtable_runs.set(nruns, type=type_name)
            ledger.charge("memtable_rows", len(batch))
            # incremental resident refresh OUTSIDE the memtable lock
            # (device staging must not serialize WAL appends)
            self._notify_delta(type_name, batch)
            self._notify_seq(type_name, batch, seq)
        if mem_rows >= int(sys_prop("stream.memtable.rows")):
            self._kick()
        return {"seq": int(seq), "rows": len(batch)}

    def _can_coalesce(self, type_name, ts, batch) -> bool:
        """Would this append fold into the tail run instead of opening
        a new one? (Caller holds ``ts.lock``.)"""
        from geomesa_tpu.conf import sys_prop

        st = self.store._types[type_name]
        target = max(int(sys_prop("stream.run.rows")), 1)
        tail = ts.runs[-1] if ts.runs else None
        return (
            tail is not None
            and not tail.sealed
            and tail.primary == st.primary
            and tail.rows + len(batch) <= target
        )

    def _prepare_run_locked(self, type_name, ts, batch):
        """The FALLIBLE half of a memtable insert, run BEFORE the WAL
        write (caller holds ``ts.lock``): Z-sort the new (or coalesced
        tail) run. Coalescing into the unsealed tail up to
        ``stream.run.rows`` bounds BOTH the per-append re-sort and the
        run count. Returns ``(coalesce, BuiltIndex)``."""
        st = self.store._types[type_name]
        ks = keyspace_for(st.sft, st.primary)
        if self._can_coalesce(type_name, ts, batch):
            merged = FeatureBatch.concat([ts.runs[-1].built.batch, batch])
            return True, build_index(
                ks, merged, self.store.partition_size
            ), st.primary
        return (
            False,
            build_index(ks, batch, self.store.partition_size),
            st.primary,
        )

    @staticmethod
    def _commit_run_locked(ts, built, coalesce, primary, seq) -> None:
        """The INFALLIBLE half, run after the WAL ack point: plain
        list/assignment commits only — nothing here may raise, or a
        durable record would replay rows its client saw fail."""
        run = _MemRun(built, max_seq=seq, primary=primary)
        if coalesce:
            ts.runs[-1] = run
        else:
            ts.runs.append(run)

    def _insert_locked(self, type_name, ts, batch, seq) -> None:
        """Prepare + commit in one step (recovery replay — no WAL
        write races the insert there)."""
        coalesce, built, primary = self._prepare_run_locked(
            type_name, ts, batch
        )
        self._commit_run_locked(ts, built, coalesce, primary, seq)

    def apply_replicated(self, type_name: str, seq: int, payload: bytes) -> int:
        """Follower apply path: land ONE leader-shipped WAL record —
        the record keeps the LEADER's seq (``append_at``) so the
        manifest watermark and replay idempotence hold bit-identically
        across the replica group, and promotion needs no renumbering.
        A seq this replica already holds durably (its WAL or at/below
        its manifest watermark) is skipped — the ≤-watermark idempotent
        replay contract, which is what makes re-shipping after a torn
        tail, a follower crash, or an overlapping tail harmless.
        Returns rows applied (0 = idempotent skip). Never sheds: the
        leader already acked these rows, so backpressure here would be
        data loss — the follower's own compactor bounds the memtable
        exactly like the leader's does."""
        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.failpoints import fail_point

        ts = self._ts(type_name)
        st = self.store._types[type_name]
        fail_point("fail.replica.apply")
        with ts.lock:
            nxt = int(ts.wal.next_seq)
            wm = int(st.wal_watermark)
            if seq < nxt or seq <= wm:
                metrics.replica_apply_skipped.inc()
                return 0
            if seq > max(nxt, wm + 1):
                # a hole: the records in [next_seq, seq) were never
                # applied here and are not covered by the manifest
                # watermark — applying past them would lose acked rows
                # while reporting lag 0 (the 410/truncation race a
                # gapped ship stream surfaces as)
                raise ReplicationGapError(
                    f"shipped seq {seq} for {type_name!r} would gap "
                    f"this replica (next_seq={nxt}, watermark={wm})"
                )
            # decode (fallible) BEFORE the local durability point: an
            # undecodable record must fail the apply cleanly, not leave
            # a durable WAL entry that replays nothing
            batch = self._decode(type_name, payload)
            ts.wal.append_at(seq, payload)
            if len(batch):
                self._insert_locked(type_name, ts, batch, seq)
            ts.appended_rows += len(batch)
            mem_rows = sum(r.rows for r in ts.runs)
            nruns = len(ts.runs)
        metrics.replica_apply_records.inc()
        metrics.stream_memtable_rows.set(mem_rows, type=type_name)
        metrics.stream_memtable_runs.set(nruns, type=type_name)
        ledger.charge("replica_apply_rows", len(batch))
        if len(batch):
            # resident-index delta outside the memtable lock, exactly
            # like the leader's append path
            self._notify_delta(type_name, batch)
            self._notify_seq(type_name, batch, seq)
        from geomesa_tpu.conf import sys_prop

        if mem_rows >= int(sys_prop("stream.memtable.rows")):
            self._kick()
        return len(batch)

    def replica_positions(self) -> dict:
        """Per-type WAL position + manifest watermark: the follower's
        lag accounting, the election's most-caught-up comparison and
        the ship endpoint's 410 detection all read from here."""
        out = {}
        for t in self.store.type_names:
            ts = self._ts(t)
            st = self.store._types[t]
            out[t] = {
                "next_seq": int(ts.wal.next_seq),
                "watermark": int(st.wal_watermark),
            }
        return out

    def install_snapshot(self, type_name: str, doc: dict, src_dir: str) -> dict:
        """Swap a fully-downloaded, checksum-verified snapshot into the
        live tree (the reprovision/bootstrap install): data files land
        next to the current generation, the snapshot manifest publishes
        over it atomically, and the live layer resets to the snapshot's
        history — memtable dropped, local WAL wiped — so tailing
        resumes from ``doc["wal_watermark"] + 1`` (``apply_replicated``
        explicitly legalizes that jump). Everything happens under the
        store's exclusive lock: a compactor racing the install blocks,
        then re-reads the installed manifest and finds no runs to
        merge. A crash or ``fail.snapshot.install`` before the manifest
        publish leaves the previous generation intact (the staged files
        are unpinned orphans the sweep reclaims)."""
        import shutil

        from geomesa_tpu import metrics
        from geomesa_tpu.failpoints import fail_point
        from geomesa_tpu.store import snapshot as snapshot_mod
        from geomesa_tpu.store.wal import WriteAheadLog

        store = self.store
        with store._exclusive():
            fail_point("fail.snapshot.install")
            d = store._dir(type_name)
            os.makedirs(d, exist_ok=True)
            moved = snapshot_mod.install_files(d, doc, src_dir)
            # adopt the installed manifest in-memory (a brand-new type
            # loads from scratch — the add-node bootstrap path); the
            # refresh's own recovery sweep reclaims the superseded
            # generation, minus anything snapshot-pinned
            store._refresh_from_disk(type_name)
            wal_dir = self._wal_dir(type_name)
            with self._streams_lock:
                ts = self._streams.get(type_name)
            if ts is not None:
                with ts.lock:
                    # the memtable and local WAL describe a history
                    # this replica just abandoned (diverged tail,
                    # compacted-past gap): the snapshot's rows are all
                    # in partition files at or below its watermark
                    ts.runs.clear()
                    ts.wal.close()
                    self._wipe_wal_dir(wal_dir)
                    ts.wal = WriteAheadLog(wal_dir)
            else:
                self._wipe_wal_dir(wal_dir)
        shutil.rmtree(src_dir, ignore_errors=True)
        metrics.snapshot_installs.inc()
        metrics.snapshot_install_bytes.inc(moved)
        metrics.stream_memtable_rows.set(0, type=type_name)
        metrics.stream_memtable_runs.set(0, type=type_name)
        return {
            "type": type_name,
            "generation": doc.get("generation"),
            "watermark": int(doc.get("wal_watermark", -1)),
            "bytes": int(moved),
        }

    @staticmethod
    def _wipe_wal_dir(wal_dir: str) -> None:
        """Remove every WAL segment (snapshot install: the local log's
        history is abandoned wholesale; an empty log accepts the
        leader's next seq via ``append_at``)."""
        if not os.path.isdir(wal_dir):
            return
        for f in os.listdir(wal_dir):
            if f.startswith("wal-"):
                try:
                    os.unlink(os.path.join(wal_dir, f))
                except OSError:
                    pass

    @staticmethod
    def _encode(batch: FeatureBatch) -> bytes:
        import pyarrow as pa

        from geomesa_tpu.pyarrow_compat import preload_pyarrow

        preload_pyarrow()
        t = batch.to_arrow()
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        return sink.getvalue().to_pybytes()

    def _decode(self, type_name: str, payload: bytes) -> FeatureBatch:
        import pyarrow as pa

        t = pa.ipc.open_stream(pa.BufferReader(payload)).read_all()
        return FeatureBatch.from_arrow(
            t, self.store._types[type_name].sft
        )

    def _retry_after(self, ts: _TypeStream, stalled: bool) -> float:
        """Backpressure Retry-After from the measured compaction rate:
        roughly one compaction's duration (jittered so a shed fleet
        de-correlates), clamped [0.1s, 30s]; a stalled compactor
        advertises the cap."""
        if stalled:
            return 30.0
        est = ts.last_compact_s or 1.0
        est *= 0.75 + 0.5 * _retry_rng.random()
        return min(max(est, 0.1), 30.0)

    def _note_stall(self, type_name: str, ts: _TypeStream,
                    detail: dict) -> bool:
        """Backpressured appends with a compactor that has not
        published for ``stream.stall.s``: snapshot an ``ingest-stall``
        flight-recorder bundle (rate-limited per reason by the
        recorder) so the stall is inspectable postmortem. MUST be
        called with ``ts.lock`` RELEASED: the recorder's bundle
        providers include this layer's own ``stream_stats`` (and the
        store snapshot), which re-take the locks — firing under them
        would self-deadlock the appender and wedge the whole type."""
        from geomesa_tpu.conf import sys_prop

        stall_s = float(sys_prop("stream.stall.s"))
        if stall_s <= 0:
            return False
        age = time.monotonic() - ts.last_publish
        if age < stall_s:
            return False
        try:
            from geomesa_tpu import slo

            detail = dict(detail)
            detail["seconds_since_publish"] = round(age, 3)
            detail["wal"] = ts.wal.stats()
            slo.FLIGHTREC.trigger("ingest-stall", detail=detail)
        except Exception:  # pragma: no cover - observability must not break  # lint: disable=GT011(flight-recorder trigger is best-effort observability; the stall verdict already returned)
            pass
        return True

    # -- resident-index deltas ---------------------------------------------

    def add_delta_listener(self, cb) -> None:
        """``cb(type_name, batch)`` after every acked append — the
        resident DeviceIndex incremental-refresh hook. Listener faults
        degrade (stamped ``ingest-degraded``): the rows are acked and
        queryable via the store path regardless."""
        self._listeners.append(cb)

    def remove_delta_listener(self, cb) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)

    def add_seq_listener(self, cb) -> None:
        """``cb(type_name, batch, seq)`` after every durably landed WAL
        record — acked leader appends and follower ``apply_replicated``
        both fire it, so a listener sees the identical seq-stamped
        record stream on every replica. The continuous-query matcher
        rides this: the seq is the delivery cursor. Listener faults
        degrade like delta-listener faults — the rows are already
        durable and queryable regardless."""
        self._seq_listeners.append(cb)

    def remove_seq_listener(self, cb) -> None:
        if cb in self._seq_listeners:
            self._seq_listeners.remove(cb)

    def _notify_seq(self, type_name: str, batch, seq: int) -> None:
        from geomesa_tpu import resilience

        for cb in list(self._seq_listeners):
            try:
                cb(type_name, batch, int(seq))
            except Exception as e:
                import logging

                resilience.note_degraded("ingest-degraded")
                logging.getLogger(__name__).warning(
                    "dataset %r: seq listener failed at seq %d (%s) -- "
                    "subscribers recover via cursor replay",
                    type_name, seq, e,
                )

    def add_retention_floor(self, fn) -> None:
        """Install an additional WAL retention floor (``fn(type_name)
        -> int | None``). Composes with ``retention_floor`` — the
        compactor truncates up to the min over all installed floors."""
        self._retention_floors.append(fn)

    def remove_retention_floor(self, fn) -> None:
        if fn in self._retention_floors:
            self._retention_floors.remove(fn)

    def _notify_delta(self, type_name: str, batch) -> None:
        from geomesa_tpu import resilience

        for cb in list(self._listeners):
            try:
                cb(type_name, batch)
            except Exception as e:
                import logging

                resilience.note_degraded("ingest-degraded")
                logging.getLogger(__name__).warning(
                    "dataset %r: resident delta refresh failed (%s) -- "
                    "rows serve from the store path until restage",
                    type_name, e,
                )

    # -- merged serving ----------------------------------------------------

    def _runs_snapshot(self, type_name: str) -> "list[_MemRun]":
        ts = self._streams.get(type_name)
        if ts is None:
            return []
        with ts.lock:
            return list(ts.runs)

    def _run_index(self, run: _MemRun, type_name: str):
        """The run's BuiltIndex, rebuilt only if the primary changed
        under it (reindex mid-stream) so plan ranges stay comparable."""
        st = self.store._types[type_name]
        if run.primary == st.primary:
            return run.built
        ks = keyspace_for(st.sft, st.primary)
        return build_index(
            ks, run.built.batch, self.store.partition_size
        )

    def _mem_chunks(self, type_name: str, runs, plan) -> "list":
        """Per-run filtered batches (visibility/projection applied, no
        global sort/cap — exactly the fs per-partition discipline)."""
        import dataclasses

        from geomesa_tpu.query.plan import Query
        from geomesa_tpu.query.runner import _post_process, run_query

        inner = dataclasses.replace(
            plan,
            query=Query(filter=plan.filter, hints={"internal_scan": True}),
        )
        outer = dataclasses.replace(
            plan,
            query=dataclasses.replace(
                plan.query, sort_by=None, max_features=None
            ),
        )
        out = []
        for run in runs:
            sub = run_query(self._run_index(run, type_name), inner)
            if len(sub.batch):
                pp = _post_process(sub.batch, outer)
                if len(pp):
                    out.append(pp)
        return out

    def query(self, type_name: str, query=ast.Include):
        """Merged scan: memtable runs ∪ resident/on-disk partitions,
        one plan. The memtable snapshot and the store read happen under
        one shared store-lock section (see module docstring), so a
        mid-compaction query sees every row exactly once."""
        import dataclasses

        from geomesa_tpu.query.plan import Query, as_query
        from geomesa_tpu.query.runner import (
            QueryResult,
            _post_process,
        )
        from geomesa_tpu.tracing import span

        import time as _time

        q = as_query(query)
        t0 = _time.perf_counter()
        with span("stream.query", type=type_name) as sp:
            # flush OUTSIDE the shared section (exclusive-lock upgrade
            # under a held shared flock would deadlock); pending is
            # normally empty here — streaming writes go to the WAL
            self.store.flush(type_name)
            with self.store._shared():
                runs = self._runs_snapshot(type_name)
                if not runs:
                    return self.store._query_locked(type_name, q, t0)
                # global sort/cap have cross-source semantics: strip
                # them from the store pass, apply once after the merge
                base_q = dataclasses.replace(
                    q, sort_by=None, max_features=None
                )
                base = self.store._query_locked(type_name, base_q, t0)
            plan = base.plan
            chunks = self._mem_chunks(type_name, runs, plan)
            mem_rows = sum(r.rows for r in runs)
            sp.set(runs=len(runs), mem_rows=mem_rows)
            merged = base.batch
            if chunks:
                merged = FeatureBatch.concat([base.batch] + chunks) \
                    if len(base.batch) else (
                        chunks[0] if len(chunks) == 1
                        else FeatureBatch.concat(chunks)
                    )
            if q.sort_by or q.max_features is not None:
                final_q = Query(
                    filter=ast.Include,
                    sort_by=q.sort_by,
                    sort_desc=q.sort_desc,
                    max_features=q.max_features,
                    hints={"internal_scan": True},
                )
                merged = _post_process(
                    merged, dataclasses.replace(plan, query=final_q)
                )
            return QueryResult(
                merged,
                plan,
                base.scanned + mem_rows,
                base.total + mem_rows,
            )

    def count(self, type_name: str, query=ast.Include) -> int:
        """Merged count: the store side keeps its chunk-pushdown fast
        path; memtable hits add on top from the same plan."""
        from geomesa_tpu.query.plan import as_query

        q = as_query(query)
        if q.max_features is not None or q.sort_by:
            return len(self.query(type_name, q))
        self.store.flush(type_name)  # see query(): outside the lock
        with self.store._shared():
            runs = self._runs_snapshot(type_name)
            # nested store.count under the held shared lock is safe:
            # its flush pre-check sees the empty pending (mixing legacy
            # store.write() with streaming on one type is unsupported)
            if not runs:
                return self.store.count(type_name, q)
            self.store._refresh_from_disk(type_name)
            plan = self.store._plan_locked(type_name, q)
            base = self.store.count(type_name, q)
        return base + sum(
            len(c) for c in self._mem_chunks(type_name, runs, plan)
        )

    def density_pushdown(self, type_name, query, envelope, width, height):
        """Chunk pre-aggregates cannot see the memtable: with live runs
        present the pushdown declines (None) and the caller row-scans
        through :meth:`query`, which merges."""
        if self._runs_snapshot(type_name):
            return None
        return self.store.density_pushdown(
            type_name, query, envelope, width, height
        )

    def stats_pushdown(self, type_name, query, stat_spec):
        if self._runs_snapshot(type_name):
            return None
        return self.store.stats_pushdown(type_name, query, stat_spec)

    def has_chunk_stats(self, type_name: str) -> bool:
        """False while live runs exist: the brownout rung must not
        promise a pre-aggregated answer that misses the memtable."""
        if self._runs_snapshot(type_name):
            return False
        return self.store.has_chunk_stats(type_name)

    def manifest_rows(self, type_name: str) -> int:
        return self.store.manifest_rows(type_name) + sum(
            r.rows for r in self._runs_snapshot(type_name)
        )

    # -- compaction --------------------------------------------------------

    def _kick(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def compact_now(self, type_name: "str | None" = None) -> None:
        """Synchronous compaction (tests, CLI, drain): merge every live
        run of ``type_name`` (or all types) into the partition files."""
        for t in ([type_name] if type_name else list(self._streams)):
            ts = self._streams.get(t)
            if ts is None:
                continue
            ts.kicked = True
            self._compact_type(t, ts)

    def _compact_due(self, ts: _TypeStream) -> bool:
        from geomesa_tpu.conf import sys_prop

        if ts.kicked:
            return True
        with ts.lock:
            rows = sum(r.rows for r in ts.runs)
            nruns = len(ts.runs)
        return rows >= int(sys_prop("stream.memtable.rows")) or \
            nruns >= max(int(sys_prop("wal.max.generations")), 1)

    def _at_bound(self, ts: _TypeStream) -> bool:
        from geomesa_tpu.conf import sys_prop

        with ts.lock:
            return len(ts.runs) >= max(
                int(sys_prop("wal.max.generations")), 1
            )

    def _yield_to_serving(self, ts: _TypeStream) -> None:
        """Brownout discipline: while the scheduler queue is past the
        brownout fraction AND appends are not yet blocked at the bound,
        the compactor pauses in ``stream.compact.yield.ms`` steps —
        bounded by ``stream.stall.s`` so a permanently saturated queue
        can never starve compaction into an ingest stall."""
        from geomesa_tpu import metrics, resilience
        from geomesa_tpu.conf import sys_prop

        step = max(float(sys_prop("stream.compact.yield.ms")), 1.0) / 1e3
        budget = max(float(sys_prop("stream.stall.s")) / 2.0, step)
        spent = 0.0
        while (
            spent < budget
            and not self._stop
            and not ts.kicked
            and not self._at_bound(ts)
            and resilience.brownout(self.scheduler)
        ):
            metrics.stream_compact_yields.inc()
            time.sleep(step)
            spent += step

    def _compact_loop(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
                self._cv.wait(timeout=0.25)
                if self._stop:
                    return
            for t in list(self._streams):
                ts = self._streams.get(t)
                if ts is None or not self._compact_due(ts):
                    continue
                self._yield_to_serving(ts)
                try:
                    self._compact_type(t, ts)
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "dataset %r: background compaction failed "
                        "(%s: %s); acked rows remain WAL-durable and "
                        "memtable-served; will retry",
                        t, type(e).__name__, e,
                    )
                    time.sleep(0.2)  # no hot-loop against a broken disk

    def _compact_type(self, type_name: str, ts: _TypeStream) -> None:
        """One generational compaction: seal + merge the live runs,
        flush them through the store's crash-consistent rewrite with
        the WAL watermark in the SAME manifest publish, drop the sealed
        runs inside the same exclusive section, then truncate the
        consumed WAL segments. A crash before publish replays
        everything; after publish, the watermark makes replay skip it."""
        from geomesa_tpu import ledger, metrics
        from geomesa_tpu.failpoints import fail_point
        from geomesa_tpu.tracing import span

        t0 = time.perf_counter()
        ts.kicked = False
        with span("stream.compact", type=type_name) as sp, \
                self.store._exclusive():
            self.store._refresh_from_disk(type_name)
            st = self.store._types[type_name]
            with ts.lock:
                runs = list(ts.runs)
                for r in runs:
                    r.sealed = True  # appends stop coalescing into these
            if not runs:
                return
            watermark = max(r.max_seq for r in runs)
            merged = (
                runs[0].built.batch
                if len(runs) == 1
                else FeatureBatch.concat([r.built.batch for r in runs])
            )
            sp.set(runs=len(runs), rows=len(merged))
            prev_wm = st.wal_watermark
            st.pending.append(merged)
            st.wal_watermark = max(prev_wm, watermark)
            try:
                self.store._flush_locked(type_name)
            except BaseException:
                # an unpublished failure restored pending (including
                # our merged batch) for retry — but the RUNS remain the
                # live copy and the WAL the durable one; leaving the
                # batch in pending would double every row on the next
                # flush. Roll both back. The one exception: a POST-
                # publish failure adopted the new on-disk state (the
                # manifest owns the rows, pending was NOT restored —
                # detected by our batch's absence) — fall through and
                # drop the compacted runs like a success.
                advanced = not any(b is merged for b in st.pending)
                if not advanced:
                    st.pending = [
                        b for b in st.pending if b is not merged
                    ]
                    st.wal_watermark = prev_wm
                    with ts.lock:
                        # the runs stay live: re-open them to tail
                        # coalescing, or one transient flush error
                        # would pin every future append into its own
                        # run and race the 429 bound spuriously
                        for r in runs:
                            r.sealed = False
                    raise
            with ts.lock:
                sealed = {id(r) for r in runs}
                ts.runs = [r for r in ts.runs if id(r) not in sealed]
                mem_rows = sum(r.rows for r in ts.runs)
                nruns = len(ts.runs)
        metrics.stream_memtable_rows.set(mem_rows, type=type_name)
        metrics.stream_memtable_runs.set(nruns, type=type_name)
        fail_point("fail.compact.publish")
        ts.wal.truncate_through(self._retention_seq(type_name, watermark))
        dur = time.perf_counter() - t0
        ts.compactions += 1
        ts.last_publish = time.monotonic()
        ts.last_compact_s = dur
        metrics.stream_compactions.inc()
        metrics.stream_compact_seconds.observe(dur)
        if ledger.enabled():
            # background work still lands on /stats/ledger, under the
            # _system tenant — never through the SLO engine (a 30s
            # compaction is not a serving-latency sample)
            cost = ledger.RequestCost(
                tenant="_system", endpoint="other", lane="batch",
                shape="compact",
            )
            cost.status = 200
            cost.dur_s = dur
            cost.charge("compact_seconds", dur)
            ledger.LEDGER.record(cost)

    def _retention_seq(self, type_name: str, watermark: int) -> int:
        """WAL truncation bound: the manifest watermark, capped by the
        replication retention floor when one is installed — segments a
        recently-seen follower still has to ship must outlive their
        compaction, or the leader's own GC forces that follower into a
        410 snapshot re-provision (the check-then-act race the review
        flagged). Subscriber-cursor floors (``add_retention_floor``)
        compose the same way: the bound is the min over every installed
        floor. Best-effort: a broken hook never blocks compaction."""
        bound = int(watermark)
        hooks = list(self._retention_floors)
        if self.retention_floor is not None:
            hooks.append(self.retention_floor)
        for fn in hooks:
            try:
                floor = fn(type_name)
            except Exception:  # lint: disable=GT011(a failing retention hook must not wedge compaction; skipping it only retains MORE, never less)
                continue
            if floor is not None:
                bound = min(bound, int(floor))
        return bound

    # -- recovery ----------------------------------------------------------

    def _recover_all(self) -> None:
        for type_name in self.store.type_names:
            if os.path.isdir(self._wal_dir(type_name)):
                self._recover_type(type_name)

    def _recover_type(self, type_name: str) -> None:
        """Replay the WAL into memtable runs at open: records at or
        below the manifest watermark are already in the partition files
        (skipped — idempotent), torn tails were truncated by the
        segment scan (stamped ``wal-replay-truncated``), and stale
        fully-compacted segments are garbage-collected."""
        from geomesa_tpu import metrics, resilience

        ts = self._ts(type_name)  # opening the WAL truncates torn tails
        st = self.store._types[type_name]
        watermark = int(st.wal_watermark)
        replayed = 0
        with ts.lock:
            for seq, payload in ts.wal.replay(after_seq=watermark):
                batch = self._decode(type_name, payload)
                if len(batch):
                    self._insert_locked(type_name, ts, batch, seq)
                    replayed += len(batch)
            ts.appended_rows += replayed
            mem_rows = sum(r.rows for r in ts.runs)
            nruns = len(ts.runs)
        if ts.wal.truncations:
            resilience.note_degraded("wal-replay-truncated")
        if replayed:
            metrics.stream_wal_replay_rows.inc(replayed)
            metrics.stream_memtable_rows.set(mem_rows, type=type_name)
            metrics.stream_memtable_runs.set(nruns, type=type_name)
            import logging

            logging.getLogger(__name__).info(
                "dataset %r: WAL replay recovered %d acked row(s) into "
                "%d memtable run(s)", type_name, replayed, nruns,
            )
        ts.wal.truncate_through(watermark)

    # -- introspection / lifecycle -----------------------------------------

    def stream_stats(self) -> dict:
        """The ``/stats/stream`` document."""
        from geomesa_tpu import metrics
        from geomesa_tpu.conf import sys_prop

        types = {}
        for t, ts in list(self._streams.items()):
            with ts.lock:
                runs = [
                    {"rows": r.rows, "max_seq": r.max_seq,
                     "sealed": r.sealed}
                    for r in ts.runs
                ]
            st = self.store._types.get(t)
            types[t] = {
                "memtable_rows": int(sum(r["rows"] for r in runs)),
                "runs": runs,
                "wal_watermark": int(st.wal_watermark) if st else -1,
                "appended_rows": ts.appended_rows,
                "compactions": ts.compactions,
                "last_compact_seconds": round(ts.last_compact_s, 4),
                "seconds_since_publish": round(
                    time.monotonic() - ts.last_publish, 3
                ),
                "wal": ts.wal.stats(),
            }
        return {
            "enabled": True,
            "max_generations": int(sys_prop("wal.max.generations")),
            "types": types,
            "counters": {
                "appends": metrics.stream_appends.value(),
                "rows": metrics.stream_rows.value(),
                "wal_bytes": metrics.stream_wal_bytes.value(),
                "wal_fsyncs": metrics.stream_wal_fsyncs.value(),
                "backpressure": metrics.stream_backpressure.value(),
                "compactions": metrics.stream_compactions.value(),
                "replay_rows": metrics.stream_wal_replay_rows.value(),
                "replay_truncations":
                    metrics.stream_wal_truncations.value(),
            },
        }

    def close(self, compact: bool = False) -> None:
        """Stop the compactor and close the WAL segments. Acked rows
        not yet compacted stay durable in the WAL and replay on the
        next open; ``compact=True`` folds them into partition files
        first (a drain, not a data-safety requirement)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._compactor.join(timeout=10.0)
        if compact:
            for t in list(self._streams):
                ts = self._streams[t]
                if self._runs_snapshot(t):
                    try:
                        self._compact_type(t, ts)
                    except Exception:  # lint: disable=GT011(final best-effort compact on close: rows stay WAL-durable and replay on reopen)  # rows stay WAL-durable
                        pass
        for ts in self._streams.values():
            ts.wal.close()
