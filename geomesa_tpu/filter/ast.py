"""Filter expression tree.

A structural subset of GeoTools' Filter model as used by GeoMesa's planner
(ref: geomesa-filter .../FilterHelper.scala visitors [UNVERIFIED - empty
reference mount]). Temporal literals are epoch milliseconds; geometries are
geomesa_tpu.geom values.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

from geomesa_tpu.geom import Envelope, Geometry


class Filter:
    def __and__(self, other: "Filter") -> "Filter":
        return And((self, other))

    def __or__(self, other: "Filter") -> "Filter":
        return Or((self, other))

    def __invert__(self) -> "Filter":
        return Not(self)


@dataclass(frozen=True)
class _Include(Filter):
    def __repr__(self):
        return "INCLUDE"


@dataclass(frozen=True)
class _Exclude(Filter):
    def __repr__(self):
        return "EXCLUDE"


Include = _Include()
Exclude = _Exclude()


@dataclass(frozen=True)
class And(Filter):
    children: tuple

    def __init__(self, children: Sequence[Filter]):
        flat = []
        for c in children:
            if isinstance(c, And):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))


@dataclass(frozen=True)
class Or(Filter):
    children: tuple

    def __init__(self, children: Sequence[Filter]):
        flat = []
        for c in children:
            if isinstance(c, Or):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))


@dataclass(frozen=True)
class Not(Filter):
    child: Filter


@dataclass(frozen=True)
class BBox(Filter):
    attr: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    def __post_init__(self):
        # normalize numpy scalars (e.g. a kNN window computed in float64)
        # to plain Python floats: numpy scalars are STRONG-typed under
        # jax and would silently promote float32 device planes to float64
        # inside the scan kernels — which Mosaic cannot lower on TPU
        for f in ("xmin", "ymin", "xmax", "ymax"):
            object.__setattr__(self, f, float(getattr(self, f)))

    @property
    def envelope(self) -> Envelope:
        return Envelope(self.xmin, self.ymin, self.xmax, self.ymax)


@dataclass(frozen=True)
class Intersects(Filter):
    """Geometry relation predicate (op records the original verb; WITHIN =
    data within query geometry as issued by typical GeoServer clients).
    ``pattern`` carries the DE-9IM mask for op='relate'."""

    attr: str
    geometry: Geometry
    # intersects | within | contains | disjoint | crosses | touches |
    # overlaps | equals | relate
    op: str = "intersects"
    pattern: "str | None" = None


@dataclass(frozen=True)
class DWithin(Filter):
    """Distance-within (degrees; ref geomesa handles unit conversion at
    parse time)."""

    attr: str
    geometry: Geometry
    distance: float

    def __post_init__(self):
        # same numpy-scalar normalization as BBox (f64 promotion guard)
        object.__setattr__(self, "distance", float(self.distance))


@dataclass(frozen=True)
class During(Filter):
    """t in [t0, t1] (ms). GeoTools DURING is exclusive at both ends, but
    GeoMesa's planner treats intervals inclusively at ms resolution
    (FilterHelper.extractIntervals endpoint handling); we keep inclusive
    bounds and record the original exclusivity."""

    attr: str
    t0: int
    t1: int
    exclusive: bool = False


@dataclass(frozen=True)
class Compare(Filter):
    """attr <op> literal; op in =, <>, <, <=, >, >=."""

    op: str
    attr: str
    value: Any


@dataclass(frozen=True)
class Between(Filter):
    attr: str
    lo: Any
    hi: Any


@dataclass(frozen=True)
class In(Filter):
    attr: str
    values: tuple


@dataclass(frozen=True)
class Like(Filter):
    attr: str
    pattern: str  # SQL LIKE: % and _

    def regex(self) -> str:
        import re as _re

        out = []
        for ch in self.pattern:
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(_re.escape(ch))
        return "^" + "".join(out) + "$"


@dataclass(frozen=True)
class IsNull(Filter):
    attr: str
    negate: bool = False


def attributes_of(f: Filter) -> set:
    """All attribute names referenced by a filter."""
    if isinstance(f, (And, Or)):
        out: set = set()
        for c in f.children:
            out |= attributes_of(c)
        return out
    if isinstance(f, Not):
        return attributes_of(f.child)
    attr = getattr(f, "attr", None)
    return {attr} if attr else set()
