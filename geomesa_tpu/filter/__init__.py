"""CQL filter layer (maps reference L5: geomesa-filter).

- ``ast``:     filter expression tree
- ``ecql``:    text parser for the (E)CQL subset
               (ref: GeoTools ECQL + geomesa-filter FilterHelper usage)
- ``extract``: spatial/temporal bound extraction
               (ref: geomesa-filter .../FilterHelper.scala
               extractGeometries / extractIntervals)
- ``compile``: AST -> vectorized evaluators (host numpy exact; device jax
               for the kernel-scannable subset -- the Z3Iterator /
               FilterTransformIterator analog)
"""

from geomesa_tpu.filter.ast import (
    And,
    BBox,
    Between,
    Compare,
    During,
    Exclude,
    Filter,
    In,
    Include,
    Intersects,
    IsNull,
    Like,
    Not,
    Or,
)
from geomesa_tpu.filter.compile import CompiledFilter, compile_filter
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.filter.extract import (
    FilterBounds,
    extract_geometries,
    extract_intervals,
)

__all__ = [
    "Filter",
    "Include",
    "Exclude",
    "And",
    "Or",
    "Not",
    "BBox",
    "Intersects",
    "During",
    "Between",
    "Compare",
    "In",
    "Like",
    "IsNull",
    "parse_ecql",
    "extract_geometries",
    "extract_intervals",
    "FilterBounds",
    "compile_filter",
    "CompiledFilter",
]
