"""(E)CQL text parser for the supported filter subset.

Grammar (recursive descent, case-insensitive keywords):

    filter    := or
    or        := and (OR and)*
    and       := not (AND not)*
    not       := NOT not | primary
    primary   := '(' filter ')' | INCLUDE | EXCLUDE | spatial | predicate
    spatial   := BBOX '(' attr ',' num ',' num ',' num ',' num [',' str] ')'
               | INTERSECTS/WITHIN/CONTAINS/DISJOINT '(' attr ',' wkt ')'
               | DWITHIN '(' attr ',' wkt ',' num ',' units ')'
    predicate := attr op literal                 op in = <> != < <= > >=
               | attr BETWEEN literal AND literal
               | attr DURING instant '/' instant
               | attr (AFTER|BEFORE) instant
               | attr IN '(' literal (',' literal)* ')'
               | attr LIKE string
               | attr IS [NOT] NULL
    literal   := number | 'string' | instant
    instant   := ISO-8601 date-time (optionally quoted)

Matches the operator coverage GeoMesa's planner extracts bounds from
(ref: geomesa-filter .../FilterHelper.scala + visitor utilities
[UNVERIFIED - empty reference mount]).
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.geom import Envelope, parse_wkt
from geomesa_tpu.geom.base import Polygon

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<lparen>\()
      | (?P<rparen>\))
      | (?P<comma>,)
      | (?P<slash>/)
      | (?P<op><=|>=|<>|!=|=|<|>)
      | (?P<string>'(?:[^']|'')*')
      | (?P<datetime>\d{4}-\d{2}-\d{2}T[\d:.]+Z?)
      | (?P<number>-?\d+\.?\d*(?:[eE][-+]?\d+)?)
      | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""",
    re.VERBOSE,
)

_SPATIAL = {
    "BBOX",
    "INTERSECTS",
    "WITHIN",
    "CONTAINS",
    "DISJOINT",
    "DWITHIN",
    "CROSSES",
    "TOUCHES",
    "OVERLAPS",
    "EQUALS",
    "RELATE",
}


class _P:
    def __init__(self, text: str):
        self.text = text
        self.toks: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m or m.end() == pos:
                if text[pos:].strip():
                    raise ValueError(f"cannot tokenize at {text[pos:pos+20]!r}")
                break
            pos = m.end()
            for name, val in m.groupdict().items():
                if val is not None:
                    self.toks.append((name, val))
                    break
        self.i = 0

    def peek(self, k: int = 0):
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        if t[0] is None:
            raise ValueError("unexpected end of filter")
        self.i += 1
        return t

    def expect(self, kind: str, val: str | None = None):
        k, v = self.next()
        if k != kind or (val is not None and v.upper() != val):
            raise ValueError(f"expected {val or kind}, got {v!r}")
        return v

    def at_word(self, *words: str) -> bool:
        k, v = self.peek()
        return k == "word" and v.upper() in words


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


def parse_instant(s: str) -> int:
    """ISO-8601 -> epoch millis (UTC)."""
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1]
    return int(np.datetime64(s, "ms").astype(np.int64))


_DT_RE = re.compile(r"^\d{4}-\d{2}-\d{2}(T[\d:.]+Z?)?$")


def _instant_tok(tok) -> int:
    """Instant from a datetime or (quoted) string token."""
    kind, val = tok
    if kind == "string":
        return parse_instant(_unquote(val))
    return parse_instant(val)


def _literal(tok) -> object:
    kind, val = tok
    if kind == "number":
        f = float(val)
        return int(f) if f.is_integer() and "." not in val and "e" not in val.lower() else f
    if kind == "string":
        s = _unquote(val)
        if _DT_RE.match(s):
            try:
                return parse_instant(s)
            except Exception:
                return s
        return s
    if kind == "datetime":
        return parse_instant(val)
    raise ValueError(f"expected literal, got {val!r}")


def parse_ecql(text: str) -> ast.Filter:
    text = text.strip()
    if not text:
        return ast.Include
    p = _P(text)
    f = _or(p)
    if p.peek()[0] is not None:
        raise ValueError(f"trailing input at {p.peek()[1]!r}")
    return f


def _or(p: _P) -> ast.Filter:
    left = _and(p)
    parts = [left]
    while p.at_word("OR"):
        p.next()
        parts.append(_and(p))
    return parts[0] if len(parts) == 1 else ast.Or(tuple(parts))


def _and(p: _P) -> ast.Filter:
    parts = [_not(p)]
    while p.at_word("AND"):
        p.next()
        parts.append(_not(p))
    return parts[0] if len(parts) == 1 else ast.And(tuple(parts))


def _not(p: _P) -> ast.Filter:
    if p.at_word("NOT"):
        p.next()
        return ast.Not(_not(p))
    return _primary(p)


def _wkt_geom(p: _P):
    """Consume a WKT geometry (word + balanced parens) from the stream."""
    kind, word = p.next()
    if kind != "word":
        raise ValueError(f"expected geometry, got {word!r}")
    start = p.i
    p.expect("lparen")
    depth = 1
    while depth:
        k, v = p.next()
        if k == "lparen":
            depth += 1
        elif k == "rparen":
            depth -= 1
    # reconstruct the wkt text span
    toks = p.toks[start : p.i]
    body = ""
    for k, v in toks:
        body += v if k != "comma" else ", "
        if k in ("number",):
            body += " "
    return parse_wkt(word + " " + body)


def _primary(p: _P) -> ast.Filter:
    kind, val = p.peek()
    if kind == "lparen":
        p.next()
        f = _or(p)
        p.expect("rparen")
        return f
    if kind != "word":
        raise ValueError(f"unexpected token {val!r}")
    upper = val.upper()
    if upper == "INCLUDE":
        p.next()
        return ast.Include
    if upper == "EXCLUDE":
        p.next()
        return ast.Exclude
    # spatial verbs are only reserved when called like functions -- a
    # column may legitimately be named 'overlaps' or 'equals'
    if upper in _SPATIAL and p.peek(1)[0] == "lparen":
        return _spatial(p, upper)
    return _predicate(p)


def _spatial(p: _P, op: str) -> ast.Filter:
    p.next()  # the op word
    p.expect("lparen")
    attr = p.expect("word")
    p.expect("comma")
    if op == "BBOX":
        nums = []
        for i in range(4):
            k, v = p.next()
            if k != "number":
                raise ValueError(f"BBOX expects numbers, got {v!r}")
            nums.append(float(v))
            if i < 3:
                p.expect("comma")
        # optional crs string
        if p.peek()[0] == "comma":
            p.next()
            p.next()  # crs literal, ignored (4326 assumed)
        p.expect("rparen")
        return ast.BBox(attr, nums[0], nums[1], nums[2], nums[3])
    geom = _wkt_geom(p)
    if isinstance(geom, Envelope):
        geom_poly = Polygon(
            [
                (geom.xmin, geom.ymin),
                (geom.xmax, geom.ymin),
                (geom.xmax, geom.ymax),
                (geom.xmin, geom.ymax),
                (geom.xmin, geom.ymin),
            ]
        )
    else:
        geom_poly = geom
    if op == "DWITHIN":
        p.expect("comma")
        k, v = p.next()
        dist = float(v)
        p.expect("comma")
        units = p.expect("word").lower()
        p.expect("rparen")
        factor = {
            "meters": 1 / 111_320.0,
            "kilometers": 1 / 111.32,
            "feet": 0.3048 / 111_320.0,
            "statute": 1609.34 / 111_320.0,
        }.get(units, 1.0)
        return ast.DWithin(attr, geom_poly, dist * factor)
    if op == "RELATE":
        p.expect("comma")
        k, v = p.next()
        if k != "string":
            raise ValueError(f"RELATE expects a DE-9IM pattern string, got {v!r}")
        from geomesa_tpu.geom.predicates import validate_de9im_pattern

        # fail at parse time, not deep inside a per-row scan
        pat = validate_de9im_pattern(_unquote(v))
        p.expect("rparen")
        return ast.Intersects(attr, geom_poly, op="relate", pattern=pat)
    p.expect("rparen")
    return ast.Intersects(attr, geom_poly, op=op.lower())


def _predicate(p: _P) -> ast.Filter:
    attr = p.expect("word")
    kind, val = p.peek()
    if kind == "op":
        p.next()
        lit = _literal(p.next())
        op = "<>" if val == "!=" else val
        return ast.Compare(op, attr, lit)
    if kind != "word":
        raise ValueError(f"unexpected {val!r} after {attr!r}")
    word = val.upper()
    p.next()
    if word == "BETWEEN":
        lo = _literal(p.next())
        p.expect("word", "AND")
        hi = _literal(p.next())
        return ast.Between(attr, lo, hi)
    if word == "DURING":
        t0 = _instant_tok(p.next())
        p.expect("slash")
        t1 = _instant_tok(p.next())
        return ast.During(attr, t0, t1)
    if word == "AFTER":
        return ast.Compare(">", attr, _instant_tok(p.next()))
    if word == "BEFORE":
        return ast.Compare("<", attr, _instant_tok(p.next()))
    if word == "IN":
        p.expect("lparen")
        vals = [_literal(p.next())]
        while p.peek()[0] == "comma":
            p.next()
            vals.append(_literal(p.next()))
        p.expect("rparen")
        return ast.In(attr, tuple(vals))
    if word == "LIKE":
        k, v = p.next()
        if k != "string":
            raise ValueError("LIKE expects a string pattern")
        return ast.Like(attr, _unquote(v))
    if word == "IS":
        negate = False
        if p.at_word("NOT"):
            p.next()
            negate = True
        p.expect("word", "NULL")
        return ast.IsNull(attr, negate)
    raise ValueError(f"unsupported predicate {word!r}")
