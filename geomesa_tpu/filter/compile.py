"""Filter compilation: AST -> vectorized evaluators.

Two targets (mirrors the reference's split between key-range planning and
per-feature iterator evaluation, ref: geomesa-accumulo iterators/
FilterTransformIterator + Z3Iterator [UNVERIFIED - empty reference mount]):

- **host**: exact numpy evaluation over a FeatureBatch. Supports the whole
  AST including object columns (strings, non-point geometries). This is the
  correctness oracle and the residual evaluator.
- **device**: a jit-compatible function over a dict of jax arrays for the
  device-scannable subset (numeric/temporal compares, bbox, point-in-polygon
  on point columns). The filter is CNF-split: supported conjuncts fuse into
  one device mask; the remainder becomes the host residual applied to
  device-surviving candidates.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.geom import Envelope, Point, Polygon, points_in_polygon
from geomesa_tpu.geom.predicates import (
    geometry_intersects,
    geometry_within,
    points_in_polygon_jax,
)


# ---------------------------------------------------------------------------
# host (exact, numpy)
# ---------------------------------------------------------------------------


def evaluate_host(f: ast.Filter, batch: FeatureBatch) -> np.ndarray:
    """Exact boolean mask for the full filter over a batch."""
    n = len(batch)
    if f is ast.Include:
        return np.ones(n, dtype=bool)
    if f is ast.Exclude:
        return np.zeros(n, dtype=bool)
    if isinstance(f, ast.And):
        m = np.ones(n, dtype=bool)
        for c in f.children:
            m &= evaluate_host(c, batch)
        return m
    if isinstance(f, ast.Or):
        m = np.zeros(n, dtype=bool)
        for c in f.children:
            m |= evaluate_host(c, batch)
        return m
    if isinstance(f, ast.Not):
        return ~evaluate_host(f.child, batch)
    if isinstance(f, ast.BBox):
        return _host_bbox(f, batch)
    if isinstance(f, (ast.Intersects, ast.DWithin)):
        return _host_spatial(f, batch)
    if isinstance(f, ast.During):
        col = batch.column(f.attr)
        return (col >= f.t0) & (col <= f.t1)
    if isinstance(f, ast.Between):
        col = batch.column(f.attr)
        return (col >= f.lo) & (col <= f.hi)
    if isinstance(f, ast.Compare):
        col = batch.column(f.attr)
        v = f.value
        if f.op == "=":
            return col == v
        if f.op == "<>":
            return col != v
        if f.op == "<":
            return col < v
        if f.op == "<=":
            return col <= v
        if f.op == ">":
            return col > v
        if f.op == ">=":
            return col >= v
        raise ValueError(f.op)
    if isinstance(f, ast.In):
        col = batch.column(f.attr)
        return np.isin(col, np.array(list(f.values), dtype=col.dtype if col.dtype != object else object))
    if isinstance(f, ast.Like):
        col = batch.column(f.attr)
        pat = re.compile(f.regex())
        return np.array(
            [v is not None and pat.match(str(v)) is not None for v in col],
            dtype=bool,
        )
    if isinstance(f, ast.IsNull):
        col = batch.column(f.attr)
        if col.dtype == object:
            m = np.array([v is None for v in col], dtype=bool)
        else:
            m = np.zeros(len(col), dtype=bool)
        return ~m if f.negate else m
    raise TypeError(f"cannot evaluate {type(f)}")


def _host_bbox(f: ast.BBox, batch: FeatureBatch) -> np.ndarray:
    desc = batch.sft.descriptor(f.attr)
    if desc.is_point:
        x, y = batch.point_coords(f.attr)
        return (x >= f.xmin) & (x <= f.xmax) & (y >= f.ymin) & (y <= f.ymax)
    bb = batch.bboxes(f.attr)
    return (
        (bb[:, 2] >= f.xmin)
        & (bb[:, 0] <= f.xmax)
        & (bb[:, 3] >= f.ymin)
        & (bb[:, 1] <= f.ymax)
    )


def _host_spatial(f, batch: FeatureBatch) -> np.ndarray:
    desc = batch.sft.descriptor(f.attr)
    geom = f.geometry
    if isinstance(f, ast.DWithin):
        # expand: for point query geometry, distance test; else envelope pad
        if desc.is_point and isinstance(geom, Point):
            x, y = batch.point_coords(f.attr)
            return (x - geom.x) ** 2 + (y - geom.y) ** 2 <= f.distance**2
        e = geom.envelope
        env = Envelope(
            e.xmin - f.distance,
            e.ymin - f.distance,
            e.xmax + f.distance,
            e.ymax + f.distance,
        )
        return _host_bbox(
            ast.BBox(f.attr, env.xmin, env.ymin, env.xmax, env.ymax), batch
        )
    if isinstance(f, ast.Intersects) and f.op in (
        "crosses",
        "touches",
        "overlaps",
        "equals",
        "relate",
    ):
        return _host_relation(f, batch, desc)
    if desc.is_point:
        x, y = batch.point_coords(f.attr)
        if f.op == "contains" and not isinstance(geom, Point):
            # a point can only contain a point
            return np.zeros(len(batch), dtype=bool)
        if isinstance(geom, Point):
            m = (x == geom.x) & (y == geom.y)
        elif hasattr(geom, "rings"):
            m = points_in_polygon(x, y, geom.rings()) if isinstance(geom, Polygon) else _points_in_multi(x, y, geom)
            # boundary note: crossing-number treats boundary points per
            # half-open rule; GeoMesa/JTS intersects includes boundaries --
            # acceptable divergence at float boundary measure zero.
        else:  # linestring vs point: envelope fallback
            e = geom.envelope
            m = (x >= e.xmin) & (x <= e.xmax) & (y >= e.ymin) & (y <= e.ymax)
        return ~m if f.op == "disjoint" else m
    # non-point data: bbox prefilter then exact per-candidate
    bb = batch.bboxes(f.attr)
    e = geom.envelope
    cand = (
        (bb[:, 2] >= e.xmin)
        & (bb[:, 0] <= e.xmax)
        & (bb[:, 3] >= e.ymin)
        & (bb[:, 1] <= e.ymax)
    )
    col = batch.column(f.attr)
    out = np.zeros(len(batch), dtype=bool)
    if f.op == "within":  # data geometry within query geometry
        for i in np.nonzero(cand)[0]:
            out[i] = geometry_within(col[i], geom)
        return out
    if f.op == "contains":  # data geometry contains query geometry
        for i in np.nonzero(cand)[0]:
            out[i] = geometry_within(geom, col[i])
        return out
    for i in np.nonzero(cand)[0]:
        out[i] = geometry_intersects(col[i], geom)
    return ~out if f.op == "disjoint" else out


def _host_relation(f: "ast.Intersects", batch: FeatureBatch, desc) -> np.ndarray:
    """CROSSES / TOUCHES / OVERLAPS / EQUALS / RELATE residual evaluation:
    bbox prefilter, then the exact DE-9IM-lite predicate per candidate
    (data geometry as first operand, matching ECQL argument order).
    RELATE patterns can match disjoint features, so it skips the prefilter."""
    from geomesa_tpu.geom.predicates import (
        geometry_crosses,
        geometry_overlaps,
        geometry_relate_matches,
        geometry_touches,
    )

    geom = f.geometry
    if desc.is_point:
        x, y = batch.point_coords(f.attr)

        def rowgeom(i):
            return Point(float(x[i]), float(y[i]))

    else:
        col = batch.column(f.attr)

        def rowgeom(i):
            return col[i]

    if f.op == "relate":
        cand = np.arange(len(batch))
        fn = lambda g: geometry_relate_matches(g, geom, f.pattern)
    else:
        e = geom.envelope
        cand = np.nonzero(
            _host_bbox(ast.BBox(f.attr, e.xmin, e.ymin, e.xmax, e.ymax), batch)
        )[0]
        if f.op == "crosses":
            fn = lambda g: geometry_crosses(g, geom)
        elif f.op == "touches":
            fn = lambda g: geometry_touches(g, geom)
        elif f.op == "overlaps":
            fn = lambda g: geometry_overlaps(g, geom)
        else:  # equals via the DE-9IM equality mask
            fn = lambda g: geometry_relate_matches(g, geom, "T*F**FFF*")
    out = np.zeros(len(batch), dtype=bool)
    for i in cand:
        out[i] = fn(rowgeom(i))
    return out


def _points_in_multi(x, y, geom) -> np.ndarray:
    m = np.zeros(len(x), dtype=bool)
    for p in getattr(geom, "polygons", ()):
        m |= points_in_polygon(x, y, p.rings())
    return m


# ---------------------------------------------------------------------------
# device (jax)
# ---------------------------------------------------------------------------


def _device_supported(f: ast.Filter, sft: SimpleFeatureType) -> bool:
    if f in (ast.Include, ast.Exclude):
        return True
    if isinstance(f, (ast.And, ast.Or)):
        return all(_device_supported(c, sft) for c in f.children)
    if isinstance(f, ast.Not):
        return _device_supported(f.child, sft)
    if isinstance(f, ast.BBox):
        # point: coordinate compare; non-point: envelope-overlap compare on
        # the staged bbox planes — which IS the exact BBOX semantics
        # (_host_bbox evaluates envelope intersection for non-points)
        return sft.descriptor(f.attr).is_geometry
    if isinstance(f, ast.Intersects):
        return (
            sft.descriptor(f.attr).is_point
            and hasattr(f.geometry, "rings")
            and f.op in ("intersects", "within", "disjoint")
        )
    if isinstance(f, ast.DWithin):
        # (point, Point): exact distance compare. Every other shape's
        # exact host semantics (_host_spatial) IS the padded-envelope
        # bbox — the same compare runs on device instead.
        return sft.descriptor(f.attr).is_geometry
    if isinstance(f, (ast.During, ast.Between)):
        dtype = sft.descriptor(f.attr).column_dtype
        return dtype is not None and dtype != np.bool_
    if isinstance(f, (ast.Compare, ast.In)):
        dtype = sft.descriptor(f.attr).column_dtype
        return (
            dtype is not None
            and dtype != np.bool_
            and all(
                isinstance(v, (int, float))
                for v in (f.values if isinstance(f, ast.In) else (f.value,))
            )
        )
    return False


def _is_i64(sft: SimpleFeatureType, attr: str) -> bool:
    return sft.descriptor(attr).column_dtype == np.int64


def device_columns_for(f: ast.Filter, sft: SimpleFeatureType) -> list[str]:
    """Device column names needed: ``attr`` for scalars, ``attr__x/__y`` for
    point geometries, ``attr__hi/__lo`` int32/uint32 planes for int64
    scalars (Date/Long -- see ops/int64lanes.py)."""
    cols: list[str] = []
    for attr in sorted(ast.attributes_of(f)):
        desc = sft.descriptor(attr)
        if desc.is_point:
            cols += [f"{attr}__x", f"{attr}__y"]
        elif desc.is_geometry:
            # non-point geometries: per-row envelope planes
            cols += [f"{attr}__x0", f"{attr}__y0",
                     f"{attr}__x1", f"{attr}__y1"]
        elif desc.column_dtype == np.int64:
            cols += [f"{attr}__hi", f"{attr}__lo"]
        elif desc.column_dtype is not None:
            cols.append(attr)
    return cols


def build_device_fn(f: ast.Filter, sft: SimpleFeatureType) -> Callable:
    """AST -> fn(cols: dict[str, jnp.ndarray]) -> bool mask. Caller must
    have checked _device_supported."""

    def rec(node):
        import jax.numpy as jnp

        if node is ast.Include:
            return lambda cols, n: jnp.ones(n, dtype=bool)
        if node is ast.Exclude:
            return lambda cols, n: jnp.zeros(n, dtype=bool)
        if isinstance(node, ast.And):
            fns = [rec(c) for c in node.children]
            def f_and(cols, n, fns=fns):
                m = fns[0](cols, n)
                for fn in fns[1:]:
                    m = m & fn(cols, n)
                return m
            return f_and
        if isinstance(node, ast.Or):
            fns = [rec(c) for c in node.children]
            def f_or(cols, n, fns=fns):
                m = fns[0](cols, n)
                for fn in fns[1:]:
                    m = m | fn(cols, n)
                return m
            return f_or
        if isinstance(node, ast.Not):
            fn = rec(node.child)
            return lambda cols, n, fn=fn: ~fn(cols, n)
        if isinstance(node, ast.BBox):
            if not sft.descriptor(node.attr).is_point:
                pre = f"{node.attr}__"
                def f_bbenv(cols, n, node=node, pre=pre):
                    # envelope overlap == exact BBOX for non-points
                    return (
                        (cols[pre + "x1"] >= node.xmin)
                        & (cols[pre + "x0"] <= node.xmax)
                        & (cols[pre + "y1"] >= node.ymin)
                        & (cols[pre + "y0"] <= node.ymax)
                    )
                return f_bbenv
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"
            def f_bbox(cols, n, node=node, ax=ax, ay=ay):
                x, y = cols[ax], cols[ay]
                return (
                    (x >= node.xmin)
                    & (x <= node.xmax)
                    & (y >= node.ymin)
                    & (y <= node.ymax)
                )
            return f_bbox
        if isinstance(node, ast.Intersects):
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"
            rings = node.geometry.rings()
            neg = node.op == "disjoint"
            def f_int(cols, n, rings=rings, ax=ax, ay=ay, neg=neg):
                m = points_in_polygon_jax(cols[ax], cols[ay], rings)
                return ~m if neg else m
            return f_int
        if isinstance(node, ast.DWithin):
            if not (
                sft.descriptor(node.attr).is_point
                and isinstance(node.geometry, Point)
            ):
                # padded-envelope bbox == the exact host semantics for
                # these shapes (_host_spatial)
                e = node.geometry.envelope
                return rec(ast.BBox(
                    node.attr,
                    e.xmin - node.distance, e.ymin - node.distance,
                    e.xmax + node.distance, e.ymax + node.distance,
                ))
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"
            def f_dw(cols, n, node=node, ax=ax, ay=ay):
                dx = cols[ax] - node.geometry.x
                dy = cols[ay] - node.geometry.y
                return dx * dx + dy * dy <= node.distance**2
            return f_dw
        if isinstance(node, (ast.During, ast.Between)):
            lo = node.t0 if isinstance(node, ast.During) else node.lo
            hi = node.t1 if isinstance(node, ast.During) else node.hi
            attr = node.attr
            if _is_i64(sft, attr):
                import math

                from geomesa_tpu.ops.int64lanes import cmp_jax

                def f_rng64(
                    cols, n, attr=attr, lo=math.ceil(lo), hi=math.floor(hi)
                ):
                    chi, clo = cols[f"{attr}__hi"], cols[f"{attr}__lo"]
                    return cmp_jax(">=", chi, clo, lo) & cmp_jax(
                        "<=", chi, clo, hi
                    )
                return f_rng64
            def f_rng(cols, n, attr=attr, lo=lo, hi=hi):
                c = cols[attr]
                return (c >= lo) & (c <= hi)
            return f_rng
        if isinstance(node, ast.Compare):
            attr, op, v = node.attr, node.op, node.value
            if _is_i64(sft, attr):
                import math

                from geomesa_tpu.ops.int64lanes import cmp_jax

                # Non-integer literals vs int64 lanes: round the bound so the
                # integer compare is equivalent ('>5.5' == '>=6' == '>5').
                if v != math.floor(v):
                    if op in ("=", "<>"):
                        const = op == "<>"
                        def f_const(cols, n, const=const):
                            import jax.numpy as jnp

                            some = next(iter(cols.values()))
                            return jnp.full(some.shape, const, dtype=bool)
                        return f_const
                    # c < 5.5 == c <= 5 ; c > 5.5 == c >= 6
                    if op in ("<", "<="):
                        op, v = "<=", math.floor(v)
                    else:
                        op, v = ">=", math.ceil(v)
                else:
                    v = int(v)

                def f_cmp64(cols, n, attr=attr, op=op, v=v):
                    return cmp_jax(op, cols[f"{attr}__hi"], cols[f"{attr}__lo"], v)
                return f_cmp64
            ops = {
                "=": lambda c: c == v,
                "<>": lambda c: c != v,
                "<": lambda c: c < v,
                "<=": lambda c: c <= v,
                ">": lambda c: c > v,
                ">=": lambda c: c >= v,
            }
            fn0 = ops[op]
            return lambda cols, n, attr=attr, fn0=fn0: fn0(cols[attr])
        if isinstance(node, ast.In):
            attr, vals = node.attr, node.values
            if _is_i64(sft, attr):
                import math

                from geomesa_tpu.ops.int64lanes import cmp_jax

                ivals = [int(v) for v in vals if v == math.floor(v)]

                def f_in64(cols, n, attr=attr, ivals=ivals):
                    import jax.numpy as jnp

                    chi, clo = cols[f"{attr}__hi"], cols[f"{attr}__lo"]
                    if not ivals:
                        return jnp.zeros(chi.shape, dtype=bool)
                    m = cmp_jax("=", chi, clo, ivals[0])
                    for v in ivals[1:]:
                        m = m | cmp_jax("=", chi, clo, v)
                    return m
                return f_in64
            def f_in(cols, n, attr=attr, vals=vals):
                c = cols[attr]
                m = c == vals[0]
                for v in vals[1:]:
                    m = m | (c == v)
                return m
            return f_in
        raise TypeError(f"not device-supported: {type(node)}")

    inner = rec(f)

    def device_fn(cols: dict):
        n = next(iter(cols.values())).shape[0] if cols else 0
        return inner(cols, n)

    return device_fn


# ---------------------------------------------------------------------------
# CompiledFilter
# ---------------------------------------------------------------------------


@dataclass
class CompiledFilter:
    filter: ast.Filter
    sft: SimpleFeatureType
    device_part: ast.Filter  # conjuncts evaluable on device
    residual_part: ast.Filter  # exact host remainder (Include if none)
    device_fn: Callable  # dict[str, jnp.ndarray] -> bool mask
    device_cols: list

    @property
    def fully_on_device(self) -> bool:
        return self.residual_part is ast.Include

    def pallas_scan(self, **kw):
        """(count_fn, mask_fn) Pallas TPU kernels for the device part, or
        None when the filter can't be tiled (callers use device_fn). Cached
        per CompiledFilter and option set."""
        if not hasattr(self, "_pallas"):
            self._pallas = {}
        key = tuple(sorted(kw.items()))
        if key not in self._pallas:
            from geomesa_tpu.ops.pallas_scan import (
                PallasUnsupported,
                build_pallas_scan,
            )

            try:
                count_fn, mask_fn, _ = build_pallas_scan(
                    self.device_part, self.sft, **kw
                )
                self._pallas[key] = (count_fn, mask_fn)
            except PallasUnsupported:
                self._pallas[key] = None
        return self._pallas[key]

    def jitted_scan(self):
        """(count_fn, mask_fn), jitted, choosing the Pallas tile kernels on
        real TPUs and XLA-fused jnp elsewhere (interpret-mode pallas would
        crawl) or when the filter isn't tileable. The single source of the
        kernel-selection rule (used by the query runner and DeviceIndex);
        cached per CompiledFilter."""
        if not hasattr(self, "_jitted_scan"):
            import jax

            scan = (
                self.pallas_scan()
                if jax.devices()[0].platform == "tpu"
                else None
            )
            if scan is not None:
                count_fn, mask_fn = jax.jit(scan[0]), jax.jit(scan[1])
            else:
                mask_fn = jax.jit(self.device_fn)
                count_fn = jax.jit(lambda c: self.device_fn(c).sum())
            self._jitted_scan = (count_fn, mask_fn)
        return self._jitted_scan

    def host_mask(self, batch: FeatureBatch) -> np.ndarray:
        """Exact full-filter mask (oracle path)."""
        return evaluate_host(self.filter, batch)

    def residual_mask(self, batch: FeatureBatch) -> np.ndarray:
        return evaluate_host(self.residual_part, batch)


def _envelope_prefilter(c: ast.Filter, sft: SimpleFeatureType):
    """Device BBox prefilter implied by a residual spatial conjunct, or
    None. Safe only for ops where a hit's envelope must intersect the
    query geometry's envelope (everything except disjoint/relate — the
    complement/arbitrary-matrix cases)."""
    if isinstance(c, ast.Intersects) and c.op in (
        "intersects", "within", "contains", "crosses", "touches",
        "overlaps", "equals",
    ):
        if not sft.descriptor(c.attr).is_geometry:
            return None
        e = c.geometry.envelope
        return ast.BBox(c.attr, e.xmin, e.ymin, e.xmax, e.ymax)
    return None


def compile_filter(f: ast.Filter, sft: SimpleFeatureType) -> CompiledFilter:
    conjuncts = list(f.children) if isinstance(f, ast.And) else [f]
    dev = [c for c in conjuncts if _device_supported(c, sft)]
    res = [c for c in conjuncts if not _device_supported(c, sft)]
    # residual spatial conjuncts still contribute a device envelope
    # prefilter (the classic bbox-then-exact split): the conjunct stays in
    # the residual for exactness, but the device mask prunes candidates
    for c in res:
        pre = _envelope_prefilter(c, sft)
        if pre is not None and _device_supported(pre, sft):
            dev.append(pre)
    device_part: ast.Filter = (
        ast.Include if not dev else (dev[0] if len(dev) == 1 else ast.And(tuple(dev)))
    )
    residual_part: ast.Filter = (
        ast.Include if not res else (res[0] if len(res) == 1 else ast.And(tuple(res)))
    )
    return CompiledFilter(
        filter=f,
        sft=sft,
        device_part=device_part,
        residual_part=residual_part,
        device_fn=build_device_fn(device_part, sft),
        device_cols=device_columns_for(device_part, sft),
    )
