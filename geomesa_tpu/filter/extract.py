"""Spatial/temporal bound extraction from filters.

Mirrors the role of GeoMesa's FilterHelper.extractGeometries /
extractIntervals (ref: geomesa-filter .../FilterHelper.scala [UNVERIFIED -
empty reference mount]): given a filter and an attribute, produce the
extractable bounds (union semantics) that the key spaces turn into scan
ranges, with AND = pairwise intersection, OR = union (only if every branch
is bounded), NOT/other predicates = unbounded.

``FilterBounds.values`` is a list of per-disjunct bounds; ``unbounded=True``
means the filter does not constrain the attribute (full-domain scan);
``values == []`` with ``unbounded=False`` means provably empty (EXCLUDE).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from geomesa_tpu.filter import ast
from geomesa_tpu.geom import Envelope, Geometry


@dataclass(frozen=True)
class FilterBounds:
    values: tuple
    unbounded: bool = False

    @property
    def empty(self) -> bool:
        return not self.unbounded and not self.values

    @staticmethod
    def all() -> "FilterBounds":
        return FilterBounds((), unbounded=True)

    @staticmethod
    def none() -> "FilterBounds":
        return FilterBounds((), unbounded=False)


# ---------------------------------------------------------------------------
# spatial
# ---------------------------------------------------------------------------


def extract_geometries(f: ast.Filter, attr: str) -> FilterBounds:
    """Bounds as a union of (Envelope, exact Geometry | None) pairs. The
    envelope drives range generation; the geometry (when present) is the
    exact shape for residual evaluation."""
    if f is ast.Include:
        return FilterBounds.all()
    if f is ast.Exclude:
        return FilterBounds.none()
    if isinstance(f, ast.BBox) and f.attr == attr:
        return FilterBounds(((f.envelope, None),))
    # every relation except DISJOINT and RELATE implies the data geometry
    # meets the query geometry's envelope (a RELATE pattern can select
    # disjoint features, e.g. 'FF*FF****', so it must not prune)
    if (
        isinstance(f, ast.Intersects)
        and f.attr == attr
        and f.op not in ("disjoint", "relate")
    ):
        return FilterBounds(((f.geometry.envelope, f.geometry),))
    if isinstance(f, ast.DWithin) and f.attr == attr:
        e = f.geometry.envelope
        d = f.distance
        return FilterBounds(
            ((Envelope(e.xmin - d, e.ymin - d, e.xmax + d, e.ymax + d), None),)
        )
    if isinstance(f, ast.And):
        bounds = [extract_geometries(c, attr) for c in f.children]
        return _intersect_all(bounds, _intersect_spatial)
    if isinstance(f, ast.Or):
        bounds = [extract_geometries(c, attr) for c in f.children]
        return _union_all(bounds)
    return FilterBounds.all()


def _intersect_spatial(a, b):
    env_a, geom_a = a
    env_b, geom_b = b
    inter = env_a.intersection(env_b)
    if inter is None:
        return None
    # keep whichever exact geometry survives (both surviving is rare; the
    # residual filter still applies the full predicate set)
    return (inter, geom_a if geom_a is not None else geom_b)


# ---------------------------------------------------------------------------
# temporal
# ---------------------------------------------------------------------------

NEG_INF = -(1 << 62)
POS_INF = 1 << 62


def extract_intervals(f: ast.Filter, attr: str) -> FilterBounds:
    """Bounds as a union of inclusive (t0_ms, t1_ms) intervals."""
    if f is ast.Include:
        return FilterBounds.all()
    if f is ast.Exclude:
        return FilterBounds.none()
    if isinstance(f, ast.During) and f.attr == attr:
        return FilterBounds(((f.t0, f.t1),))
    if isinstance(f, ast.Between) and f.attr == attr:
        lo, hi = f.lo, f.hi
        if isinstance(lo, (int, float)) and isinstance(hi, (int, float)):
            return FilterBounds(((int(lo), int(hi)),))
        return FilterBounds.all()
    if isinstance(f, ast.Compare) and f.attr == attr and isinstance(
        f.value, (int, float)
    ):
        v = int(f.value)
        if f.op == "=":
            return FilterBounds(((v, v),))
        if f.op in (">", ">="):
            return FilterBounds(((v if f.op == ">=" else v + 1, POS_INF),))
        if f.op in ("<", "<="):
            return FilterBounds(((NEG_INF, v if f.op == "<=" else v - 1),))
        return FilterBounds.all()  # <>
    if isinstance(f, ast.And):
        return _intersect_all(
            [extract_intervals(c, attr) for c in f.children], _intersect_interval
        )
    if isinstance(f, ast.Or):
        return _union_all([extract_intervals(c, attr) for c in f.children])
    return FilterBounds.all()


def _intersect_interval(a, b):
    lo = max(a[0], b[0])
    hi = min(a[1], b[1])
    return (lo, hi) if lo <= hi else None


# ---------------------------------------------------------------------------
# combinators
# ---------------------------------------------------------------------------


def _intersect_all(bounds: Sequence[FilterBounds], pair_fn) -> FilterBounds:
    acc: FilterBounds | None = None
    for b in bounds:
        if b.unbounded:
            continue
        if acc is None:
            acc = b
            continue
        values = []
        for va in acc.values:
            for vb in b.values:
                v = pair_fn(va, vb)
                if v is not None:
                    values.append(v)
        acc = FilterBounds(tuple(values))
    return acc if acc is not None else FilterBounds.all()


def _union_all(bounds: Sequence[FilterBounds]) -> FilterBounds:
    values: list = []
    for b in bounds:
        if b.unbounded:
            return FilterBounds.all()
        values.extend(b.values)
    return FilterBounds(tuple(values))
