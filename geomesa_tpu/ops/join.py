"""Batched spatial-join refinement kernels.

The join engine (``geomesa_tpu/join``) plans candidate RUNS — contiguous
row ranges of the Z-sorted join layout, one per (window, covering cell) —
and this module turns run batches into emitted (row, window) pairs:

- **expansion**: run p of the batch contributes rows ``starts[p] ..
  starts[p] + lens[p]`` against window ``wins[p]``; the flat candidate
  index space is segmented by the run-length cumsum (a vectorized
  ``searchsorted``, no per-run dispatch).
- **refinement**: each candidate row's coordinates test against its
  window's envelope — except candidates from INTERIOR runs (cells
  strictly inside the window's covering ring), which are hits by
  construction and skip the coordinate fetch entirely.
- **emission**: fixed-shape count -> cap -> compact (the ``_mesh_hits``
  discipline): a cheap count launch sizes a power-of-two compaction cap,
  then the compact launch scatters the surviving pairs into bounded
  buffers fetched once. Order is preserved end to end (runs are planned
  window-major with ascending rows), so emission needs no sort.

The host (numpy) twins are the bit-identical oracle the device kernels
are tested against AND the production engine on all-CPU harnesses, where
XLA:CPU gathers lose to numpy (the ``mesh.sort.engine`` precedent).
"""

from __future__ import annotations

import numpy as np

# jit caches keyed by static kernel shape buckets (candidate bucket C,
# run bucket R, compaction cap, dtype, gating): bounded — every bucket
# edge sits on the conf-declared compile-shape ladder (next power of
# two on the default ladder)
_COUNT_JITS: dict = {}
_COMPACT_JITS: dict = {}
_MESH_JITS: dict = {}


def next_pow2(n: int) -> int:
    """Round a candidate/run capacity onto the canonical compile-shape
    ladder (:mod:`geomesa_tpu.bucketing`). The name survives from the
    pow2-only era — the default ladder IS next-power-of-two."""
    from geomesa_tpu.bucketing import bucket_cap

    return bucket_cap(n)


def mesh_key(mesh) -> tuple:
    """Stable identity for a mesh: device ids + axis shape. Keying the
    jit caches on ``id(mesh)`` would grow one executable set per mesh
    OBJECT ever constructed (and pin each dead mesh alive through the
    kernel closures); keyed on identity, equal meshes share entries and
    the cache is bounded by the distinct device topologies in use."""
    return (
        tuple(int(d.id) for d in np.ravel(mesh.devices)),
        tuple(mesh.shape.items()),
    )


# -- host expansion + refinement (the oracle engine) -----------------------


def expand_runs(starts, lens, wins, interior):
    """Flatten candidate runs into aligned (rows, wins, interior) arrays.

    ``rows`` enumerates ``starts[p] .. starts[p]+lens[p]`` for each run p
    in order — one cumsum over the candidate space, no per-run python.
    Zero-length runs are dropped before expansion."""
    lens = np.asarray(lens, np.int64)
    keep = lens > 0
    if not np.all(keep):
        starts = np.asarray(starts)[keep]
        wins = np.asarray(wins)[keep]
        interior = np.asarray(interior)[keep]
        lens = lens[keep]
    if len(lens) == 0:
        e = np.empty(0, np.int64)
        return e, e.copy(), np.empty(0, bool)
    total = int(lens.sum())
    csum = np.cumsum(lens)
    # rows via delta-encoded cumsum: position 0 starts the first run and
    # every run boundary jumps from the previous run's end to the next
    # run's start; everything else increments by one
    deltas = np.ones(total, np.int64)
    deltas[0] = int(starts[0])
    deltas[csum[:-1]] = np.asarray(starts[1:], np.int64) - (
        np.asarray(starts[:-1], np.int64) + lens[:-1] - 1
    )
    rows = np.cumsum(deltas)
    winv = np.repeat(np.asarray(wins, np.int64), lens)
    iflag = np.repeat(np.asarray(interior, bool), lens)
    return rows, winv, iflag


def refine_host(xs, ys, envs, rows, winv, iflag, gate=None):
    """Exact envelope refinement of expanded candidates on host: hit
    mask over the candidates. Interior candidates skip the coordinate
    fetch (hits by construction); ``gate`` is an optional per-row bool
    plane (base filter / visibility) ANDed into every candidate."""
    hit = iflag.copy()
    bidx = np.nonzero(~iflag)[0]
    if len(bidx):
        brow = rows[bidx]
        e = envs[winv[bidx]]
        px = xs[brow]
        py = ys[brow]
        bh = (
            (px >= e[:, 0])
            & (px <= e[:, 2])
            & (py >= e[:, 1])
            & (py <= e[:, 3])
        )
        hit[bidx] = bh
    if gate is not None:
        hit &= gate[rows]
    return hit


def refine_host_env(ex0, ey0, ex1, ey1, envs, rows, winv, iflag, gate=None):
    """Envelope-OVERLAP refinement for non-point left sides (per-row
    envelope planes vs window envelopes) — the coarse pass of a
    topological join; the exact predicate refines the emitted pairs."""
    hit = iflag.copy()
    bidx = np.nonzero(~iflag)[0]
    if len(bidx):
        brow = rows[bidx]
        e = envs[winv[bidx]]
        bh = (
            (ex1[brow] >= e[:, 0])
            & (ex0[brow] <= e[:, 2])
            & (ey1[brow] >= e[:, 1])
            & (ey0[brow] <= e[:, 3])
        )
        hit[bidx] = bh
    if gate is not None:
        hit &= gate[rows]
    return hit


# -- device kernels (count -> cap -> compact) ------------------------------


def _expand_refine(planes, starts, lens, csum, winv, iflag, envs, total,
                   gate, C, n_planes):
    """Shared traced body: expand the run batch into the C-sized
    candidate space and compute the hit vector. ``planes`` is (x, y) for
    point layouts or (x0, y0, x1, y1) envelope planes for non-point
    (overlap test)."""
    import jax.numpy as jnp

    R = starts.shape[0]
    p = jnp.arange(C, dtype=jnp.int32)
    seg = jnp.searchsorted(csum, p, side="right").astype(jnp.int32)
    segc = jnp.minimum(seg, R - 1)
    base = csum[segc] - lens[segc]
    row = starts[segc] + (p - base)
    row = jnp.clip(row, 0, planes[0].shape[0] - 1)
    win = winv[segc]
    valid = p < total
    e = envs[win]
    if n_planes == 2:
        px = planes[0][row]
        py = planes[1][row]
        env_hit = (
            (px >= e[:, 0]) & (px <= e[:, 2])
            & (py >= e[:, 1]) & (py <= e[:, 3])
        )
    else:
        env_hit = (
            (planes[2][row] >= e[:, 0]) & (planes[0][row] <= e[:, 2])
            & (planes[3][row] >= e[:, 1]) & (planes[1][row] <= e[:, 3])
        )
    hit = valid & (iflag[segc] | env_hit)
    if gate is not None:
        hit = hit & gate[row]
    return row, win, hit


def count_kernel(C: int, n_planes: int, gated: bool, dtype):
    """Jitted candidate-count launch for one (C, planes, gate) bucket:
    returns the number of surviving pairs (a scalar fetch that sizes the
    compact launch's cap)."""
    import jax
    import jax.numpy as jnp

    key = ("count", C, n_planes, gated, np.dtype(dtype).str)
    fn = _COUNT_JITS.get(key)
    if fn is None:

        def _count(planes, starts, lens, csum, winv, iflag, envs, total,
                   gate):
            _, _, hit = _expand_refine(
                planes, starts, lens, csum, winv, iflag, envs, total,
                gate, C, n_planes,
            )
            return jnp.sum(hit, dtype=jnp.int32)

        fn = jax.jit(_count)
        _COUNT_JITS[key] = fn
    return fn


def compact_kernel(C: int, cap: int, n_planes: int, gated: bool, dtype):
    """Jitted compact launch for one (C, cap, planes, gate) bucket:
    scatters surviving (row, window) pairs — order preserved — into
    cap-sized buffers plus the true count (callers slice ``[:count]``)."""
    import jax
    import jax.numpy as jnp

    key = ("compact", C, cap, n_planes, gated, np.dtype(dtype).str)
    fn = _COMPACT_JITS.get(key)
    if fn is None:

        def _compact(planes, starts, lens, csum, winv, iflag, envs, total,
                     gate):
            row, win, hit = _expand_refine(
                planes, starts, lens, csum, winv, iflag, envs, total,
                gate, C, n_planes,
            )
            pos = jnp.cumsum(hit.astype(jnp.int32)) - 1
            idx = jnp.where(hit & (pos < cap), pos, cap)  # cap = trash slot
            rbuf = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(row)
            wbuf = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(win)
            return rbuf[:cap], wbuf[:cap], jnp.sum(hit, dtype=jnp.int32)

        fn = jax.jit(_compact)
        _COMPACT_JITS[key] = fn
    return fn


def mesh_count_kernel(mesh, axis: str, C: int, n_planes: int,
                      gated: bool, dtype):
    """Per-shard candidate counts for one co-partitioned run batch —
    the count half of the mesh count -> cap -> compact discipline (one
    cheap (shards,)-vector fetch sizes the compact launch's cap)."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.dist import shard_map

    key = ("mesh-count", mesh_key(mesh), axis, C, n_planes, gated,
           np.dtype(dtype).str)
    fn = _MESH_JITS.get(key)
    if fn is None:
        spec = P(axis)

        @partial(
            shard_map, mesh=mesh,
            in_specs=(spec,) * n_planes + (spec,) * 5 + (P(),)
            + ((spec,) if gated else ()),
            out_specs=spec, check_vma=False,
        )
        def _mesh_count(*args):
            planes = args[:n_planes]
            starts, lens, csum, winv, iflag = args[n_planes:n_planes + 5]
            envs = args[n_planes + 5]
            gate = args[n_planes + 6] if gated else None
            total = csum[-1]
            _, _, hit = _expand_refine(
                planes, starts, lens, csum,
                winv.astype(jnp.int32), iflag, envs, total,
                gate, C, n_planes,
            )
            return jnp.sum(hit, dtype=jnp.int32)[None]

        fn = jax.jit(_mesh_count)
        _MESH_JITS[key] = fn
    return fn


def mesh_join_kernel(mesh, axis: str, C: int, cap: int, n_planes: int,
                     gated: bool, dtype):
    """Co-partitioned mesh refinement: ONE SPMD launch where every shard
    expands and refines ITS OWN run batch against ITS OWN resident rows
    and compacts local pairs into a fixed (cap) buffer — row ids are
    globalized in-kernel from the shard index. There is NO cross-shard
    collective anywhere in the body: co-partitioned planning (runs
    clipped at shard row boundaries) already guaranteed every candidate
    is shard-local, so the launch is pure local compute + one gather of
    the fixed-shape output buffers (zero row exchange)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.dist import shard_map

    key = ("mesh", mesh_key(mesh), axis, C, cap, n_planes, gated,
           np.dtype(dtype).str)
    fn = _MESH_JITS.get(key)
    if fn is None:
        spec = P(axis)

        from functools import partial

        @partial(
            shard_map, mesh=mesh,
            in_specs=(spec,) * n_planes + (spec,) * 5 + (P(),)
            + ((spec,) if gated else ()),
            out_specs=(spec, spec, spec), check_vma=False,
        )
        def _mesh_body(*args):
            planes = args[:n_planes]
            starts, lens, csum, winv, iflag = args[n_planes:n_planes + 5]
            envs = args[n_planes + 5]
            gate = args[n_planes + 6] if gated else None
            total = csum[-1]
            row, win, hit = _expand_refine(
                planes, starts, lens, csum,
                winv.astype(jnp.int32), iflag, envs, total,
                gate, C, n_planes,
            )
            shard = jax.lax.axis_index(axis).astype(jnp.int32)
            grow = row + shard * planes[0].shape[0]
            pos = jnp.cumsum(hit.astype(jnp.int32)) - 1
            idx = jnp.where(hit & (pos < cap), pos, cap)
            rbuf = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(grow)
            wbuf = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(win)
            return rbuf[:cap], wbuf[:cap], jnp.sum(hit, dtype=jnp.int32)[None]

        fn = jax.jit(_mesh_body)
        _MESH_JITS[key] = fn
    return fn
