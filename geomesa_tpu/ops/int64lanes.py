"""Two-word (hi int32, lo uint32) device representation of int64 columns.

TPU vector lanes are 32-bit; XLA emulates s64 lanes as carried pairs, which
roughly halves scan bandwidth and blocks Pallas (no 64-bit VMEM tiles). So
Date/Long columns are staged on device as two planes -- ``attr__hi``
(int32, arithmetic high word) and ``attr__lo`` (uint32, low word) -- and
compares are rewritten as lexicographic two-word compares. The mapping
``v -> (v >> 32, v & 0xffffffff)`` is order-isomorphic to int64 under
(signed hi, unsigned lo) lexicographic order, so every comparison operator
carries over exactly (incl. negative pre-1970 epoch-ms values).

Ref analog: the reference scans epoch-ms longs natively on the JVM
(geomesa-accumulo iterators compare 8-byte values [UNVERIFIED - empty
reference mount]); this module is the TPU-native storage decision replacing
that.
"""

from __future__ import annotations

import numpy as np

HI_SUFFIX = "__hi"
LO_SUFFIX = "__lo"


def split_value(v: int) -> tuple[int, int]:
    """Python int64 -> (signed hi word, unsigned lo word)."""
    v = int(v)
    return v >> 32, v & 0xFFFFFFFF


def split_array_np(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 array -> (int32 hi, uint32 lo) planes."""
    a = np.asarray(arr, dtype=np.int64)
    hi = (a >> np.int64(32)).astype(np.int32)
    lo = (a & np.int64(0xFFFFFFFF)).astype(np.uint64).astype(np.uint32)
    return hi, lo


def join_np(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Inverse of split_array_np (host-side, for round-trip tests)."""
    return (np.asarray(hi, np.int64) << np.int64(32)) | np.asarray(
        lo, np.uint32
    ).astype(np.int64)


def _consts(v: int):
    import jax.numpy as jnp

    vhi, vlo = split_value(v)
    return jnp.int32(vhi), jnp.uint32(vlo)


def cmp_lanes_jax(op: str, hi, lo, vhi, vlo):
    """Elementwise ``(hi, lo) <op> (vhi, vlo)`` where both sides encode
    int64 as (signed hi, unsigned lo) lane pairs — THE order-isomorphism
    compare; the bound side may be scalars OR arrays (broadcastable).
    This is the single source of the signed-hi/unsigned-lo convention."""
    import jax.numpy as jnp

    hi = hi.astype(jnp.int32)
    lo = lo.astype(jnp.uint32)
    if op == "=":
        return (hi == vhi) & (lo == vlo)
    if op == "<>":
        return (hi != vhi) | (lo != vlo)
    if op == "<":
        return (hi < vhi) | ((hi == vhi) & (lo < vlo))
    if op == "<=":
        return (hi < vhi) | ((hi == vhi) & (lo <= vlo))
    if op == ">":
        return (hi > vhi) | ((hi == vhi) & (lo > vlo))
    if op == ">=":
        return (hi > vhi) | ((hi == vhi) & (lo >= vlo))
    raise ValueError(op)


def cmp_jax(op: str, hi, lo, v: int):
    """Elementwise ``(hi, lo) <op> v`` where (hi, lo) encode int64 lanes.

    op in {'=', '<>', '<', '<=', '>', '>='}. Pure jnp; traces inside both
    XLA jit and Pallas kernels.
    """
    import jax.numpy as jnp

    vhi, vlo = _consts(v)
    return cmp_lanes_jax(op, hi, lo, vhi, vlo)
