"""Pallas density rasterization: pixel binning as MXU work.

Ref role: DensityIterator, the reference's flagship pushdown aggregation
(SURVEY section 2.3 [UNVERIFIED - empty reference mount]). The XLA lowering
of ``grid.at[pid].add(w)`` serializes the scatter (measured 0.14B rows/s,
0.3% of HBM peak, BENCH_r03); a TPU has no fast scatter — but it has a
systolic array.

The TPU-native formulation: a weighted 2-D histogram is a pair of one-hot
contractions,

    grid[h, w] = sum_r  weight_r * onehot(py_r)[h] * onehot(px_r)[w]
               = OH_y(w) @ OH_x^T

so each row tile builds two narrow one-hot matrices IN VMEM (doing this in
plain XLA materializes them in HBM — ~1KB/row of traffic, measured only
1.5x the scatter) and feeds one MXU contraction into a VMEM-resident f32
grid accumulated across the sequential TPU grid.

Layout note: the one-hots are built LANES-MAJOR — (cells, rows), rows on
the lane axis — because Mosaic cannot reshape a (sublanes, lanes) tile
into a flat row vector, and the contraction is order-invariant so no
row-flattening is ever needed: the pixel ids arrive as (1, R) lane
vectors and broadcast against a sublane iota. The pixel math itself
(viewport scaling, clipping, inside test, hit-mask fold) runs in plain
XLA *outside* the kernel at full lane efficiency, encoding masked-out
rows as pixel id -1 (matches no one-hot lane). The viewport is therefore
a runtime value: one compiled kernel serves every bbox.

Precision: unweighted counts use {0,1} one-hots in INT8 with int32
accumulation — exact, and the int8 MXU path is 2x the bf16 rate
(measured 1.51B rows/s vs 1.12B bf16 vs 0.14B scatter at 2^26 on v5e).
Weighted grids contract in float32 with HIGHEST matmul precision (TPU
default rounds f32 operands through bfloat16).
"""

from __future__ import annotations

import numpy as np


def build_density_pallas(
    width: int,
    height: int,
    weighted: bool = False,
    *,
    rows_per_step: "int | None" = None,
    interpret: "bool | None" = None,
):
    """(height, width) f32 grid builder: ``fn(env, x, y, m, w=None)``.

    ``env`` is a float32 (4,) [xmin, ymin, xmax, ymax] runtime viewport;
    ``x``/``y`` are float32 planes, ``m`` a bool/int8 hit-mask plane
    (rows with 0 contribute nothing), ``w`` a float32 weight plane when
    ``weighted``. Pixel mapping matches process/density._pixel_ids
    exactly (clip + inside test). Jittable; the fused-agg hook calls it
    inside one dispatch with the filter mask.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    LANES = 128
    # weighted: float32 one-hots; (cells, R) f32 temporaries cap R at
    # 2048 inside the ~16MB VMEM budget. Unweighted int8 fits 4x that.
    R = rows_per_step or (2048 if weighted else 8192)
    assert R % LANES == 0
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    # sublane-pad the one-hot cell axes (int8 tiles are (32, 128))
    HP = max(32, -(-height // 32) * 32)
    WP = max(32, -(-width // 32) * 32)
    oh_dtype = jnp.float32 if weighted else jnp.int8
    acc_dtype = jnp.float32 if weighted else jnp.int32
    prec = (
        jax.lax.Precision.HIGHEST if weighted else jax.lax.Precision.DEFAULT
    )

    _zero = lambda: jnp.int32(0)  # noqa: E731 (int32 index-map literal)

    def kernel(py_ref, px_ref, *rest):
        w_ref = rest[0] if weighted else None
        out_ref = rest[-1]

        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[...] = jnp.zeros((HP, WP), acc_dtype)

        py = py_ref[...]  # (1, R) int32; -1 encodes "contributes nothing"
        px = px_ref[...]
        ioh = jax.lax.broadcasted_iota(jnp.int32, (HP, R), 0)
        iow = jax.lax.broadcasted_iota(jnp.int32, (WP, R), 0)
        if weighted:
            ohy = jnp.where(ioh == py, w_ref[...], jnp.float32(0.0))
        else:
            ohy = (ioh == py).astype(oh_dtype)
        ohx = (iow == px).astype(oh_dtype)
        out_ref[...] = out_ref[...] + jax.lax.dot_general(
            ohy, ohx,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=prec,
        )

    def fn(env, x, y, m, w=None):
        from geomesa_tpu.process.density import _pixel_ids

        n = int(x.shape[0])
        grid = max(1, -(-n // R))
        pad = grid * R - n
        # XLA pre-pass at full lane efficiency: viewport scale + clip +
        # inside test + hit-mask fold, masked rows -> pixel id -1
        px, py, inside = _pixel_ids(x, y, env, width, height, jnp)
        keep = inside & (m if m.dtype == jnp.bool_ else (m > 0))
        px = jnp.where(keep, px, jnp.int32(-1))
        ins = [
            jnp.pad(py, (0, pad), constant_values=-1).reshape(grid, 1, R),
            jnp.pad(px, (0, pad), constant_values=-1).reshape(grid, 1, R),
        ]
        if weighted:
            ins.append(
                jnp.pad(w.astype(jnp.float32), (0, pad)).reshape(grid, 1, R)
            )
        out = pl.pallas_call(
            kernel,
            grid=(grid,),
            # int32 index-map literals: a raw Python 0 traces to an i64
            # constant under x64, which Mosaic cannot legalize
            in_specs=[
                pl.BlockSpec(
                    (None, 1, R), lambda i: (i, _zero(), _zero())
                )
            ] * len(ins),
            out_specs=pl.BlockSpec((HP, WP), lambda i: (_zero(), _zero())),
            out_shape=jax.ShapeDtypeStruct((HP, WP), acc_dtype),
            interpret=interpret,
        )(*ins)
        return out[:height, :width].astype(jnp.float32)

    return fn


def density_oracle(x, y, m, w, env, width, height):
    """Host reference for the kernel: the same pixel mapping as
    process/density._pixel_ids computed in FLOAT32 — the device path
    receives the viewport as a float32 runtime array, so the scale
    factors must quantize identically or borderline pixels disagree."""
    env32 = np.asarray(env, np.float32)
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.float32)
    sx = np.float32(width) / (env32[2] - env32[0])
    sy = np.float32(height) / (env32[3] - env32[1])
    px = np.clip(np.floor((x - env32[0]) * sx), 0, width - 1).astype(np.int32)
    py = np.clip(np.floor((y - env32[1]) * sy), 0, height - 1).astype(
        np.int32
    )
    inside = (
        (x >= env32[0]) & (x <= env32[2]) & (y >= env32[1]) & (y <= env32[3])
    )
    keep = inside & (np.asarray(m) > 0)
    grid = np.zeros(height * width, np.float64)
    ww = np.ones(len(x)) if w is None else np.asarray(w, np.float64)
    np.add.at(grid, (py * width + px)[keep], ww[keep])
    return grid.reshape(height, width).astype(np.float32)
