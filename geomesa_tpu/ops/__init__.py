"""Device-side scan/sort kernels (the server-side iterator analog).

The reference runs per-KV Scala iterators next to the data (ref:
geomesa-accumulo .../iterators/Z3Iterator.scala,
FilterTransformIterator.scala); here the same role is fused jax/Pallas
masks over resident columnar partitions (SURVEY.md sections 2.6, 7).
"""
